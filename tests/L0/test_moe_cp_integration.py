"""Generalized-mesh integration: ring attention (cp) + MoE (ep x tp).

Validates that the 'ep' and 'cp' axes coexist in one SPMD program — the
five-axis mesh (pp, dp, ep, cp, tp) parallel_state builds — with each
subsystem's collectives riding its own axis: ring attention ppermutes K/V
around 'cp', the SwitchMLP all_to_alls experts over 'ep' and psums the
expert ffn over 'tp'. No reference counterpart (the reference has neither
capability; SURVEY.md §2.3 note).
"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.testing import shard_map
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.context_parallel import ring_self_attention
from apex_tpu.transformer.moe import SwitchMLP

B, NH, SEQ, D = 2, 2, 16, 8
HID = NH * D
EP, CP, TP = 2, 2, 2
E = 4  # global experts


def _reference(q, k, v, layer, params):
    """Full attention per batch element, then SwitchMLP per (cp, ep)
    token shard with all experts local (each device routes only its own
    tokens, so the oracle processes shard-by-shard)."""
    scores = jnp.einsum("bsnd,btnd->bnst", q, k) / np.sqrt(D)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bnst,btnd->bsnd", probs, v)
    h = attn.transpose(1, 0, 2, 3).reshape(SEQ, B, HID)  # [s, b, hid]
    shards = []
    for j in range(CP):  # seq shards
        rows = []
        for i in range(EP):  # batch shards
            blk = h[j * (SEQ // CP):(j + 1) * (SEQ // CP), i:i + 1]
            rows.append(layer.apply({"params": params}, blk))
        shards.append(jnp.concatenate(rows, axis=1))
    return jnp.concatenate(shards, axis=0)  # [s, b, hid]


@pytest.mark.slow
def test_ring_attention_plus_moe_on_five_axis_mesh():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, expert_model_parallel_size_=EP,
        context_parallel_size_=CP, devices=jax.devices()[:8])
    assert tuple(mesh.axis_names) == ("pp", "dp", "ep", "cp", "tp")
    assert parallel_state.get_expert_model_parallel_world_size() == EP
    assert parallel_state.get_context_parallel_world_size() == CP

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, SEQ, NH, D), jnp.float32)
               for _ in range(3))

    layer = SwitchMLP(hidden_size=HID, ffn_hidden_size=2 * HID,
                      num_experts=E, capacity_factor=8.0,
                      compute_dtype=jnp.float32)
    h_probe = jnp.zeros((SEQ // CP, 1, HID), jnp.float32)

    # Params: build once with ep=tp=1 so the oracle owns all E experts and
    # the full ffn, then hand each (ep, tp) rank its slice via the specs.
    saved_ep = parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE
    saved_tp = parallel_state._TENSOR_MODEL_PARALLEL_WORLD_SIZE
    parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = 1
    parallel_state._TENSOR_MODEL_PARALLEL_WORLD_SIZE = 1
    params = layer.init(jax.random.PRNGKey(0), h_probe)["params"]
    ref = _reference(q, k, v, layer, params)
    parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = saved_ep
    parallel_state._TENSOR_MODEL_PARALLEL_WORLD_SIZE = saved_tp

    pspec = {"router": {"gate_weight": P()},
             "experts": {"w1": P("ep", None, "tp"), "b1": P("ep", "tp"),
                         "w2": P("ep", "tp", None), "b2": P("ep", None)}}

    @shard_map(mesh=mesh,
               in_specs=(pspec, P(None, "cp"), P(None, "cp"), P(None, "cp")),
               out_specs=P("cp", "ep", None))
    def run(p, qs, ks, vs):
        # ring attention over the cp axis (full heads per rank)
        attn = ring_self_attention(qs, ks, vs, causal=False)
        s_local = attn.shape[1]
        h = attn.transpose(1, 0, 2, 3).reshape(s_local, B, HID)
        # each ep rank keeps its batch shard for the MoE tokens
        i = jax.lax.axis_index("ep")
        h = jax.lax.dynamic_slice_in_dim(h, i * (B // EP), B // EP, axis=1)
        return layer.apply({"params": p}, h)

    out = run(params, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
