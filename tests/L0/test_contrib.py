"""Contrib tier tests: flash attention (Pallas interpret mode), xentropy,
clip_grad, focal loss, index_mul_2d.

Mirrors reference apex/contrib/test/ per-extension numerics tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import apex_tpu.contrib.fmha as fmha_mod
from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.fmha import _attention_reference, flash_attention
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss


class TestFlashAttention:
    @pytest.fixture(autouse=True)
    def _interpret_pallas(self, monkeypatch):
        """Run the Pallas kernel in interpreter mode on CPU so the TPU code
        path is exercised by the CPU test suite."""
        monkeypatch.setattr(fmha_mod, "_INTERPRET", True)
        monkeypatch.setattr(fmha_mod, "_use_pallas", lambda: True)

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, rng, causal):
        b, n, s, d = 1, 2, 128, 64
        q = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        out = flash_attention(q, k, v, causal, None, 64, 64)
        ref = _attention_reference(q, k, v, 1.0 / np.sqrt(d), causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_gradients_flow(self, rng):
        b, n, s, d = 1, 1, 128, 64
        q = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))

        def f(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, True, None, 64, 64))

        def f_ref(q_, k_, v_):
            return jnp.sum(_attention_reference(q_, k_, v_, 1.0 / np.sqrt(d),
                                                True))

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("window", [1, 37, 64, 100, 256])
    def test_sliding_window_matches_reference(self, rng, window):
        """Windowed flash (block-skip band) vs the reference band mask:
        windows below/at/above the block size and spanning several
        blocks, forward and all three gradients."""
        b, n, s, d = 1, 2, 256, 64
        q = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))

        out = flash_attention(q, k, v, True, None, 64, 64, window)
        ref = _attention_reference(q, k, v, 1.0 / np.sqrt(d), True,
                                   window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

        def f(q_, k_, v_):
            return jnp.sum(
                flash_attention(q_, k_, v_, True, None, 64, 64,
                                window) ** 2)

        def f_ref(q_, k_, v_):
            return jnp.sum(_attention_reference(
                q_, k_, v_, 1.0 / np.sqrt(d), True, window) ** 2)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-4)

    def test_alibi_bias_matches_reference(self, rng):
        """In-kernel alibi bias (key-position form) vs the reference
        band-free einsum path, forward and all three gradients; slopes
        cotangent is zero by construction."""
        b, n, s, d = 1, 3, 128, 64
        q = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        slopes = jnp.asarray([0.5, 0.25, 0.0625], jnp.float32)

        out = flash_attention(q, k, v, True, None, 64, 64, None, slopes)
        ref = _attention_reference(q, k, v, 1.0 / np.sqrt(d), True,
                                   None, slopes)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

        def f(q_, k_, v_):
            return jnp.sum(flash_attention(
                q_, k_, v_, True, None, 64, 64, None, slopes) ** 2)

        def f_ref(q_, k_, v_):
            return jnp.sum(_attention_reference(
                q_, k_, v_, 1.0 / np.sqrt(d), True, None, slopes) ** 2)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-4)

    def test_window_requires_causal(self):
        q = jnp.zeros((1, 1, 128, 64), jnp.float32)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, False, None, 64, 64, 37)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("bq,bk", [(64, 64), (64, 128), (128, 64)])
    def test_streamed_backward_multiblock(self, rng, causal, bq, bk):
        """The Pallas dq/dkv kernels stream multiple blocks here (s=256)
        including unequal block_q/block_k — covers accumulator
        init/finish and both causal clamp derivations."""
        b, n, s, d = 1, 2, 256, 64
        q = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, n, s, d).astype(np.float32))

        def f(q_, k_, v_):
            return jnp.sum(
                flash_attention(q_, k_, v_, causal, None, bq, bk) ** 2)

        def f_ref(q_, k_, v_):
            return jnp.sum(_attention_reference(
                q_, k_, v_, 1.0 / np.sqrt(d), causal) ** 2)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-4)


class TestXentropy:
    def test_matches_torch(self, rng):
        logits = rng.randn(6, 11).astype(np.float32)
        labels = rng.randint(1, 11, size=(6,))
        ours = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), padding_idx=None)
        theirs = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels), reduction="none")
        np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_label_smoothing_matches_torch(self, rng):
        logits = rng.randn(6, 11).astype(np.float32)
        labels = rng.randint(1, 11, size=(6,))
        ours = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), smoothing=0.1,
            padding_idx=None)
        theirs = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels), reduction="none",
            label_smoothing=0.1)
        np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_padding_idx_zeroes_loss(self, rng):
        logits = rng.randn(4, 7).astype(np.float32)
        labels = np.array([0, 1, 0, 2])
        ours = softmax_cross_entropy_loss(jnp.asarray(logits),
                                          jnp.asarray(labels), padding_idx=0)
        assert float(ours[0]) == 0.0 and float(ours[2]) == 0.0
        assert float(ours[1]) > 0.0

    def test_half_to_float_dtype(self, rng):
        logits = jnp.asarray(rng.randn(4, 7).astype(np.float32)).astype(jnp.bfloat16)
        labels = jnp.asarray(rng.randint(0, 7, size=(4,)))
        assert softmax_cross_entropy_loss(logits, labels,
                                          half_to_float=True).dtype == jnp.float32
        assert softmax_cross_entropy_loss(logits, labels,
                                          half_to_float=False).dtype == jnp.bfloat16


class TestClipGrad:
    def test_matches_torch_clip(self, rng):
        grads = {"a": jnp.asarray(rng.randn(5, 3).astype(np.float32)),
                 "b": jnp.asarray(rng.randn(7).astype(np.float32))}
        clipped, norm = clip_grad_norm_(grads, max_norm=1.0)
        tgrads = [torch.tensor(np.asarray(grads["a"]), requires_grad=True),
                  torch.tensor(np.asarray(grads["b"]), requires_grad=True)]
        for t in tgrads:
            t.grad = t.detach().clone()
        tnorm = torch.nn.utils.clip_grad_norm_(tgrads, 1.0)
        np.testing.assert_allclose(float(norm), float(tnorm), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(clipped["a"]),
                                   tgrads[0].grad.numpy(), rtol=1e-4,
                                   atol=1e-6)

    def test_no_clip_below_max(self, rng):
        grads = {"a": jnp.asarray((rng.randn(4) * 0.01).astype(np.float32))}
        clipped, _ = clip_grad_norm_(grads, max_norm=100.0)
        np.testing.assert_allclose(np.asarray(clipped["a"]),
                                   np.asarray(grads["a"]), rtol=1e-6)


class TestFocalLoss:
    def test_reduces_easy_example_weight(self, rng):
        logits = jnp.asarray([[5.0, -5.0], [0.1, -0.1]])
        targets = jnp.asarray([0, 0])
        fl = focal_loss(logits, targets, jnp.asarray(2.0), 2, gamma=2.0)
        # focal loss is finite and positive
        assert np.isfinite(float(fl)) and float(fl) > 0

    def test_ignore_labels(self):
        logits = jnp.zeros((2, 3))
        targets = jnp.asarray([-2, -2])  # ignored
        fl = focal_loss(logits, targets, jnp.asarray(1.0), 3)
        assert float(fl) == 0.0


class TestIndexMul2d:
    def test_matches_reference(self, rng):
        in1 = jnp.asarray(rng.randn(10, 4).astype(np.float32))
        in2 = jnp.asarray(rng.randn(6, 4).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, 10, size=(6,)))
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(in1)[np.asarray(idx)] * np.asarray(in2),
            rtol=1e-6)
