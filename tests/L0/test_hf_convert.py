"""External numerics oracle: apex_tpu GPTModel vs HuggingFace GPT-2.

A randomly-initialized ``transformers`` GPT2LMHeadModel (no download) is
converted with tools/convert_hf_gpt2; identical weights must produce
matching logits — validating embeddings, layernorm, the fused QKV column
permutation, causal softmax, gelu MLP, and the tied LM head against an
independent implementation end to end.
"""

import sys

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, ".")  # repo root for tools/


def _tiny_hf(seed=0):
    cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=48, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(seed)
    model = transformers.GPT2LMHeadModel(cfg)
    return model.eval(), cfg


def test_logits_match_hf_gpt2():
    from tools.convert_hf_gpt2 import convert_gpt2

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_hf()
    cfg, params = convert_gpt2(hf.state_dict(), hf_cfg)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, hf_cfg.vocab_size, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()

    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def test_greedy_generation_matches_hf():
    from tools.convert_hf_gpt2 import convert_gpt2

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_hf(seed=1)
    cfg, params = convert_gpt2(hf.state_dict(), hf_cfg)

    prompt = np.random.RandomState(1).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)
