"""External numerics oracle: apex_tpu GPTModel vs HuggingFace GPT-2.

A randomly-initialized ``transformers`` GPT2LMHeadModel (no download) is
converted with tools/convert_hf_gpt2; identical weights must produce
matching logits — validating embeddings, layernorm, the fused QKV column
permutation, causal softmax, gelu MLP, and the tied LM head against an
independent implementation end to end.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, ".")  # repo root for tools/


def _tiny_hf(seed=0):
    cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=48, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(seed)
    model = transformers.GPT2LMHeadModel(cfg)
    return model.eval(), cfg


def test_logits_match_hf_gpt2():
    from tools.convert_hf_gpt2 import convert_gpt2

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_hf()
    cfg, params = convert_gpt2(hf.state_dict(), hf_cfg)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, hf_cfg.vocab_size, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()

    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def _tiny_llama(seed=0, kv_heads=2):
    cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kv_heads, max_position_embeddings=32,
        attention_dropout=0.0)
    torch.manual_seed(seed)
    return transformers.LlamaForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("kv_heads", [4, 2])
def test_logits_match_hf_llama(kv_heads):
    """Oracle for the modern stack: RMSNorm + RoPE + SwiGLU + (GQA when
    kv_heads < heads) against HF's independent implementation."""
    from tools.convert_hf_llama import convert_llama

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_llama(kv_heads=kv_heads)
    cfg, params = convert_llama(hf.state_dict(), hf_cfg)
    assert cfg.normalization == "rmsnorm"

    tokens = np.random.RandomState(0).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def test_llama_greedy_generation_matches_hf():
    from tools.convert_hf_llama import convert_llama

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_llama(seed=2)
    cfg, params = convert_llama(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(2).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_logits_match_hf_qwen2():
    """Qwen2 = llama shape + QKV biases: oracles the fused bias layout."""
    from tools.convert_hf_qwen2 import convert_qwen2

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.Qwen2Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, sliding_window=None, use_sliding_window=False)
    torch.manual_seed(4)
    hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    # HF zero-inits biases; randomize so the bias mapping is exercised
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if "self_attn" in name and name.endswith("bias"):
                p.copy_(torch.randn_like(p) * 0.5)
    cfg, params = convert_qwen2(hf.state_dict(), hf_cfg)
    # qkv biases must be nonzero after conversion (llama zeros them)
    b0 = params["transformer"]["layer_0"]["self_attention"][
        "query_key_value"]["bias"]
    assert float(jnp.abs(b0).sum()) > 0

    tokens = np.random.RandomState(4).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_logits_match_hf_mixtral():
    """Oracle for the MoE stack: top-2 routing + SwiGLU experts + GQA
    attention vs HF Mixtral (dropless via capacity == all tokens)."""
    from tools.convert_hf_mixtral import convert_mixtral

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=32, sliding_window=None,
        attention_dropout=0.0)
    torch.manual_seed(3)
    hf = transformers.MixtralForCausalLM(hf_cfg).eval()
    cfg, params = convert_mixtral(hf.state_dict(), hf_cfg)
    assert cfg.num_moe_experts == 4 and cfg.moe_top_k == 2

    tokens = np.random.RandomState(3).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours, _ = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens),
                                  mutable=["moe_losses"])
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def _tiny_qwen2moe(norm_topk=False, seed=5):
    cfg = transformers.Qwen2MoeConfig(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        moe_intermediate_size=24, shared_expert_intermediate_size=40,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=8, num_experts_per_tok=2, norm_topk_prob=norm_topk,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=32, attention_dropout=0.0,
        use_sliding_window=False)
    torch.manual_seed(seed)
    return transformers.Qwen2MoeForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("norm_topk", [False, True])
def test_logits_match_hf_qwen2moe(norm_topk):
    """Oracle for the shared-expert MoE block (Qwen1.5-MoE lineage):
    fine-grained routed experts + always-on shared expert scaled by a
    sigmoid scalar gate + QKV-biased GQA attention. norm_topk_prob
    toggles Mixtral-style gate renormalization vs raw softmax mass —
    both appear in published configs."""
    from tools.convert_hf_qwen2moe import convert_qwen2moe

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_qwen2moe(norm_topk)
    cfg, params = convert_qwen2moe(hf.state_dict(), hf_cfg)
    assert cfg.moe_normalize_topk == norm_topk
    assert cfg.moe_shared_expert_size == 40

    tokens = np.random.RandomState(5).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours, _ = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens),
                                  mutable=["moe_losses"])
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_qwen2moe_greedy_matches_hf():
    """Token-exact greedy through the cached decode path — end to end
    over the ragged dropless dispatch (capacity == all tokens)."""
    from tools.convert_hf_qwen2moe import convert_qwen2moe

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_qwen2moe(seed=6)
    cfg, params = convert_qwen2moe(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(6).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_qwen2moe_converter_refusals():
    """Per-layer dense/MoE interleavings this mapping cannot express are
    refused loudly."""
    from tools.convert_hf_qwen2moe import convert_qwen2moe

    base = dict(vocab_size=32, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2,
                num_experts=4, num_experts_per_tok=2,
                moe_intermediate_size=16,
                shared_expert_intermediate_size=16)
    with pytest.raises(ValueError, match="decoder_sparse_step"):
        convert_qwen2moe({}, transformers.Qwen2MoeConfig(
            **base, decoder_sparse_step=2))
    with pytest.raises(ValueError, match="mlp_only_layers"):
        convert_qwen2moe({}, transformers.Qwen2MoeConfig(
            **base, mlp_only_layers=[0]))


def test_greedy_generation_matches_hf():
    from tools.convert_hf_gpt2 import convert_gpt2

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_hf(seed=1)
    cfg, params = convert_gpt2(hf.state_dict(), hf_cfg)

    prompt = np.random.RandomState(1).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_gemma(seed=5, kv_heads=1):
    cfg = transformers.GemmaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kv_heads, head_dim=12,
        max_position_embeddings=32, attention_dropout=0.0)
    torch.manual_seed(seed)
    return transformers.GemmaForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("kv_heads", [1, 4])
def test_logits_match_hf_gemma(kv_heads):
    """Gemma oracle: GeGLU gate, sqrt(hidden) embedding scale, (1+w)
    rmsnorm folding, always-tied head, MQA when kv_heads=1 — against
    HF's independent implementation."""
    from tools.convert_hf_gemma import convert_gemma

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gemma(kv_heads=kv_heads)
    cfg, params = convert_gemma(hf.state_dict(), hf_cfg)
    assert cfg.activation == "geglu" and cfg.tie_word_embeddings
    assert "lm_head" not in params

    tokens = np.random.RandomState(5).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("kv_heads", [2, 4])
def test_logits_match_hf_gemma_decoupled_head_dim(kv_heads):
    """gemma-7b shape: head_dim (16) != hidden/heads (12) — oracles the
    cfg.head_dim decoupling through q/k/v (both the GQA and the MHA
    fused layouts), the output projection, and rope."""
    from tools.convert_hf_gemma import convert_gemma

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.GemmaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kv_heads, head_dim=16,
        max_position_embeddings=32, attention_dropout=0.0)
    torch.manual_seed(6)
    hf = transformers.GemmaForCausalLM(hf_cfg).eval()
    cfg, params = convert_gemma(hf.state_dict(), hf_cfg)
    assert cfg.head_dim == 16 and cfg.kv_channels == 16

    tokens = np.random.RandomState(6).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("parallel_res,rotary_pct", [(True, 0.25),
                                                     (False, 1.0)])
def test_logits_match_hf_neox(parallel_res, rotary_pct):
    """GPT-NeoX/Pythia oracle: parallel residual + partial rotary + gelu
    biases + untied embed_out against HF's independent implementation."""
    from tools.convert_hf_neox import convert_neox

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=32, rotary_pct=rotary_pct,
        use_parallel_residual=parallel_res, attention_dropout=0.0,
        hidden_dropout=0.0)
    torch.manual_seed(7)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    cfg, params = convert_neox(hf.state_dict(), hf_cfg)
    assert cfg.parallel_residual == parallel_res
    assert cfg.rotary_percent == rotary_pct

    tokens = np.random.RandomState(7).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_neox_greedy_generation_matches_hf():
    from tools.convert_hf_neox import convert_neox

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True, attention_dropout=0.0,
        hidden_dropout=0.0)
    torch.manual_seed(8)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    cfg, params = convert_neox(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(8).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_mistral(seed=10, window=8):
    cfg = transformers.MistralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        sliding_window=window, attention_dropout=0.0)
    torch.manual_seed(seed)
    return transformers.MistralForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_mistral_sliding_window():
    """Sliding-window oracle: seq (24) well beyond the window (8), where
    full causal attention would diverge from HF — pins the band mask."""
    from tools.convert_hf_mistral import convert_mistral

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_mistral()
    cfg, params = convert_mistral(hf.state_dict(), hf_cfg)
    assert cfg.sliding_window == 8

    tokens = np.random.RandomState(10).randint(0, 96, size=(2, 24))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_mistral_sliding_window_greedy_decode_matches_hf():
    """KV-cache decode with stale-but-resident cache entries masked out
    beyond the window: generate far past sliding_window, token-exact."""
    from tools.convert_hf_mistral import convert_mistral

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_mistral(seed=11)
    cfg, params = convert_mistral(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(11).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=16,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=16)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_phi(seed=15):
    cfg = transformers.PhiConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        partial_rotary_factor=0.5, attention_dropout=0.0,
        resid_pdrop=0.0, embd_pdrop=0.0)
    torch.manual_seed(seed)
    return transformers.PhiForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_phi():
    """Phi oracle: shared-LN parallel residual + partial rotary + biased
    head against HF's independent implementation."""
    from tools.convert_hf_phi import convert_phi

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_phi()
    cfg, params = convert_phi(hf.state_dict(), hf_cfg)
    assert cfg.parallel_residual_shared_ln and cfg.lm_head_bias
    # HF zero-inits the head bias; randomize so the mapping is exercised
    params["lm_head_bias"] = jnp.asarray(
        np.random.RandomState(1).randn(96).astype(np.float32) * 0.3)
    with torch.no_grad():
        hf.lm_head.bias.copy_(torch.asarray(
            np.asarray(params["lm_head_bias"])))

    tokens = np.random.RandomState(15).randint(0, 96, size=(2, 24))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_phi_greedy_generation_matches_hf():
    from tools.convert_hf_phi import convert_phi

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_phi(seed=16)
    cfg, params = convert_phi(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(16).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


@pytest.mark.parametrize("variant", ["7b_mqa", "rw_mha", "rw_mha_bias",
                                     "new_arch"])
def test_logits_match_hf_falcon(variant):
    """Falcon oracle across all three attention layouts and residual
    forms: 7b (MQA + shared-LN parallel residual), rw (MHA, sequential
    residual), 40b-style (grouped new_decoder_architecture, two LNs)."""
    from tools.convert_hf_falcon import convert_falcon

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    kw = dict(vocab_size=96, hidden_size=48, num_hidden_layers=2,
              num_attention_heads=4, max_position_embeddings=64,
              alibi=False, attention_dropout=0.0, hidden_dropout=0.0,
              bias=False)
    if variant == "7b_mqa":
        kw.update(multi_query=True, parallel_attn=True,
                  new_decoder_architecture=False)
    elif variant.startswith("rw_mha"):
        kw.update(multi_query=False, parallel_attn=False,
                  new_decoder_architecture=False,
                  bias=variant.endswith("bias"))
    else:
        kw.update(new_decoder_architecture=True, num_kv_heads=2)
    hf_cfg = transformers.FalconConfig(**kw)
    torch.manual_seed(17)
    hf = transformers.FalconForCausalLM(hf_cfg).eval()
    if variant == "rw_mha_bias":
        # HF zero-inits projection biases; randomize so the mapping
        # (incl. the qkv bias regroup) is actually exercised
        with torch.no_grad():
            for name, prm in hf.named_parameters():
                if name.endswith(".bias") and "layernorm" not in name                         and "ln_" not in name:
                    prm.copy_(torch.randn_like(prm) * 0.3)
    cfg, params = convert_falcon(hf.state_dict(), hf_cfg)
    if variant == "rw_mha_bias":
        b0 = params["transformer"]["layer_0"]["self_attention"][
            "query_key_value"]["bias"]
        assert float(jnp.abs(b0).sum()) > 0

    tokens = np.random.RandomState(17).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_falcon_refuses_alibi():
    from tools.convert_hf_falcon import convert_falcon

    hf_cfg = transformers.FalconConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=1,
        num_attention_heads=4, alibi=True)
    with pytest.raises(ValueError, match="alibi"):
        convert_falcon({}, hf_cfg)


def _tiny_opt(seed=19):
    cfg = transformers.OPTConfig(
        vocab_size=96, hidden_size=48, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=32,
        word_embed_proj_dim=48, do_layer_norm_before=True,
        activation_function="relu", dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(seed)
    return transformers.OPTForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_opt():
    """OPT oracle: relu MLP, learned positions with the +2 offset folded,
    per-layer LN naming, tied head."""
    from tools.convert_hf_opt import convert_opt

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_opt()
    cfg, params = convert_opt(hf.state_dict(), hf_cfg)
    assert cfg.activation == "relu" and cfg.tie_word_embeddings

    tokens = np.random.RandomState(19).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_opt_greedy_generation_matches_hf():
    """Learned-position decode: generate() must feed explicit positions
    so the +2-offset fold stays consistent past the prefill."""
    from tools.convert_hf_opt import convert_opt

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_opt(seed=20)
    cfg, params = convert_opt(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(20).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_opt_refuses_post_ln():
    from tools.convert_hf_opt import convert_opt

    hf_cfg = transformers.OPTConfig(
        vocab_size=96, hidden_size=48, ffn_dim=128, num_hidden_layers=1,
        num_attention_heads=4, do_layer_norm_before=False,
        word_embed_proj_dim=48)
    with pytest.raises(ValueError, match="do_layer_norm_before"):
        convert_opt({}, hf_cfg)


def _tiny_gptj(seed=23):
    cfg = transformers.GPTJConfig(
        vocab_size=96, n_embd=48, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=8, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(seed)
    return transformers.GPTJForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_gptj():
    """GPT-J oracle: interleaved partial rotary (rotate_every_two over
    rotary_dim of head_dim), shared-LN parallel residual, biased MLP and
    LM head."""
    from tools.convert_hf_gptj import convert_gptj

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gptj()
    cfg, params = convert_gptj(hf.state_dict(), hf_cfg)
    assert cfg.rotary_interleaved and cfg.rotary_percent < 1.0
    # HF zero-inits the head bias; randomize so the mapping is exercised
    params["lm_head_bias"] = jnp.asarray(
        np.random.RandomState(2).randn(96).astype(np.float32) * 0.3)
    with torch.no_grad():
        hf.lm_head.bias.copy_(torch.asarray(
            np.asarray(params["lm_head_bias"])))

    tokens = np.random.RandomState(23).randint(0, 96, size=(2, 24))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_gptj_greedy_generation_matches_hf():
    from tools.convert_hf_gptj import convert_gptj

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gptj(seed=24)
    cfg, params = convert_gptj(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(24).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_bloom(seed=27, n_head=4):
    cfg = transformers.BloomConfig(
        vocab_size=96, hidden_size=48, n_layer=2, n_head=n_head,
        attention_dropout=0.0, hidden_dropout=0.0)
    torch.manual_seed(seed)
    return transformers.BloomForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("n_head", [4, 6])
def test_logits_match_hf_bloom(n_head):
    """BLOOM oracle: alibi position bias (incl. the non-power-of-two
    slope interpolation at 6 heads), embedding layernorm, per-head fused
    qkv, tied head."""
    from tools.convert_hf_bloom import convert_bloom

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_bloom(n_head=n_head)
    cfg, params = convert_bloom(hf.state_dict(), hf_cfg)
    assert cfg.position_embedding_type == "alibi"
    assert "embedding_layernorm" in params

    tokens = np.random.RandomState(27).randint(0, 96, size=(2, 24))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4, atol=3e-4)


def test_bloom_greedy_generation_matches_hf():
    """KV-cache decode under alibi: the key-position bias must track
    absolute cache positions past the prefill."""
    from tools.convert_hf_bloom import convert_bloom

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_bloom(seed=28)
    cfg, params = convert_bloom(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(28).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=10,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=10)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_bigcode(seed=0):
    cfg = transformers.GPTBigCodeConfig(
        vocab_size=96, n_embd=48, n_layer=2, n_head=4, n_positions=32,
        multi_query=True, resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0)
    torch.manual_seed(seed)
    return transformers.GPTBigCodeForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_gptbigcode():
    """StarCoder family: multi-query attention = num_query_groups=1;
    HF c_attn's [q_all | k | v] rows transpose straight into our fused
    GQA column layout."""
    from tools.convert_hf_gptbigcode import convert_gptbigcode

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_bigcode()
    cfg, params = convert_gptbigcode(hf.state_dict(), hf_cfg)
    assert cfg.query_groups == 1

    tokens = np.random.RandomState(0).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4,
                               atol=2e-4)


def test_gptbigcode_greedy_matches_hf():
    from tools.convert_hf_gptbigcode import convert_gptbigcode

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_bigcode(seed=3)
    cfg, params = convert_gptbigcode(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(3).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_stablelm(seed=0, qkv_bias=False, kv_heads=4):
    cfg = transformers.StableLmConfig(
        vocab_size=96, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=kv_heads,
        intermediate_size=128, partial_rotary_factor=0.25,
        max_position_embeddings=32, use_qkv_bias=qkv_bias,
        attention_dropout=0.0, hidden_dropout=0.0)
    torch.manual_seed(seed)
    return transformers.StableLmForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("qkv_bias,kv_heads", [(False, 4), (True, 2)])
def test_logits_match_hf_stablelm(qkv_bias, kv_heads):
    """StableLM: LayerNorm blocks + SwiGLU + partial rotary (0.25) —
    the knob combination no other family pairs."""
    from tools.convert_hf_stablelm import convert_stablelm

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_stablelm(qkv_bias=qkv_bias, kv_heads=kv_heads)
    cfg, params = convert_stablelm(hf.state_dict(), hf_cfg)
    assert cfg.normalization == "layernorm" and cfg.activation == "swiglu"
    assert cfg.rotary_percent == 0.25

    tokens = np.random.RandomState(1).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4,
                               atol=2e-4)


def _tiny_mpt(seed=0, n_heads=4):
    cfg = transformers.MptConfig(
        vocab_size=96, d_model=48, n_heads=n_heads, n_layers=2,
        max_seq_len=32, resid_pdrop=0.0, emb_pdrop=0.0)
    torch.manual_seed(seed)
    return transformers.MptForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_mpt():
    """MPT: the bias-free ALiBi family — no position embeddings, zero
    biases everywhere, exact gelu, tied head."""
    from tools.convert_hf_mpt import convert_mpt

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_mpt()
    cfg, params = convert_mpt(hf.state_dict(), hf_cfg)
    assert cfg.position_embedding_type == "alibi"

    tokens = np.random.RandomState(0).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4,
                               atol=2e-4)


def test_mpt_greedy_matches_hf():
    from tools.convert_hf_mpt import convert_mpt

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_mpt(seed=4)
    cfg, params = convert_mpt(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(4).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_gemma2(seed=11, n_layers=4):
    cfg = transformers.Gemma2Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2, head_dim=12,
        max_position_embeddings=32, attention_dropout=0.0,
        # window < seq so the local/global alternation actually bites,
        # and a query_pre_attn_scalar != head_dim so the decoupled
        # softmax scale is exercised (27b shape: 144 vs 128)
        sliding_window=8, query_pre_attn_scalar=20.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        attn_implementation="eager")  # eager = the softcap reference
    torch.manual_seed(seed)
    return transformers.Gemma2ForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_gemma2():
    """Gemma-2 oracle: attention + final-logit tanh softcaps, sandwich
    norms (4 RMSNorms/layer), alternating local/global attention
    (sliding_window_pattern=2 with window < seq), decoupled softmax
    scale — against HF's eager implementation."""
    from tools.convert_hf_gemma2 import convert_gemma2

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gemma2()
    cfg, params = convert_gemma2(hf.state_dict(), hf_cfg)
    assert cfg.sandwich_norm and cfg.sliding_window_pattern == 2
    assert cfg.attn_logit_softcapping == 50.0
    assert "post_mlp_norm" in params["transformer"]["layer_0"]

    tokens = np.random.RandomState(11).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)


def test_gemma2_window_alternation_matters():
    """The even-local/odd-global split must actually change numerics:
    forcing every layer local (pattern=1) at window < seq must diverge
    from the converted model — guards against the per-layer window
    silently collapsing to one global setting."""
    import dataclasses

    from tools.convert_hf_gemma2 import convert_gemma2

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gemma2()
    cfg, params = convert_gemma2(hf.state_dict(), hf_cfg)
    tokens = jnp.asarray(
        np.random.RandomState(12).randint(0, 96, size=(2, 16)))
    ours = GPTModel(cfg).apply({"params": params}, tokens)
    all_local = GPTModel(dataclasses.replace(
        cfg, sliding_window_pattern=1)).apply({"params": params}, tokens)
    assert not np.allclose(np.asarray(ours), np.asarray(all_local),
                           atol=1e-5)


def test_gemma2_greedy_generation_matches_hf():
    """Token-exact greedy decode through the KV cache: exercises the
    softcaps and the per-layer window in the decode attention path."""
    from tools.convert_hf_gemma2 import convert_gemma2

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gemma2(seed=13)
    cfg, params = convert_gemma2(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(13).randint(0, 96, size=(2, 12))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=10,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=10)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_gemma2_nonstandard_layer_types_refused():
    """A checkpoint whose layer_types is not the even-local/odd-global
    alternation must be refused, not silently misconverted."""
    from tools.convert_hf_gemma2 import convert_gemma2

    hf_cfg = transformers.Gemma2Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=12,
        layer_types=["full_attention", "full_attention"])
    with pytest.raises(ValueError, match="layer_types"):
        convert_gemma2({}, hf_cfg)


def test_logits_match_hf_llama31_rope_scaling():
    """Llama-3.1 "llama3" RoPE frequency rescaling oracle: a small
    original_max_position_embeddings (8) at seq 16 puts frequencies in
    all three bands (kept / interpolated / divided), so a mismatch in
    any branch of the rescaling breaks parity."""
    from tools.convert_hf_llama import convert_llama

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        attention_dropout=0.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 8})
    torch.manual_seed(21)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg, params = convert_llama(hf.state_dict(), hf_cfg)
    assert cfg.rope_scaling is not None
    assert cfg.rope_scaling.rope_type == "llama3"

    tokens = np.random.RandomState(21).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)
    # the rescaling must actually bite at these shapes (else this test
    # would vacuously pass with scaling ignored)
    import dataclasses

    unscaled = GPTModel(dataclasses.replace(cfg, rope_scaling=None)
                        ).apply({"params": params}, jnp.asarray(tokens))
    assert not np.allclose(np.asarray(ours), np.asarray(unscaled),
                           atol=1e-5)


def test_logits_match_hf_llama_linear_rope_scaling():
    """Legacy position-interpolation ("linear", factor 2) oracle."""
    from tools.convert_hf_llama import convert_llama

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        attention_dropout=0.0,
        rope_scaling={"rope_type": "linear", "factor": 2.0})
    torch.manual_seed(22)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg, params = convert_llama(hf.state_dict(), hf_cfg)
    assert cfg.rope_scaling is not None and cfg.rope_scaling.factor == 2.0

    tokens = np.random.RandomState(22).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)


def test_llama31_rope_scaled_greedy_matches_hf():
    """Greedy decode with llama3 rescaled frequencies through the KV
    cache (rope offsets from the cache index use the SCALED freqs)."""
    from tools.convert_hf_llama import convert_llama

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 8})
    torch.manual_seed(23)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg, params = convert_llama(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(23).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_unsupported_rope_scaling_refused():
    """yarn/dynamic/longrope must be refused, not silently ignored."""
    from tools.convert_hf_llama import _map_rope_scaling

    with pytest.raises(ValueError, match="rope_scaling"):
        _map_rope_scaling({"rope_type": "yarn", "factor": 4.0})
    assert _map_rope_scaling(None) is None
    assert _map_rope_scaling({"rope_type": "default"}) is None


def _tiny_olmoe(seed=31, norm_topk=False):
    cfg = transformers.OlmoeConfig(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, num_experts=8, num_experts_per_tok=2,
        norm_topk_prob=norm_topk, clip_qkv=None)
    torch.manual_seed(seed)
    hf = transformers.OlmoeForCausalLM(cfg).eval()
    # HF inits all RMSNorm weights to ones; randomize the q/k norms so
    # the weight MAPPING (not just the normalization math) is oracled
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if name.endswith(("q_norm.weight", "k_norm.weight")):
                p.copy_(1.0 + torch.randn_like(p) * 0.3)
    return hf, cfg


@pytest.mark.parametrize("norm_topk", [False, True])
def test_logits_match_hf_olmoe(norm_topk):
    """OLMoE oracle (22nd family): projection-wide q/k RMSNorm before
    rope + 8-expert top-2 routing with raw (norm_topk_prob=False) or
    renormalized gate mass, dropless capacity — against HF's
    independent implementation."""
    from tools.convert_hf_olmoe import convert_olmoe

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_olmoe(norm_topk=norm_topk)
    cfg, params = convert_olmoe(hf.state_dict(), hf_cfg)
    assert cfg.qk_norm == "projection"
    assert cfg.moe_normalize_topk == norm_topk
    assert "q_norm" in params["transformer"]["layer_0"]["self_attention"]

    tokens = np.random.RandomState(31).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_olmoe_greedy_generation_matches_hf():
    """Token-exact greedy decode: qk-norm + MoE routing through the
    KV-cache path."""
    from tools.convert_hf_olmoe import convert_olmoe

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_olmoe(seed=32)
    cfg, params = convert_olmoe(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(32).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_olmoe_clip_qkv_refused():
    from tools.convert_hf_olmoe import convert_olmoe

    hf_cfg = transformers.OlmoeConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2, clip_qkv=5.0)
    with pytest.raises(ValueError, match="clip_qkv"):
        convert_olmoe({}, hf_cfg)


def test_gemma2_knobs_refuse_unsupported_parallelism():
    """query_pre_attn_scalar + context parallelism and alternating
    windows under SPMD pipelining would be silently wrong — both must
    refuse loudly (review findings)."""
    from apex_tpu.models import TransformerConfig
    from apex_tpu.models.gpt_stage import GPTStage

    with pytest.raises(ValueError, match="query_pre_attn_scalar"):
        TransformerConfig(query_pre_attn_scalar=144.0,
                          context_parallel=True,
                          position_embedding_type="rope")
    cfg = TransformerConfig(num_layers=4, sliding_window=8,
                            sliding_window_pattern=2)
    with pytest.raises(ValueError, match="sliding_window_pattern"):
        GPTStage(cfg, layers_per_stage=2).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
            method=GPTStage.embed)


def _tiny_qwen3(seed=41, tie=True):
    cfg = transformers.Qwen3Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16,
        max_position_embeddings=32, attention_dropout=0.0,
        use_sliding_window=False, tie_word_embeddings=tie)
    torch.manual_seed(seed)
    hf = transformers.Qwen3ForCausalLM(cfg).eval()
    # exercise the per-head norm weight mapping, not just the math
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if name.endswith(("q_norm.weight", "k_norm.weight")):
                p.copy_(1.0 + torch.randn_like(p) * 0.3)
    return hf, cfg


@pytest.mark.parametrize("tie", [True, False])
def test_logits_match_hf_qwen3(tie):
    """Qwen3 oracle (23rd family): per-head q/k RMSNorm before rope
    ("unlike olmo, only on the head dim" — one [head_dim] weight shared
    across heads), decoupled head_dim, no attention biases, tied and
    untied heads."""
    from tools.convert_hf_qwen3 import convert_qwen3

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_qwen3(tie=tie)
    cfg, params = convert_qwen3(hf.state_dict(), hf_cfg)
    assert cfg.qk_norm == "head" and cfg.head_dim == 16

    tokens = np.random.RandomState(41).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)


def test_qwen3_greedy_generation_matches_hf():
    from tools.convert_hf_qwen3 import convert_qwen3

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_qwen3(seed=42)
    cfg, params = convert_qwen3(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(42).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_qwen3_sliding_window_refused():
    from tools.convert_hf_qwen3 import convert_qwen3

    hf_cfg = transformers.Qwen3Config(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        use_sliding_window=True, sliding_window=16, max_window_layers=1)
    with pytest.raises(ValueError, match="sliding_window"):
        convert_qwen3({}, hf_cfg)


def test_gpt_stage_applies_final_logit_softcapping():
    """A pipelined softcap model must produce the same capped logits as
    the single-stage head: the stage loss on uncapped vs capped logits
    differs measurably at cap=0.5 (review finding)."""
    import dataclasses

    from apex_tpu.models import TransformerConfig
    from apex_tpu.models.gpt_stage import GPTStage

    # embedding_multiplier inflates the logits so the cap visibly bites
    # (random-init logits are near zero, where tanh is ~identity)
    base = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=16,
        compute_dtype=jnp.float32, use_flash_attention=False,
        activation_checkpointing=False, embedding_multiplier=100.0)
    capped = dataclasses.replace(base, final_logit_softcapping=0.5)
    tokens = jnp.asarray(
        np.random.RandomState(7).randint(0, 64, size=(1, 8)))
    labels = jnp.asarray(
        np.random.RandomState(8).randint(0, 64, size=(1, 8)))

    def stage_loss(cfg):
        stage = GPTStage(cfg, layers_per_stage=2)
        v = stage.init(jax.random.PRNGKey(0), tokens,
                       jnp.zeros((8, 1, 32), jnp.float32),
                       jnp.ones(()), labels, method=GPTStage.full)
        return float(stage.apply(
            v, tokens, jnp.zeros((8, 1, 32), jnp.float32),
            jnp.ones(()), labels, method=GPTStage.full))

    assert abs(stage_loss(capped) - stage_loss(base)) > 1e-3


def _tiny_qwen3moe(seed=51, norm_topk=True):
    cfg = transformers.Qwen3MoeConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        moe_intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        max_position_embeddings=32, attention_dropout=0.0,
        num_experts=8, num_experts_per_tok=2, norm_topk_prob=norm_topk,
        decoder_sparse_step=1, mlp_only_layers=[],
        use_sliding_window=False)
    torch.manual_seed(seed)
    hf = transformers.Qwen3MoeForCausalLM(cfg).eval()
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if name.endswith(("q_norm.weight", "k_norm.weight")):
                p.copy_(1.0 + torch.randn_like(p) * 0.3)
    return hf, cfg


@pytest.mark.parametrize("norm_topk", [True, False])
def test_logits_match_hf_qwen3moe(norm_topk):
    """Qwen3-MoE oracle (24th family): the Qwen3 attention stack
    (per-head qk-norm) + routed-only top-k experts, renormalized
    (30B-A3B ships norm_topk_prob=true) and raw gate mass."""
    from tools.convert_hf_qwen3moe import convert_qwen3moe

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_qwen3moe(norm_topk=norm_topk)
    cfg, params = convert_qwen3moe(hf.state_dict(), hf_cfg)
    assert cfg.qk_norm == "head"
    assert cfg.moe_normalize_topk == norm_topk
    assert cfg.moe_shared_expert_size is None

    tokens = np.random.RandomState(51).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_qwen3moe_greedy_generation_matches_hf():
    from tools.convert_hf_qwen3moe import convert_qwen3moe

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_qwen3moe(seed=52)
    cfg, params = convert_qwen3moe(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(52).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_qwen3moe_nonuniform_sparsity_refused():
    from tools.convert_hf_qwen3moe import convert_qwen3moe

    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2,
        num_experts=8, decoder_sparse_step=2)
    with pytest.raises(ValueError, match="sparsity"):
        convert_qwen3moe({}, hf_cfg)
    hf_cfg2 = transformers.Qwen3MoeConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2,
        num_experts=8, mlp_only_layers=[1])
    with pytest.raises(ValueError, match="sparsity"):
        convert_qwen3moe({}, hf_cfg2)


def test_qwen3_attention_bias_refused():
    """attention_bias=True checkpoints carry projection biases the
    converters do not map — both must refuse, not zero-fill (review
    finding)."""
    from tools.convert_hf_qwen3 import convert_qwen3
    from tools.convert_hf_qwen3moe import convert_qwen3moe

    with pytest.raises(ValueError, match="attention_bias"):
        convert_qwen3({}, transformers.Qwen3Config(
            vocab_size=96, hidden_size=48, num_hidden_layers=1,
            num_attention_heads=4, num_key_value_heads=2,
            use_sliding_window=False, attention_bias=True))
    with pytest.raises(ValueError, match="attention_bias"):
        convert_qwen3moe({}, transformers.Qwen3MoeConfig(
            vocab_size=96, hidden_size=48, num_hidden_layers=1,
            num_attention_heads=4, num_key_value_heads=2, num_experts=4,
            use_sliding_window=False, attention_bias=True))


def _tiny_phi3(seed=61, window=None):
    cfg = transformers.Phi3Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, sliding_window=window, rope_scaling=None,
        # HF defaults (pad 32000, eos 32000) exceed the tiny vocab
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(seed)
    return transformers.Phi3ForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("window", [None, 8])
def test_logits_match_hf_phi3(window):
    """Phi-3 oracle (25th family): the fused [q_all|k_all|v_all]
    qkv_proj re-sliced into our per-group layout, the [gate|up]
    gate_up_proj mapped verbatim onto fused swiglu, uniform sliding
    window (mini-128k shape, window < seq so it bites)."""
    from tools.convert_hf_phi3 import convert_phi3

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_phi3(window=window)
    cfg, params = convert_phi3(hf.state_dict(), hf_cfg)
    assert cfg.activation == "swiglu"
    assert cfg.sliding_window == window

    tokens = np.random.RandomState(61).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)


def test_phi3_greedy_generation_matches_hf():
    from tools.convert_hf_phi3 import convert_phi3

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_phi3(seed=62)
    cfg, params = convert_phi3(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(62).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_phi3_longrope_refused():
    """longrope (su) short/long factor tables are seq-dependent — must
    be refused by _map_rope_scaling, not ignored."""
    from tools.convert_hf_phi3 import convert_phi3

    hf_cfg = transformers.Phi3Config(
        vocab_size=96, hidden_size=48, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
        original_max_position_embeddings=32,
        rope_scaling={"type": "longrope",
                      "short_factor": [1.0] * 6,
                      "long_factor": [2.0] * 6})
    with pytest.raises(ValueError, match="rope_scaling"):
        convert_phi3({}, hf_cfg)


def test_logits_match_hf_phi3_partial_rotary():
    """partial_rotary_factor=0.5 parity: HF rotates the leading
    rotary_dim dims (rotate-half) — must land on our rotary_percent
    convention, not silently stay full-rotary (review finding)."""
    from tools.convert_hf_phi3 import convert_phi3

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf_cfg = transformers.Phi3Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, rope_scaling=None,
        partial_rotary_factor=0.5,
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(63)
    hf = transformers.Phi3ForCausalLM(hf_cfg).eval()
    cfg, params = convert_phi3(hf.state_dict(), hf_cfg)
    assert cfg.rotary_percent == 0.5

    tokens = np.random.RandomState(63).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)


def _tiny_olmo2(seed=71):
    cfg = transformers.Olmo2Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0)
    torch.manual_seed(seed)
    hf = transformers.Olmo2ForCausalLM(cfg).eval()
    # randomize ALL norm weights (HF inits them to ones): the post-norm
    # block placement is only oracled if the norms actually do something
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if name.endswith("norm.weight") or "layernorm" in name:
                p.copy_(1.0 + torch.randn_like(p) * 0.3)
    return hf, cfg


def test_logits_match_hf_olmo2():
    """OLMo-2 oracle (26th family): POST-norm blocks — branches read the
    raw residual stream and only their outputs are normed
    (pre_norm=False + sandwich_norm) — plus projection-wide qk-norm.
    All norm weights randomized so a misplaced norm breaks parity."""
    from tools.convert_hf_olmo2 import convert_olmo2

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_olmo2()
    cfg, params = convert_olmo2(hf.state_dict(), hf_cfg)
    assert not cfg.pre_norm and cfg.sandwich_norm
    layer0 = params["transformer"]["layer_0"]
    assert "input_layernorm" not in layer0
    assert "post_self_attn_norm" in layer0

    tokens = np.random.RandomState(71).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_olmo2_greedy_generation_matches_hf():
    from tools.convert_hf_olmo2 import convert_olmo2

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_olmo2(seed=72)
    cfg, params = convert_olmo2(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(72).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_post_norm_without_sandwich_refused():
    from apex_tpu.models import TransformerConfig

    with pytest.raises(ValueError, match="pre_norm"):
        TransformerConfig(pre_norm=False)


def _tiny_granite(seed=81):
    cfg = transformers.GraniteConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0,
        # all four muP scalars != 1 so each mapping is load-bearing
        embedding_multiplier=12.0, attention_multiplier=0.2,
        residual_multiplier=0.22, logits_scaling=8.0,
        tie_word_embeddings=True)
    torch.manual_seed(seed)
    return transformers.GraniteForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_granite():
    """Granite oracle (27th family): the four muP scalars — embedding
    multiplier, attention multiplier (mapped exactly onto
    query_pre_attn_scalar = 1/m^2), residual multiplier, logits
    divisor — all set to non-default values so any dropped scalar
    breaks parity."""
    from tools.convert_hf_granite import convert_granite

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_granite()
    cfg, params = convert_granite(hf.state_dict(), hf_cfg)
    assert cfg.residual_multiplier == 0.22
    assert cfg.logits_scaling == 8.0
    assert abs(cfg.query_pre_attn_scalar - 25.0) < 1e-9  # 1/0.2^2

    tokens = np.random.RandomState(81).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_granite_greedy_generation_matches_hf():
    from tools.convert_hf_granite import convert_granite

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_granite(seed=82)
    cfg, params = convert_granite(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(82).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_gemma3(seed=91, with_scaling=True):
    kw = dict(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=2, head_dim=12,
        max_position_embeddings=64, attention_dropout=0.0,
        sliding_window=8, sliding_window_pattern=3,
        rope_theta=1_000_000.0, rope_local_base_freq=10000.0,
        query_pre_attn_scalar=20.0, attn_implementation="eager")
    if with_scaling:
        # global layers get linear rope scaling; local layers must NOT
        kw["rope_scaling"] = {"rope_type": "linear", "factor": 8.0}
    cfg = transformers.Gemma3TextConfig(**kw)
    torch.manual_seed(seed)
    return transformers.Gemma3ForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("with_scaling", [True, False])
def test_logits_match_hf_gemma3(with_scaling):
    """Gemma-3 oracle (28th family): per-layer-type rope — local
    (windowed) layers use rope_local_base_freq with NO frequency
    rescaling while global layers use rope_theta (+ linear scaling when
    set) — plus per-head qk-norm with (1+w) folding, sandwich norms,
    pattern-3 alternation at window < seq."""
    from tools.convert_hf_gemma3 import convert_gemma3

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gemma3(with_scaling=with_scaling)
    cfg, params = convert_gemma3(hf.state_dict(), hf_cfg)
    assert cfg.rotary_base_local == 10000.0
    assert cfg.sliding_window_pattern == 3 and cfg.qk_norm == "head"
    assert (cfg.rope_scaling is not None) == with_scaling

    tokens = np.random.RandomState(91).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_gemma3_greedy_generation_matches_hf():
    from tools.convert_hf_gemma3 import convert_gemma3

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_gemma3(seed=92)
    cfg, params = convert_gemma3(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(92).randint(0, 96, size=(2, 10))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_gemma3_bidirectional_refused():
    from tools.convert_hf_gemma3 import convert_gemma3

    hf_cfg = transformers.Gemma3TextConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        use_bidirectional_attention=True)
    with pytest.raises(ValueError, match="bidirectional"):
        convert_gemma3({}, hf_cfg)


def _tiny_cohere(seed=101):
    cfg = transformers.CohereConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, logit_scale=0.0625, use_qk_norm=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(seed)
    return transformers.CohereForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_cohere():
    """Cohere/Command-R oracle (29th family): shared-LN parallel
    residual + bias-free LayerNorm + INTERLEAVED rope + multiplicative
    logit_scale (mapped onto the logits_scaling divisor) + tied head —
    all existing knobs composed a new way."""
    from tools.convert_hf_cohere import convert_cohere

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_cohere()
    cfg, params = convert_cohere(hf.state_dict(), hf_cfg)
    assert cfg.parallel_residual and cfg.parallel_residual_shared_ln
    assert cfg.rotary_interleaved
    assert cfg.logits_scaling == 16.0  # 1 / 0.0625

    tokens = np.random.RandomState(101).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_cohere_greedy_generation_matches_hf():
    from tools.convert_hf_cohere import convert_cohere

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_cohere(seed=102)
    cfg, params = convert_cohere(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(102).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_cohere_qk_norm_refused():
    from tools.convert_hf_cohere import convert_cohere

    hf_cfg = transformers.CohereConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2, use_qk_norm=True)
    with pytest.raises(ValueError, match="qk_norm"):
        convert_cohere({}, hf_cfg)


def test_cohere_untied_and_bias_paths():
    """attention_bias refusal (COVERAGE claim must be tested) and the
    untied-head mapping (an untied config without lm_head in params
    would crash at apply time, not conversion time)."""
    from tools.convert_hf_cohere import convert_cohere

    hf_cfg = transformers.CohereConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2,
        use_qk_norm=False, attention_bias=True)
    with pytest.raises(ValueError, match="attention_bias"):
        convert_cohere({}, hf_cfg)

    untied = transformers.CohereConfig(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        use_qk_norm=False, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(103)
    hf = transformers.CohereForCausalLM(untied).eval()
    cfg, params = convert_cohere(hf.state_dict(), untied)
    assert not cfg.tie_word_embeddings and "lm_head" in params
    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    tokens = np.random.RandomState(103).randint(0, 96, size=(1, 8))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def _tiny_nemotron(seed=111):
    cfg = transformers.NemotronConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, hidden_act="relu2",
        partial_rotary_factor=0.5)
    torch.manual_seed(seed)
    return transformers.NemotronForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_nemotron():
    """Nemotron oracle (30th family): LayerNorm1p (weight+1 folded at
    conversion), squared-ReLU ungated MLP (relu2), partial rotary 0.5 —
    against HF's independent implementation."""
    from tools.convert_hf_nemotron import convert_nemotron

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_nemotron()
    cfg, params = convert_nemotron(hf.state_dict(), hf_cfg)
    assert cfg.activation == "relu2" and cfg.rotary_percent == 0.5

    tokens = np.random.RandomState(111).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)


def test_nemotron_greedy_generation_matches_hf():
    from tools.convert_hf_nemotron import convert_nemotron

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_nemotron(seed=112)
    cfg, params = convert_nemotron(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(112).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_nemotron_bias_variants_refused():
    from tools.convert_hf_nemotron import convert_nemotron

    hf_cfg = transformers.NemotronConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2,
        attention_bias=True)
    with pytest.raises(ValueError, match="attention_bias"):
        convert_nemotron({}, hf_cfg)
    hf_cfg2 = transformers.NemotronConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2, mlp_bias=True)
    with pytest.raises(ValueError, match="mlp_bias"):
        convert_nemotron({}, hf_cfg2)


def _tiny_smollm3(seed=121):
    cfg = transformers.SmolLM3Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, no_rope_layer_interval=2,
        use_sliding_window=False, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(seed)
    return transformers.SmolLM3ForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_smollm3():
    """SmolLM3 oracle (31st family): NoPE alternation — every 2nd layer
    applies NO rotary embedding (4 layers: rope, none, rope, none) —
    plus a materiality check that disabling the alternation diverges."""
    import dataclasses

    from tools.convert_hf_smollm3 import convert_smollm3

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_smollm3()
    cfg, params = convert_smollm3(hf.state_dict(), hf_cfg)
    assert cfg.no_rope_layer_interval == 2

    tokens = np.random.RandomState(121).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)
    all_rope = GPTModel(dataclasses.replace(
        cfg, no_rope_layer_interval=0)).apply({"params": params},
                                              jnp.asarray(tokens))
    assert not np.allclose(np.asarray(ours), np.asarray(all_rope),
                           atol=1e-5)


def test_smollm3_greedy_generation_matches_hf():
    from tools.convert_hf_smollm3 import convert_smollm3

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_smollm3(seed=122)
    cfg, params = convert_smollm3(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(122).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_smollm3_custom_no_rope_layers_refused():
    from tools.convert_hf_smollm3 import convert_smollm3

    hf_cfg = transformers.SmolLM3Config(
        vocab_size=96, hidden_size=48, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2,
        no_rope_layers=[1, 1, 0, 1], no_rope_layer_interval=2,
        use_sliding_window=False)
    with pytest.raises(ValueError, match="no_rope_layers"):
        convert_smollm3({}, hf_cfg)


def _tiny_helium(seed=131):
    cfg = transformers.HeliumConfig(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=12,
        max_position_embeddings=32, attention_dropout=0.0,
        attention_bias=False, mlp_bias=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(seed)
    return transformers.HeliumForCausalLM(cfg).eval(), cfg


def test_logits_match_hf_helium():
    """Helium oracle (32nd family): the llama shape (RMSNorm, SwiGLU,
    GQA) under the INTERLEAVED rope convention — a combination no other
    family pins (GPT-J is interleaved but partial-rotary +
    parallel-residual; Cohere is interleaved but LayerNorm + parallel
    residual). HF's o_proj is [hidden, hidden], so head_dim must equal
    hidden/heads here."""
    from tools.convert_hf_helium import convert_helium

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_helium()
    cfg, params = convert_helium(hf.state_dict(), hf_cfg)
    assert cfg.rotary_interleaved

    tokens = np.random.RandomState(131).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=3e-4,
                               atol=3e-4)


def test_helium_greedy_generation_matches_hf():
    from tools.convert_hf_helium import convert_helium

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_helium(seed=132)
    cfg, params = convert_helium(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(132).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_glm4(seed=141, biased=True):
    cfg = transformers.Glm4Config(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=12,
        max_position_embeddings=32, attention_dropout=0.0,
        partial_rotary_factor=0.5, attention_bias=biased,
        pad_token_id=0, eos_token_id=2)
    torch.manual_seed(seed)
    hf = transformers.Glm4ForCausalLM(cfg).eval()
    if biased:  # HF zero-inits biases; randomize to oracle the mapping
        with torch.no_grad():
            for name, p in hf.named_parameters():
                if "self_attn" in name and name.endswith("bias"):
                    p.copy_(torch.randn_like(p) * 0.5)
    return hf, cfg


@pytest.mark.parametrize("biased", [True, False])
def test_logits_match_hf_glm4(biased):
    """GLM-4 oracle (33rd family): sandwich norms in the Gemma-2 slot
    semantics + partial INTERLEAVED rope (0.5, even/odd lanes) + QKV
    biases through the fused per-group layout + verbatim [gate|up]
    mapping — a knob combination no other family pins."""
    from tools.convert_hf_glm4 import convert_glm4

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_glm4(biased=biased)
    cfg, params = convert_glm4(hf.state_dict(), hf_cfg)
    assert cfg.sandwich_norm and cfg.rotary_interleaved
    assert cfg.rotary_percent == 0.5

    tokens = np.random.RandomState(141).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_glm4_greedy_generation_matches_hf():
    from tools.convert_hf_glm4 import convert_glm4

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_glm4(seed=142)
    cfg, params = convert_glm4(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(142).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_exaone4(seed=151, window=8):
    kw = dict(sliding_window=window, sliding_window_pattern=2)
    if window is None:
        # HF's config builds layer_types with % pattern and zeroes the
        # pattern for windowless configs -> ZeroDivisionError unless the
        # list is explicit
        kw = dict(sliding_window=None,
                  layer_types=["full_attention"] * 4)
    cfg = transformers.Exaone4Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, head_dim=12,
        max_position_embeddings=32, attention_dropout=0.0,
        pad_token_id=0, bos_token_id=1, eos_token_id=2, **kw)
    torch.manual_seed(seed)
    hf = transformers.Exaone4ForCausalLM(cfg).eval()
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if name.endswith(("q_norm.weight", "k_norm.weight")):
                p.copy_(1.0 + torch.randn_like(p) * 0.3)
    return hf, cfg


@pytest.mark.parametrize("window", [8, None])
def test_logits_match_hf_exaone4(window):
    """EXAONE-4 oracle (34th family): FOUR knobs composed — hybrid
    sliding (window < seq so it bites), rope ONLY on the sliding layers
    (the full-attention layers are NoPE: sliding_window_pattern and
    no_rope_layer_interval share the model's (i+1)%N convention),
    OLMo-2-style post-norm blocks, per-head qk-norm (randomized
    weights). window=None: full attention + rope everywhere."""
    from tools.convert_hf_exaone4 import convert_exaone4

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_exaone4(window=window)
    cfg, params = convert_exaone4(hf.state_dict(), hf_cfg)
    assert not cfg.pre_norm and cfg.qk_norm == "head"
    if window is not None:
        assert cfg.sliding_window_pattern == 2
        assert cfg.no_rope_layer_interval == 2
    else:
        assert cfg.no_rope_layer_interval == 0

    tokens = np.random.RandomState(151).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_exaone4_greedy_generation_matches_hf():
    from tools.convert_hf_exaone4 import convert_exaone4

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_exaone4(seed=152)
    cfg, params = convert_exaone4(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(152).randint(0, 96, size=(2, 10))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_exaone4_ambiguous_window_refused():
    """sliding_window without a pattern would silently window every
    layer with rope (HF runs full+NoPE) — refuse (review finding)."""
    from tools.convert_hf_exaone4 import convert_exaone4

    hf_cfg = transformers.Exaone4Config(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        sliding_window=8, layer_types=["full_attention"] * 2)
    hf_cfg.sliding_window_pattern = None
    with pytest.raises(ValueError, match="ambiguous"):
        convert_exaone4({}, hf_cfg)


def _tiny_dbrx(seed=161, clip=4.0):
    cfg = transformers.DbrxConfig(
        d_model=48, n_heads=4, n_layers=2, max_seq_len=32,
        vocab_size=96, attn_config=__import__('transformers.models.dbrx.configuration_dbrx', fromlist=['DbrxAttentionConfig']).DbrxAttentionConfig(
            kv_n_heads=2, clip_qkv=clip, rope_theta=10000.0,
            attn_pdrop=0.0),
        ffn_config=__import__('transformers.models.dbrx.configuration_dbrx', fromlist=['DbrxFFNConfig']).DbrxFFNConfig(
            ffn_hidden_size=64, moe_num_experts=8, moe_top_k=2,
            moe_normalize_expert_weights=1.0),
        resid_pdrop=0.0, emb_pdrop=0.0,
        pad_token_id=0, eos_token_id=2)
    torch.manual_seed(seed)
    return transformers.DbrxForCausalLM(cfg).eval(), cfg


@pytest.mark.parametrize("clip", [0.05, None])
def test_logits_match_hf_dbrx(clip):
    """DBRX oracle (35th family): fused Wqkv with QKV clamping
    (qkv_clip — clip=0.05 is far inside the random-init projection
    range, so the clamp provably bites), giant
    stacked expert tensors (w1/v1/w2 with w2 already [in, out]),
    bias-free LayerNorm, L1-renormalized top-4 routing."""
    from tools.convert_hf_dbrx import convert_dbrx

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_dbrx(clip=clip)
    cfg, params = convert_dbrx(hf.state_dict(), hf_cfg)
    assert cfg.qkv_clip == clip
    if clip is not None:
        # the clamp must actually fire at these scales, else this
        # parity would be vacuous for the clip mapping
        import dataclasses as _dc
        unclipped = GPTModel(_dc.replace(cfg, qkv_clip=None)).apply(
            {"params": params},
            jnp.asarray(np.random.RandomState(161).randint(
                0, 96, size=(2, 16))))
    assert cfg.moe_normalize_topk

    tokens = np.random.RandomState(161).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)
    if clip is not None:
        assert not np.allclose(np.asarray(ours), np.asarray(unclipped),
                               atol=1e-5)


def test_dbrx_greedy_generation_matches_hf():
    from tools.convert_hf_dbrx import convert_dbrx

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_dbrx(seed=162, clip=2.0)
    cfg, params = convert_dbrx(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(162).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_dbrx_unsupported_norm_p_refused():
    from tools.convert_hf_dbrx import convert_dbrx

    hf_cfg = transformers.DbrxConfig(
        d_model=48, n_heads=4, n_layers=1, vocab_size=96,
        attn_config=__import__('transformers.models.dbrx.configuration_dbrx', fromlist=['DbrxAttentionConfig']).DbrxAttentionConfig(kv_n_heads=2),
        ffn_config=__import__('transformers.models.dbrx.configuration_dbrx', fromlist=['DbrxFFNConfig']).DbrxFFNConfig(
            ffn_hidden_size=64, moe_num_experts=4, moe_top_k=2,
            moe_normalize_expert_weights=2.0))
    with pytest.raises(ValueError, match="normalize_expert"):
        convert_dbrx({}, hf_cfg)


def _tiny_starcoder2(seed=171, window=None):
    cfg = transformers.Starcoder2Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        attention_dropout=0.0, residual_dropout=0.0,
        embedding_dropout=0.0, use_bias=True,
        sliding_window=window,
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(seed)
    hf = transformers.Starcoder2ForCausalLM(cfg).eval()
    # HF zero-inits linear biases; randomize so all four bias mappings
    # (qkv fused, o, c_fc, c_proj) are load-bearing in the oracle
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if name.endswith(".bias") and "norm" not in name:
                p.copy_(torch.randn_like(p) * 0.3)
    return hf, cfg


@pytest.mark.parametrize("window", [None, 8])
def test_logits_match_hf_starcoder2(window):
    """Starcoder2 oracle (36th family): modern attention (rope + GQA +
    optional uniform window) over the GPT-2-era MLP form — biased
    LayerNorm blocks, non-gated tanh-gelu, and use_bias=True on every
    projection (all biases randomized so each mapping is oracled)."""
    from tools.convert_hf_starcoder2 import convert_starcoder2

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_starcoder2(window=window)
    cfg, params = convert_starcoder2(hf.state_dict(), hf_cfg)
    assert cfg.activation == "gelu" and cfg.normalization == "layernorm"
    assert cfg.sliding_window == window

    tokens = np.random.RandomState(171).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_starcoder2_greedy_generation_matches_hf():
    from tools.convert_hf_starcoder2 import convert_starcoder2

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_starcoder2(seed=172)
    cfg, params = convert_starcoder2(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(172).randint(0, 96, size=(2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def _tiny_olmo3(seed=181, scaling=False):
    kw = {}
    if scaling:
        kw["rope_scaling"] = {"rope_type": "linear", "factor": 4.0}
    cfg = transformers.Olmo3Config(
        vocab_size=96, hidden_size=48, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        attention_dropout=0.0, sliding_window=8, **kw)
    torch.manual_seed(seed)
    hf = transformers.Olmo3ForCausalLM(cfg).eval()
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if name.endswith("norm.weight") or "layernorm" in name:
                p.copy_(1.0 + torch.randn_like(p) * 0.3)
    return hf, cfg


@pytest.mark.parametrize("scaling", [False, True])
def test_logits_match_hf_olmo3(scaling):
    """OLMo-3 oracle (37th family): the OLMo-2 post-norm/qk-norm stack
    + 3:1 sliding/full alternation with DUAL rotary — scaled rope on
    the full-attention layers only (rotary_base_local == rotary_base
    expresses 'same base, no scaling' for the sliding layers)."""
    from tools.convert_hf_olmo3 import convert_olmo3

    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_olmo3(scaling=scaling)
    cfg, params = convert_olmo3(hf.state_dict(), hf_cfg)
    assert not cfg.pre_norm and cfg.sliding_window_pattern == 4
    if scaling:
        assert cfg.rotary_base_local == cfg.rotary_base
        assert cfg.rope_scaling is not None

    tokens = np.random.RandomState(181).randint(0, 96, size=(2, 16))
    with torch.no_grad():
        ref = hf(torch.asarray(tokens)).logits.numpy()
    ours = GPTModel(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-4,
                               atol=4e-4)


def test_olmo3_greedy_generation_matches_hf():
    from tools.convert_hf_olmo3 import convert_olmo3

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import generate
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_olmo3(seed=182, scaling=True)
    cfg, params = convert_olmo3(hf.state_dict(), hf_cfg)
    prompt = np.random.RandomState(182).randint(0, 96, size=(2, 10))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    ours = generate(GPTModel(cfg, decode=True), params,
                    jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_olmo3_nonstandard_layer_types_refused():
    """COVERAGE claims the refusal — it must be tested (review
    finding)."""
    from tools.convert_hf_olmo3 import convert_olmo3

    hf_cfg = transformers.Olmo3Config(
        vocab_size=96, hidden_size=48, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, sliding_window=8,
        layer_types=["full_attention"] * 4)
    with pytest.raises(ValueError, match="layer_types"):
        convert_olmo3({}, hf_cfg)
