"""Transformer auxiliary subsystems: fused softmax, microbatch
calculators, TP data broadcast, RNG streams, batch samplers.

Parity: reference tests/L0/run_transformer/{test_fused_softmax.py,
test_microbatches.py, test_data.py, test_random.py, test_batch_sampler.py}.
Oracles are plain jax.nn.softmax / hand-computed schedules, mirroring the
reference's "fused kernel vs torch.nn.Softmax" strategy.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional.fused_softmax import (
    FusedScaleMaskSoftmax,
    GenericFusedScaleMaskSoftmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)


def _mask_func(scores, mask):
    return jnp.where(mask.astype(bool), -10000.0, scores)


class TestFusedSoftmaxNumerics:
    """Fused forms vs jax.nn.softmax oracle (reference test_fused_softmax
    compares kernels against a torch softmax + explicit masking)."""

    def test_scaled_softmax_matches_oracle(self, rng):
        x = jnp.asarray(rng.randn(2, 4, 8, 16).astype(np.float32))
        out = scaled_softmax(x, 0.7)
        ref = jax.nn.softmax(x * 0.7, axis=-1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_scaled_masked_softmax_matches_oracle(self, rng):
        x = jnp.asarray(rng.randn(2, 4, 8, 16).astype(np.float32))
        mask = jnp.asarray(rng.rand(2, 1, 8, 16) < 0.3)
        out = scaled_masked_softmax(x, mask, 0.5)
        ref = jax.nn.softmax(jnp.where(mask, -1e9, x * 0.5), axis=-1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
        # masked-out positions carry exactly zero probability
        assert float(jnp.abs(jnp.where(mask, out, 0.0)).max()) == 0.0

    def test_causal_matches_oracle(self, rng):
        x = jnp.asarray(rng.randn(8, 16, 16).astype(np.float32))
        out = scaled_upper_triang_masked_softmax(x, 1.3)
        causal = np.tril(np.ones((16, 16), bool))
        ref = jax.nn.softmax(jnp.where(jnp.asarray(causal), x * 1.3, -1e9),
                             axis=-1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
        # strictly-upper entries are exactly zero
        assert float(jnp.abs(jnp.where(jnp.asarray(~causal), out,
                                       0.0)).max()) == 0.0

    def test_causal_rows_sum_to_one_bf16(self, rng):
        x = jnp.asarray(rng.randn(4, 32, 32).astype(np.float32),
                        dtype=jnp.bfloat16)
        out = scaled_upper_triang_masked_softmax(x, 1.0)
        assert out.dtype == jnp.bfloat16
        sums = jnp.sum(out.astype(jnp.float32), axis=-1)
        np.testing.assert_allclose(np.asarray(sums), 1.0, atol=1e-2)

    def test_grad_matches_oracle(self, rng):
        x = jnp.asarray(rng.randn(2, 2, 8, 8).astype(np.float32))
        mask = jnp.asarray(rng.rand(2, 1, 8, 8) < 0.25)

        def fused(x):
            return jnp.sum(scaled_masked_softmax(x, mask, 0.9) ** 2)

        def oracle(x):
            return jnp.sum(
                jax.nn.softmax(jnp.where(mask, -1e9, x * 0.9), -1) ** 2)

        gf = jax.grad(fused)(x)
        go = jax.grad(oracle)(x)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(go), atol=1e-5)


class TestFusedSoftmaxDispatch:
    """Reference heuristics (fused_softmax.py:222-246): kernel chosen only
    for fp16/bf16, 16 < sk <= 16384, divisibility conditions."""

    def make(self, mask_type=AttnMaskType.padding, fusion=True):
        return FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True, attn_mask_type=mask_type,
            scaled_masked_softmax_fusion=fusion, mask_func=_mask_func,
            softmax_in_fp32=True, scale=2.0)

    def test_kernel_available_for_standard_shape(self):
        sm = self.make()
        assert sm.is_kernel_available(None, 2, 4, 32, 64)

    def test_kernel_unavailable_small_sk(self):
        assert not self.make().is_kernel_available(None, 2, 4, 32, 16)

    def test_kernel_unavailable_without_fusion_flag(self):
        assert not self.make(fusion=False).is_kernel_available(
            None, 2, 4, 32, 64)

    def test_kernel_unavailable_fp32_input(self):
        sm = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=False,
            attn_mask_type=AttnMaskType.padding,
            scaled_masked_softmax_fusion=True, mask_func=_mask_func,
            softmax_in_fp32=True, scale=None)
        assert not sm.is_kernel_available(None, 2, 4, 32, 64)

    def test_fused_and_fallback_agree(self, rng):
        sm = self.make()
        x = jnp.asarray(rng.randn(2, 4, 32, 64).astype(np.float32),
                        dtype=jnp.bfloat16)
        mask = jnp.asarray(rng.rand(2, 1, 32, 64) < 0.3)
        fused = sm.forward_fused_softmax(x, mask)
        fallback = sm.forward_torch_softmax(x, mask)
        np.testing.assert_allclose(
            np.asarray(fused.astype(jnp.float32)),
            np.asarray(fallback.astype(jnp.float32)), atol=2e-2)

    def test_causal_dispatch_applies_triangle(self, rng):
        sm = self.make(mask_type=AttnMaskType.causal)
        x = jnp.asarray(rng.randn(2, 4, 32, 32).astype(np.float32),
                        dtype=jnp.bfloat16)
        out = sm(x, None)
        upper = jnp.triu(jnp.ones((32, 32), bool), k=1)
        assert float(jnp.abs(jnp.where(upper, out.astype(jnp.float32),
                                       0.0)).max()) == 0.0

    def test_generic_always_available(self):
        g = GenericFusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=False, mask_func=_mask_func,
            softmax_in_fp32=True, scale=None)
        assert g.is_kernel_available(None, 1, 1, 3, 5)

    def test_scale_requires_fp32_softmax(self):
        with pytest.raises(AssertionError):
            FusedScaleMaskSoftmax(
                input_in_fp16=False, input_in_bf16=True,
                attn_mask_type=AttnMaskType.padding,
                scaled_masked_softmax_fusion=True, mask_func=_mask_func,
                softmax_in_fp32=False, scale=2.0)


class TestMicrobatchCalculators:
    """Reference tests/L0/run_transformer/test_microbatches.py."""

    def test_constant(self):
        from apex_tpu.transformer.microbatches import (
            build_num_microbatches_calculator,
        )

        calc = build_num_microbatches_calculator(
            rank=1, rampup_batch_size=None, global_batch_size=32,
            micro_batch_size=2, data_parallel_size=4)
        assert calc.get() == 4
        assert calc.get_current_global_batch_size() == 32
        calc.update(10_000, consistency_check=True)  # no-op
        assert calc.get() == 4

    def test_constant_indivisible_raises(self):
        from apex_tpu.transformer.microbatches import ConstantNumMicroBatches

        with pytest.raises(AssertionError):
            ConstantNumMicroBatches(global_batch_size=30, micro_batch_size=4,
                                    data_parallel_size=2)

    def test_rampup_schedule(self):
        from apex_tpu.transformer.microbatches import (
            build_num_microbatches_calculator,
        )

        # 16 -> 32 in +8 steps over 64 samples: increments at 32-sample
        # boundaries (2 increments, 32 samples each).
        calc = build_num_microbatches_calculator(
            rank=1, rampup_batch_size=[16, 8, 64], global_batch_size=32,
            micro_batch_size=2, data_parallel_size=2)
        assert calc.get_current_global_batch_size() == 16
        assert calc.get() == 4
        calc.update(32, consistency_check=True)
        assert calc.get_current_global_batch_size() == 24
        assert calc.get() == 6
        calc.update(64, consistency_check=True)
        assert calc.get_current_global_batch_size() == 32
        calc.update(65, consistency_check=True)  # past ramp: final size
        assert calc.get_current_global_batch_size() == 32
        assert calc.get() == 8


class TestBroadcastData:
    """Reference tests/L0/run_transformer/test_data.py: the keyed dict
    arrives identically on every tp rank."""

    def test_broadcast_inside_tp_mesh(self, rng):
        from apex_tpu.transformer.tensor_parallel.data import broadcast_data

        devices = np.asarray(jax.devices()[:4])
        mesh = Mesh(devices, ("tp",))
        data = {"text": jnp.asarray(rng.randint(0, 100, (4, 8))),
                "types": jnp.asarray(rng.randint(0, 2, (4, 8)))}

        @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P("tp"), P("tp")), check_vma=False)
        def f(text, types):
            rank = jax.lax.axis_index("tp")
            # simulate rank-divergent inputs: only rank 0 has real data
            local = {"text": jnp.where(rank == 0, text, 0),
                     "types": jnp.where(rank == 0, types, 0)}
            out = broadcast_data(["text", "types"], local, jnp.int32)
            return (out["text"][None], out["types"][None])

        text_all, types_all = f(data["text"], data["types"])
        for r in range(4):
            np.testing.assert_array_equal(np.asarray(text_all[r]),
                                          np.asarray(data["text"]))
            np.testing.assert_array_equal(np.asarray(types_all[r]),
                                          np.asarray(data["types"]))


class TestRNGStreams:
    """Reference tests/L0/run_transformer/test_random.py semantics."""

    def test_seed_layout(self):
        from apex_tpu.transformer.tensor_parallel import random as tp_random

        tp_random.model_parallel_xla_manual_seed(123)
        tr = tp_random.get_rng_state_tracker()
        states = tr.get_states()
        assert set(states) == {"default",
                               tp_random.model_parallel_rng_tracker_name()}

    def test_fork_advances_stream(self):
        from apex_tpu.transformer.tensor_parallel import random as tp_random

        tp_random.model_parallel_xla_manual_seed(123)
        tr = tp_random.get_rng_state_tracker()
        with tr.fork() as k1:
            a = jax.random.normal(k1, (4,))
        with tr.fork() as k2:
            b = jax.random.normal(k2, (4,))
        assert not np.allclose(np.asarray(a), np.asarray(b))

    def test_set_get_states_roundtrip_reproduces(self):
        from apex_tpu.transformer.tensor_parallel import random as tp_random

        tp_random.model_parallel_xla_manual_seed(7)
        tr = tp_random.get_rng_state_tracker()
        saved = tr.get_states()
        with tr.fork() as k:
            a = jax.random.normal(k, (4,))
        tr.set_states(saved)
        with tr.fork() as k:
            b = jax.random.normal(k, (4,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_duplicate_add_raises(self):
        from apex_tpu.transformer.tensor_parallel import random as tp_random

        tp_random.model_parallel_xla_manual_seed(1)
        tr = tp_random.get_rng_state_tracker()
        with pytest.raises(Exception):
            tr.add("default", 5)

    def test_fold_in_tp_rank_differs_per_rank(self):
        from apex_tpu.transformer.tensor_parallel.random import (
            fold_in_tp_rank,
        )

        devices = np.asarray(jax.devices()[:4])
        mesh = Mesh(devices, ("tp",))

        @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(),
                           out_specs=P("tp"), check_vma=False)
        def f(key):
            k = fold_in_tp_rank(key)
            return jax.random.normal(k, (3,))[None]

        out = np.asarray(f(jax.random.PRNGKey(0)))
        for r in range(1, 4):
            assert not np.allclose(out[0], out[r])


class TestBatchSamplers:
    """Reference tests/L0/run_transformer/test_batch_sampler.py."""

    def test_sequential_shards_disjoint_and_ordered(self):
        from apex_tpu.transformer._data._batchsampler import (
            MegatronPretrainingSampler,
        )

        shards = []
        for rank in range(2):
            s = MegatronPretrainingSampler(
                total_samples=16, consumed_samples=0, micro_batch_size=2,
                data_parallel_rank=rank, data_parallel_size=2)
            shards.append(list(s))
        # each global granule of 4 splits 2/2 between the ranks
        assert shards[0][0] == [0, 1] and shards[1][0] == [2, 3]
        flat = sorted(i for sh in shards for b in sh for i in b)
        assert flat == list(range(16))

    def test_sequential_resume_from_consumed(self):
        from apex_tpu.transformer._data._batchsampler import (
            MegatronPretrainingSampler,
        )

        s = MegatronPretrainingSampler(
            total_samples=16, consumed_samples=8, micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=2)
        assert list(s)[0] == [8, 9]

    def test_sequential_drop_last(self):
        from apex_tpu.transformer._data._batchsampler import (
            MegatronPretrainingSampler,
        )

        kw = dict(total_samples=10, consumed_samples=0, micro_batch_size=2,
                  data_parallel_rank=0, data_parallel_size=2)
        assert len(list(MegatronPretrainingSampler(drop_last=True, **kw))) == 2
        assert len(list(MegatronPretrainingSampler(drop_last=False,
                                                   **kw))) == 3

    def test_random_sampler_covers_shard_deterministically(self):
        from apex_tpu.transformer._data._batchsampler import (
            MegatronPretrainingRandomSampler,
        )

        def collect(rank):
            s = MegatronPretrainingRandomSampler(
                total_samples=16, consumed_samples=0, micro_batch_size=2,
                data_parallel_rank=rank, data_parallel_size=2, seed=5)
            return [b for b, _ in zip(iter(s), range(4))]

        a0, a1 = collect(0), collect(1)
        assert collect(0) == a0  # same seed/epoch -> same order
        flat0 = {i for b in a0 for i in b}
        flat1 = {i for b in a1 for i in b}
        assert flat0.isdisjoint(flat1)
        assert len(flat0) == 8 and len(flat1) == 8
