"""_compile_cache.maybe_enable_compile_cache coverage (ISSUE 2
satellite): env unset -> False with NO config mutation; env set -> True
with the cache dir applied."""

import jax
import pytest

from apex_tpu._compile_cache import maybe_enable_compile_cache


@pytest.fixture
def restore_cache_config():
    before_dir = jax.config.jax_compilation_cache_dir
    before_min = jax.config.jax_persistent_cache_min_compile_time_secs
    yield
    jax.config.update("jax_compilation_cache_dir", before_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      before_min)


def test_env_unset_returns_false_without_config_mutation(
        monkeypatch, restore_cache_config):
    monkeypatch.delenv("APEX_TPU_COMPILE_CACHE", raising=False)
    before_dir = jax.config.jax_compilation_cache_dir
    before_min = jax.config.jax_persistent_cache_min_compile_time_secs
    assert maybe_enable_compile_cache() is False
    assert jax.config.jax_compilation_cache_dir == before_dir
    assert (jax.config.jax_persistent_cache_min_compile_time_secs
            == before_min)


def test_env_empty_string_counts_as_unset(monkeypatch,
                                          restore_cache_config):
    monkeypatch.setenv("APEX_TPU_COMPILE_CACHE", "")
    before_dir = jax.config.jax_compilation_cache_dir
    assert maybe_enable_compile_cache() is False
    assert jax.config.jax_compilation_cache_dir == before_dir


def test_env_set_applies_cache_dir(monkeypatch, tmp_path,
                                   restore_cache_config):
    cache_dir = str(tmp_path / "jit_cache")
    monkeypatch.setenv("APEX_TPU_COMPILE_CACHE", cache_dir)
    assert maybe_enable_compile_cache(min_compile_secs=0.25) is True
    assert jax.config.jax_compilation_cache_dir == cache_dir
    assert (jax.config.jax_persistent_cache_min_compile_time_secs
            == 0.25)
