"""Prefix caching (prefill_prefix + generate(prefix_state=...)): the
shared-system-prompt serving pattern must be TOKEN-EXACT against
prefilling the concatenated prompt from scratch — the prefix forward
runs once, continuations prefill only their suffix at offset
positions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import (
    GPTModel,
    TransformerConfig,
    generate,
    prefill_prefix,
)
from apex_tpu.transformer import parallel_state


def _cfg(**kw):
    base = dict(
        hidden_size=48, num_layers=2, num_attention_heads=4,
        vocab_size=96, max_position_embeddings=64,
        compute_dtype=jnp.float32, use_flash_attention=False,
        normalization="rmsnorm", position_embedding_type="rope",
        activation="swiglu", num_query_groups=2)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(autouse=True)
def _single_device():
    parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("kw", [
    {},                                            # rope GQA
    {"position_embedding_type": "learned",         # GPT-2-style
     "normalization": "layernorm", "activation": "gelu"},
    # tier-1 budget (ISSUE 12): the windowed variant duplicates the
    # offset-position coverage the engine-level prefix test now holds
    pytest.param({"sliding_window": 7},            # windowed decode
                 marks=pytest.mark.slow),
])
def test_prefix_matches_full_prompt(kw):
    cfg = _cfg(**kw)
    model = GPTModel(cfg, decode=True)
    rng = np.random.RandomState(0)
    prefix = jnp.asarray(rng.randint(0, 96, size=(2, 11)))
    suffix = jnp.asarray(rng.randint(0, 96, size=(2, 5)))
    full = jnp.concatenate([prefix, suffix], axis=1)
    params = model.init(jax.random.PRNGKey(1), full)["params"]

    ref = generate(model, params, full, 8)
    state = prefill_prefix(model, params, prefix)
    out = generate(model, params, suffix, 8, prefix_state=state)
    # out is [b, suffix + new]; compare against the full run's tail
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(ref)[:, prefix.shape[1]:])


@pytest.mark.slow  # tier-1 budget (ISSUE 12): the scan_layers variant
# below covers the batch-axis broadcast seam, and the engine-level
# prefix store serves many requests from one entry per run
def test_prefix_broadcasts_to_batch():
    """One batch-1 system prompt, many continuations: each row must
    equal its own full-prompt run."""
    cfg = _cfg()
    model = GPTModel(cfg, decode=True)
    rng = np.random.RandomState(2)
    prefix = jnp.asarray(rng.randint(0, 96, size=(1, 9)))
    suffix = jnp.asarray(rng.randint(0, 96, size=(3, 4)))
    full = jnp.concatenate([jnp.broadcast_to(prefix, (3, 9)), suffix],
                           axis=1)
    params = model.init(jax.random.PRNGKey(3), full)["params"]

    ref = generate(model, params, full, 6)
    state = prefill_prefix(model, params, prefix)
    out = generate(model, params, suffix, 6, prefix_state=state)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(ref)[:, 9:])


@pytest.mark.slow  # tier-1 budget (ISSUE 12): the engine-level prefix
# store serves MANY requests from one cached entry every run — this
# model-level reuse variant duplicates that coverage
def test_prefix_cache_reusable_across_calls():
    """The state must survive multiple generate() calls (nothing
    donates it): two different suffixes from ONE prefilled prefix."""
    cfg = _cfg()
    model = GPTModel(cfg, decode=True)
    rng = np.random.RandomState(4)
    prefix = jnp.asarray(rng.randint(0, 96, size=(1, 8)))
    s1 = jnp.asarray(rng.randint(0, 96, size=(1, 3)))
    s2 = jnp.asarray(rng.randint(0, 96, size=(1, 6)))
    params = model.init(
        jax.random.PRNGKey(5),
        jnp.concatenate([prefix, s2], axis=1))["params"]

    state = prefill_prefix(model, params, prefix)
    out1 = generate(model, params, s1, 5, prefix_state=state)
    out2 = generate(model, params, s2, 5, prefix_state=state)
    ref1 = generate(model, params, jnp.concatenate([prefix, s1], 1), 5)
    ref2 = generate(model, params, jnp.concatenate([prefix, s2], 1), 5)
    np.testing.assert_array_equal(np.asarray(out1),
                                  np.asarray(ref1)[:, 8:])
    np.testing.assert_array_equal(np.asarray(out2),
                                  np.asarray(ref2)[:, 8:])


def test_prefix_validation():
    cfg = _cfg()
    model = GPTModel(cfg, decode=True)
    prefix = jnp.asarray(np.zeros((2, 8), np.int32))
    suffix = jnp.asarray(np.zeros((3, 4), np.int32))
    params = model.init(jax.random.PRNGKey(6), prefix)["params"]
    state = prefill_prefix(model, params, prefix)
    # batch-2 prefix cannot serve batch-3 suffixes
    with pytest.raises(ValueError, match="batch"):
        generate(model, params, suffix, 4, prefix_state=state)
    # prefix + suffix + new must fit the position budget
    with pytest.raises(ValueError, match="prefix"):
        generate(model, params, jnp.zeros((2, 4), jnp.int32), 60,
                 prefix_state=state)
    with pytest.raises(ValueError, match="decode=True"):
        prefill_prefix(GPTModel(cfg), params, prefix)


def test_prefix_broadcast_scan_layers():
    """scan_layers stacks cache leaves with a leading layer axis
    ([L, T, b, g, d]) — the broadcast must find the batch axis there
    too (review finding)."""
    cfg = _cfg(scan_layers=True)
    model = GPTModel(cfg, decode=True)
    rng = np.random.RandomState(7)
    prefix = jnp.asarray(rng.randint(0, 96, size=(1, 8)))
    suffix = jnp.asarray(rng.randint(0, 96, size=(2, 4)))
    full = jnp.concatenate([jnp.broadcast_to(prefix, (2, 8)), suffix],
                           axis=1)
    params = model.init(jax.random.PRNGKey(8), full)["params"]

    ref = generate(model, params, full, 5)
    state = prefill_prefix(model, params, prefix)
    out = generate(model, params, suffix, 5, prefix_state=state)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(ref)[:, 8:])
