"""Pipeline-parallel _timers: monotonic clock + telemetry-span shim.

Satellite of ISSUE 2: the timers moved from ``time.time`` (wall clock —
steps under NTP skew corrupted elapsed times) to ``time.perf_counter``
via telemetry spans, with the public API preserved.
"""

import time

import pytest

from apex_tpu.telemetry import MetricsRegistry, use_registry
from apex_tpu.transformer.pipeline_parallel._timers import _Timer, _Timers


def test_timer_api_preserved():
    timers = _Timers()
    t = timers("fwd")
    assert timers("fwd") is t  # named lookup is cached
    t.start()
    t.stop()
    first = t.elapsed(reset=False)
    assert first >= 0.0
    t.start()
    t.stop()
    assert t.elapsed(reset=True) >= first  # accumulates until reset
    assert t.elapsed_ == 0.0


def test_timer_elapsed_restarts_running_timer():
    t = _Timer("x")
    t.start()
    e = t.elapsed(reset=True)  # must stop, read, reset, restart
    assert e >= 0.0
    assert t.started_
    t.stop()


def test_timer_double_start_asserts():
    t = _Timer("y")
    t.start()
    with pytest.raises(AssertionError):
        t.start()
    t.stop()
    with pytest.raises(AssertionError):
        t.stop()


def test_timer_immune_to_wall_clock_steps(monkeypatch):
    """An NTP step (time.time jumping backwards an hour) must not
    corrupt elapsed — the timers run on perf_counter now."""
    wall = iter([1e9, 1e9 - 3600.0, 1e9 - 7200.0, 1e9 + 9999.0])
    monkeypatch.setattr(time, "time", lambda: next(wall))
    t = _Timer("ntp")
    t.start()
    t.stop()
    assert 0.0 <= t.elapsed(reset=True) < 60.0


def test_timers_write_and_log(capsys):
    class Writer:
        def __init__(self):
            self.rows = []

        def add_scalar(self, name, value, it):
            self.rows.append((name, value, it))

    timers = _Timers()
    timers("tick").start()
    timers("tick").stop()
    w = Writer()
    timers.write(["tick"], w, iteration=3, normalizer=2.0)
    assert len(w.rows) == 1
    name, value, it = w.rows[0]
    assert name == "tick-time" and it == 3 and value >= 0.0
    timers.log(["tick"])
    assert "tick" in capsys.readouterr().out


def test_timer_records_span_when_telemetry_enabled():
    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        t = _Timer("layer0")
        t.start()
        t.stop()
    h = reg.snapshot()["histograms"]["span/timers/layer0"]
    assert h["count"] == 1
    assert h["last"] >= 0.0
