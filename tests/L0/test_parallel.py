"""Data-parallel runtime tests on the virtual 8-device mesh.

Mirrors reference tests/distributed/ (DDP grad-value checks, SyncBatchNorm
suite incl. different semantics) and apex/parallel unit behavior.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from apex_tpu.testing import shard_map

from apex_tpu.parallel import (
    DistributedDataParallel,
    LARC,
    SyncBatchNorm,
    all_reduce_gradients,
    broadcast_params,
    flatten,
    unflatten,
)
from apex_tpu.optimizers import FusedSGD


class TestFlattenUnflatten:
    def test_roundtrip(self, rng):
        ts = [jnp.asarray(rng.randn(3, 4).astype(np.float32)),
              jnp.asarray(rng.randn(5).astype(np.float32))]
        flat = flatten(ts)
        assert flat.shape == (17,)
        outs = unflatten(flat, ts)
        for a, b in zip(ts, outs):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_mixed_dtype_rejected(self):
        """Regression: flatten used to let jnp.concatenate silently
        promote a mixed-dtype leaf list to the widest dtype (while its
        docstring claimed an fp32-width buffer) and unflatten papered
        over it with .astype — a lossy, not-round-trip-exact pair. The
        contract is now a single dtype, which plan_buckets guarantees
        on the bucketed allreduce path."""
        ts = [jnp.zeros((3,), jnp.float32), jnp.zeros((3,), jnp.bfloat16)]
        with pytest.raises(ValueError, match="mixed dtypes"):
            flatten(ts)

    def test_bf16_roundtrip_exact(self, rng):
        ts = [jnp.asarray(rng.randn(5, 3).astype(np.float32)
                          ).astype(jnp.bfloat16),
              jnp.asarray(rng.randn(7).astype(np.float32)
                          ).astype(jnp.bfloat16)]
        flat = flatten(ts)
        assert flat.dtype == jnp.bfloat16  # no silent widening
        for a, b in zip(ts, unflatten(flat, ts)):
            np.testing.assert_array_equal(
                np.asarray(a.astype(jnp.float32)),
                np.asarray(b.astype(jnp.float32)))


@pytest.mark.multi_device
class TestAllReduceGradients:
    def test_grad_average(self, rng, dp_mesh):
        mesh = dp_mesh()
        grads = jnp.asarray(rng.randn(8, 4).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"))
        def f(g):
            return all_reduce_gradients({"g": g}, "dp")["g"]

        out = f(grads)
        expected = np.broadcast_to(
            np.asarray(grads).mean(0, keepdims=True), (8, 4))
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)

    def test_predivide_factor(self, rng, dp_mesh):
        mesh = dp_mesh()
        grads = jnp.asarray(rng.randn(8, 4).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"))
        def f(g):
            return all_reduce_gradients(
                {"g": g}, "dp", gradient_predivide_factor=2.0)["g"]

        out = f(grads)
        # predivide by 2, psum, then divide by world/2 -> same average
        expected = np.broadcast_to(
            np.asarray(grads).mean(0, keepdims=True), (8, 4))
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)

    def test_no_average(self, rng, dp_mesh):
        mesh = dp_mesh()
        grads = jnp.asarray(rng.randn(8, 4).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"))
        def f(g):
            return all_reduce_gradients({"g": g}, "dp",
                                        gradient_average=False)["g"]

        out = f(grads)
        expected = np.broadcast_to(
            np.asarray(grads).sum(0, keepdims=True), (8, 4))
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


@pytest.mark.multi_device
class TestBroadcastParams:
    def test_rank0_wins(self, rng, dp_mesh):
        mesh = dp_mesh()
        params = jnp.asarray(rng.randn(8, 4).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"))
        def f(p):
            return broadcast_params({"p": p}, "dp")["p"]

        out = np.asarray(f(params))
        for i in range(8):
            np.testing.assert_array_equal(out[i], np.asarray(params)[0])


@pytest.mark.multi_device
class TestDDPWrapper:
    def test_grads_are_synced(self, rng, dp_mesh):
        """DDP-wrapped loss fn: per-device grads equal the dp average
        (the reference's race-condition test checks exactly grad values,
        tests/distributed/DDP/ddp_race_condition_test.py:28-40)."""
        mesh = dp_mesh()
        w = jnp.asarray(rng.randn(4).astype(np.float32))
        x = jnp.asarray(rng.randn(8, 4).astype(np.float32))
        ddp = DistributedDataParallel(axis_name="dp")

        def loss_fn(w_, x_):
            return jnp.sum(w_ * x_)

        wrapped = ddp(loss_fn)

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), P("dp")), out_specs=P("dp"))
        def grad_fn(w_, x_):
            g = jax.grad(wrapped)(w_, x_[0])
            return g[None]

        grads = np.asarray(grad_fn(w, x))
        expected = np.asarray(x).mean(0)
        for i in range(8):
            np.testing.assert_allclose(grads[i], expected, rtol=1e-5)


class TestSyncBatchNorm:
    @pytest.mark.multi_device
    def test_matches_global_batchnorm(self, rng, dp_mesh):
        """Sync-BN over the dp axis == plain BN over the concatenated batch
        (reference tests/distributed/synced_batchnorm)."""
        mesh = dp_mesh()
        x = rng.randn(16, 6).astype(np.float32)
        xj = jnp.asarray(x)
        bn = SyncBatchNorm(use_running_average=False, axis_name="dp")
        params = bn.init(jax.random.PRNGKey(0), xj[:2])

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                           out_specs=P("dp"))
        def f(p, x_):
            y, _ = bn.apply(p, x_, mutable=["batch_stats"])
            return y

        y = np.asarray(f(params, xj))
        mean = x.mean(0)
        var = x.var(0)
        expected = (x - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(y, expected, rtol=1e-3, atol=1e-5)

    def test_single_device_fallback(self, rng):
        x = jnp.asarray(rng.randn(4, 3).astype(np.float32))
        bn = SyncBatchNorm(use_running_average=False, axis_name=None)
        params = bn.init(jax.random.PRNGKey(0), x)
        y, updates = bn.apply(params, x, mutable=["batch_stats"])
        expected = (np.asarray(x) - np.asarray(x).mean(0)) / np.sqrt(
            np.asarray(x).var(0) + 1e-5)
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4,
                                   atol=1e-5)

    def test_fuse_relu_and_z(self, rng):
        x = jnp.asarray(rng.randn(4, 3).astype(np.float32))
        z = jnp.asarray(rng.randn(4, 3).astype(np.float32))
        bn = SyncBatchNorm(use_running_average=False, axis_name=None,
                           fuse_relu=True)
        params = bn.init(jax.random.PRNGKey(0), x)
        y, _ = bn.apply(params, x, z=z, mutable=["batch_stats"])
        assert float(np.asarray(y).min()) >= 0.0

    def test_running_stats_update(self, rng):
        x = jnp.asarray(rng.randn(100, 3).astype(np.float32) * 2 + 1)
        bn = SyncBatchNorm(use_running_average=False, axis_name=None,
                           momentum=0.0)
        params = bn.init(jax.random.PRNGKey(0), x)
        _, updates = bn.apply(params, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(updates["batch_stats"]["mean"]),
                                   np.asarray(x).mean(0), rtol=1e-3)


class TestLARC:
    def test_trust_ratio_clips_update(self, rng):
        params = {"w": jnp.asarray(rng.randn(16).astype(np.float32))}
        opt = LARC(FusedSGD(lr=1.0), trust_coefficient=0.001, clip=True)
        state = opt.init(params)
        grads = {"w": jnp.asarray(rng.randn(16).astype(np.float32)) * 100}
        new_params, _ = opt.step(grads, state, params)
        # update magnitude bounded by trust_coefficient * ||p||
        delta = np.asarray(new_params["w"]) - np.asarray(params["w"])
        p_norm = np.linalg.norm(np.asarray(params["w"]))
        assert np.linalg.norm(delta) <= 0.001 * p_norm * 1.3

    def test_converges(self, rng):
        params = {"w": jnp.asarray(rng.randn(8).astype(np.float32))}
        target = jnp.asarray(rng.randn(8).astype(np.float32))
        opt = LARC(FusedSGD(lr=1.0, momentum=0.9), trust_coefficient=0.02)
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum((p["w"] - target) ** 2)

        losses = []
        for _ in range(100):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = opt.step(grads, state, params)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
