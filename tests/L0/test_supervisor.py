"""resilience.supervisor chaos suite (ISSUE 8 acceptance).

Three layers:

- policy-level unit tests over a stub step function (no mesh, no
  compile): failure classification, the step ledger's monotonicity
  proof, per-class recovery actions, restart budgets, backoff shape,
  preemption exit + resume;
- the failure matrix over the real guarded DDP+ZeRO harness
  (tools/chaos_run.py) on the 8-device CPU mesh — every failure class
  x its recovery policy, each scenario asserting its own invariants
  AND final-loss parity with the un-faulted baseline;
- the e2e acceptance: ONE supervised run taking NaN-escalation +
  synthetic OOM + torn checkpoint write + simulated preemption, zero
  manual restarts, strictly monotonic ledger, final loss equal to the
  clean run, plus the elastic world=8 -> world=4 ZeRO re-shard
  restoring bit-identical gathered state.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import checkpoint
from apex_tpu.resilience import (
    FailureClass,
    LedgerError,
    NonFiniteError,
    PreemptionGuard,
    RecoveryExhaustedError,
    RecoveryPolicy,
    StepLedger,
    Supervisor,
    classify_failure,
    faults,
)
from apex_tpu.resilience.faults import DeviceLostError
from apex_tpu.resilience.supervisor import (
    HotSnapshots,
    default_policies,
    loss_scale_backoff,
)
from apex_tpu.telemetry import MetricsRegistry, use_registry
from apex_tpu.telemetry.memory import HBMExhaustedError

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(ROOT, "tools"))
sys.path.insert(0, ROOT)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_classify_failure_routes_typed_errors():
    assert classify_failure(NonFiniteError("x")) == FailureClass.NUMERICS
    assert classify_failure(HBMExhaustedError("x")) == FailureClass.OOM
    assert classify_failure(
        faults.SyntheticResourceExhausted("RESOURCE_EXHAUSTED: x")) \
        == FailureClass.OOM
    assert classify_failure(
        checkpoint.CheckpointCorruptError("x")) == FailureClass.CHECKPOINT
    assert classify_failure(
        DeviceLostError("DEVICE_LOST: x")) == FailureClass.DEVICE_LOSS
    assert classify_failure(
        RuntimeError("DEVICE_LOST: slice dropped")) \
        == FailureClass.DEVICE_LOSS
    assert classify_failure(ValueError("boom")) == FailureClass.UNKNOWN


# ---------------------------------------------------------------------------
# the step ledger
# ---------------------------------------------------------------------------

def test_ledger_monotonic_applies_and_verify():
    led = StepLedger()
    for i in range(5):
        led.record_apply(i)
    out = led.verify(expect_next=5)
    assert out["monotonic"] and out["applies"] == 5


def test_ledger_rejects_double_apply_and_skip():
    led = StepLedger()
    led.record_apply(0)
    with pytest.raises(LedgerError, match="double-applied"):
        led.record_apply(0)
    with pytest.raises(LedgerError, match="lost"):
        led.record_apply(2)


def test_ledger_rollback_and_replay():
    led = StepLedger()
    for i in range(4):
        led.record_apply(i)
    assert led.record_rollback(2, cause="numerics") == 2  # steps lost
    for i in range(2, 6):
        led.record_apply(i)
    out = led.verify(expect_next=6)
    assert out["rollbacks"] == 1 and out["applies"] == 8


def test_ledger_rollback_bounds():
    led = StepLedger(start_step=3)
    led.record_apply(3)
    with pytest.raises(LedgerError, match="outside the lineage"):
        led.record_rollback(2)
    with pytest.raises(LedgerError, match="outside the lineage"):
        led.record_rollback(9)


def test_ledger_verify_catches_lost_lineage():
    led = StepLedger()
    led.record_apply(0)
    with pytest.raises(LedgerError, match="steps were lost"):
        led.verify(expect_next=5)


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------

def test_recovery_policy_validates_action_and_caps_backoff():
    with pytest.raises(ValueError, match="unknown action"):
        RecoveryPolicy("reboot")
    p = RecoveryPolicy("snapshot_restore", backoff_base_s=0.1,
                       backoff_cap_s=0.5)
    assert p.backoff(1) == pytest.approx(0.1)
    assert p.backoff(2) == pytest.approx(0.2)
    assert p.backoff(10) == 0.5  # capped


def test_default_policies_cover_the_matrix():
    pol = default_policies()
    assert pol[FailureClass.NUMERICS].action == "snapshot_restore"
    assert pol[FailureClass.CHECKPOINT].action == "checkpoint_restore"
    assert pol[FailureClass.DEVICE_LOSS].action == "mesh_shrink"
    assert pol[FailureClass.UNKNOWN].action == "reraise"


def test_loss_scale_backoff_hook():
    adj = loss_scale_backoff(factor=0.5, min_scale=2.0)
    st = adj({"loss_scale": np.float32(8.0)}, None)
    assert float(st["loss_scale"]) == 4.0
    st = adj({"loss_scale": np.float32(2.5)}, None)
    assert float(st["loss_scale"]) == 2.0  # floored
    assert adj({"other": 1}, None) == {"other": 1}  # no-op without key


def test_hot_snapshots_bounded_and_isolated():
    snaps = HotSnapshots(keep=2)
    for i in range(4):
        snaps.take(i, {"x": jnp.asarray(float(i))})
    assert len(snaps) == 2
    snap = snaps.latest()
    assert snap.step == 3
    copy = HotSnapshots.copy_state(snap)
    copy["x"] = None  # container edit must not touch the snapshot
    assert snaps.latest().state["x"] is not None


# ---------------------------------------------------------------------------
# supervisor over a stub step (no mesh, no compile)
# ---------------------------------------------------------------------------

def _stub_state():
    return {"x": jnp.zeros(()), "loss_scale": np.float32(8.0)}


def _stub_step(state, i):
    return {"x": state["x"] + 1, "loss_scale": state["loss_scale"]}


def _mk(step_fn, state=None, **kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("snapshot_every", 2)
    return Supervisor(step_fn, state or _stub_state(), **kw)


def test_supervisor_clean_run_applies_every_step():
    sup = _mk(_stub_step)
    rep = sup.run(5)
    assert rep["exit"] == "completed" and rep["final_step"] == 5
    assert rep["restarts"] == 0 and rep["goodput_step_ratio"] == 1.0
    assert float(sup.state["x"]) == 5
    assert rep["ledger"]["monotonic"]


def test_supervisor_numerics_snapshot_restore_and_backoff():
    fired = []

    def step(state, i):
        if i == 3 and not fired:
            fired.append(i)
            raise NonFiniteError("escalated")
        return _stub_step(state, i)

    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        sup = _mk(step, registry=reg)
        rep = sup.run(6)
    assert rep["restarts"] == 1 and rep["snapshot_restores"] == 1
    assert rep["causes"] == {"numerics": 1}
    # snapshot at 2, failure at 3 -> one step replayed
    assert rep["steps_lost"] == 1 and rep["mttr_steps"] == 1.0
    assert float(sup.state["loss_scale"]) == 4.0  # default backoff
    assert float(sup.state["x"]) == 6
    snap = reg.snapshot()
    assert snap["counters"]["recovery/restarts"] == 1
    assert snap["counters"]["recovery/cause/numerics"] == 1
    assert snap["gauges"]["recovery/mttr_steps"] == 1.0


def test_supervisor_bounded_restarts_exhaust():
    def always_bad(state, i):
        raise NonFiniteError("never recovers")

    sup = _mk(always_bad, snapshot_every=1)
    with pytest.raises(RecoveryExhaustedError, match="restart budget"):
        sup.run(3)


def test_supervisor_unknown_failure_reraises():
    def bad(state, i):
        raise ValueError("not a known class")

    sup = _mk(bad)
    with pytest.raises(ValueError, match="not a known class"):
        sup.run(2)


def test_supervisor_backoff_waits_are_capped_exponential():
    waits = []
    fired = []

    def step(state, i):
        if len(fired) < 3:
            fired.append(i)
            raise NonFiniteError("x")
        return _stub_step(state, i)

    sup = _mk(step, sleep=waits.append, snapshot_every=1,
              policies={FailureClass.NUMERICS: RecoveryPolicy(
                  "snapshot_restore", max_restarts=5,
                  backoff_base_s=0.1, backoff_cap_s=0.25)})
    sup.run(2)
    assert waits == pytest.approx([0.1, 0.2, 0.25])


def test_supervisor_torn_checkpoint_restores_last_good(tmp_path):
    """A torn periodic save is caught by post-save verification; the
    restore chain rejects the torn step, settles on the last good one,
    and the audit metadata names what was walked past."""
    sup = _mk(_stub_step, checkpoint_dir=str(tmp_path),
              checkpoint_every=2, snapshot_every=100)
    state = {"writes": 0}
    real = checkpoint._write_state

    def torn_second_write(path, host_state, use_orbax):
        state["writes"] += 1
        if state["writes"] == 2:  # the step-2 boundary save
            import json as _json
            import pickle as _pickle

            payload = _pickle.dumps(host_state)
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "state.pkl"), "wb") as f:
                f.write(payload[:24])
            manifest = checkpoint._manifest_for(host_state, "pickle")
            manifest["files"] = {"state.pkl": {
                "size": len(payload),
                "sha256": checkpoint._sha256_bytes(payload)}}
            with open(os.path.join(path, checkpoint.MANIFEST_NAME),
                      "w") as f:
                _json.dump(manifest, f)
            return
        return real(path, host_state, use_orbax)

    checkpoint._write_state = torn_second_write
    try:
        with pytest.warns(UserWarning, match="REJECTED step 2"):
            rep = sup.run(5)
    finally:
        checkpoint._write_state = real
    assert rep["checkpoint_restores"] == 1
    assert rep["causes"] == {"checkpoint_corrupt": 1}
    assert float(sup.state["x"]) == 5
    meta = sup.last_restore_meta
    assert meta["settled_step"] == 0
    assert [r["step"] for r in meta["rejected"]] == [2]


def test_supervisor_preemption_saves_and_resumes(tmp_path):
    guard = PreemptionGuard()
    hit = []

    def step(state, i):
        if i == 2 and not hit:
            hit.append(i)
            guard.trigger()
        return _stub_step(state, i)

    with guard:
        sup = _mk(step, checkpoint_dir=str(tmp_path),
                  preemption_guard=guard, snapshot_every=100)
        rep = sup.run(10)
    assert rep["exit"] == "preempted" and rep["final_step"] == 3
    assert rep["causes"] == {"preemption": 1}
    # "new process": restore + finish
    sup2 = _mk(_stub_step, state=_stub_state(),
               checkpoint_dir=str(tmp_path))
    meta = sup2.restore_from_checkpoint()
    assert meta["settled_step"] == 3
    rep2 = sup2.run(10)
    assert rep2["exit"] == "completed"
    assert float(sup2.state["x"]) == 10
    assert rep2["ledger"]["start_step"] == 3


def test_supervisor_device_loss_mesh_shrink():
    def make_step(world):
        def step(state, i):
            if world == 8 and i == 3:
                raise DeviceLostError("DEVICE_LOST: injected",
                                      shrink_to=4)
            return _stub_step(state, i)
        return step

    rebuilds = []

    def rebuild(world, host_state, step):
        rebuilds.append((world, step))
        return make_step(world), host_state

    sup = _mk(make_step(8), rebuild=rebuild, world=8,
              topology={"world": 8})
    rep = sup.run(6)
    assert rep["mesh_shrinks"] == 1 and rep["world"] == 4
    assert rebuilds == [(4, 2)]  # snapshot cadence 2 -> resume step 2
    assert sup.topology["world"] == 4
    assert float(sup.state["x"]) == 6


def test_supervisor_snapshot_ok_gates_cadence():
    taken = []

    def step(state, i):
        return _stub_step(state, i)

    sup = _mk(step, snapshot_every=1,
              snapshot_ok=lambda st: float(st["x"]) >= 2)
    sup.snapshots.take = lambda s, st, w=None: taken.append(s)
    sup.run(5)
    assert taken == [2, 3, 4]  # states 0 and 1 rejected by the gate


# ---------------------------------------------------------------------------
# the failure matrix over the real guarded DDP+ZeRO harness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def chaos_clean():
    """The un-faulted baseline every scenario compares against (module
    scope: the clean run compiles the step once for the whole
    matrix)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from tools.chaos_run import run_scenario

    return run_scenario("clean", steps=12, world=8, hidden=16)


@pytest.mark.multi_device
@pytest.mark.parametrize("scenario", ["nan", "oom", "ckpt_torn",
                                      "preempt", "device_loss"])
def test_failure_matrix(scenario, chaos_clean, tmp_path, monkeypatch):
    """Every failure class x its recovery policy on the 8-device mesh:
    the scenario's own invariants (exactly-one restore of the right
    kind, audit metadata, world shrink, ...) plus final-loss parity
    with the clean run — all asserted inside run_scenario."""
    from tools.chaos_run import run_scenario

    monkeypatch.setenv("APEX_TPU_MEMORY_DIR", str(tmp_path))
    out = run_scenario(scenario, steps=12, world=8, hidden=16,
                       ckpt_dir=str(tmp_path / "ckpt"),
                       clean_report=chaos_clean)
    assert out["violations"] == []
    assert out["report"]["ledger"]["monotonic"]


@pytest.mark.multi_device
def test_chaos_e2e_acceptance(tmp_path, monkeypatch):
    """ISSUE-8 acceptance: one supervised DDP+ZeRO run under
    NaN-escalation + synthetic OOM + torn checkpoint write + simulated
    preemption — every class recovered automatically (zero manual
    restarts: nothing escapes the supervisor), the step ledger
    strictly monotonic with no silent loss, the final loss EQUAL to
    the un-faulted run (snapshot replay is bit-exact), and the
    world=8 ZeRO state restoring bit-identically onto world=4."""
    from tools.chaos_run import run_acceptance

    monkeypatch.setenv("APEX_TPU_MEMORY_DIR", str(tmp_path))
    with pytest.warns(UserWarning, match="REJECTED step 12"):
        out = run_acceptance(steps=18, world=8, hidden=16,
                             ckpt_dir=str(tmp_path / "ckpt"))
    assert out["violations"] == []
    assert out["exit_chain"] == ["preempted", "completed"]
    assert out["cause_histogram"] == {
        "numerics": 1, "oom": 1, "checkpoint_corrupt": 1,
        "preemption": 1}
    assert out["restarts"] == 3          # nan + oom + torn, all automatic
    assert out["final_loss_delta"] == 0.0
    assert out["reshard_bitexact"]
    assert 0 < out["goodput_step_ratio"] <= 1


@pytest.mark.multi_device
def test_bench_ddp_recovery_contract(capsys, tmp_path, monkeypatch):
    """The round-13 bench contract: ddp_recovery emits restarts /
    mttr_steps / snapshot_restores / goodput_step_ratio /
    final_loss_delta and passes the round-13 schema gate."""
    import json

    import bench
    import bench_schema_check as schema

    monkeypatch.setenv("APEX_TPU_MEMORY_DIR", str(tmp_path))
    ret = bench.bench_ddp_recovery(16, 18, hidden=16)
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["metric"] == "ddp_recovery_steps_per_sec"
    assert schema.check_metric_line(dict(line), round_n=13,
                                    errors=[]) == []
    msgs = schema.check_metric_line(dict(line), round_n=12, errors=[])
    assert any("only defined" in m for m in msgs)
    assert ret["restarts"] >= 3
    assert ret["reshard_bitexact"] is True
    assert 0 < ret["goodput_step_ratio"] <= 1
