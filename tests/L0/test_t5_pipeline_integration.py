"""T5Model riding the split-rank pipeline schedule (pp=2, split=1).

The round-3 split-rank schedule was verified with a standalone test
vehicle; this closes the loop with the REAL model family: the full
T5Model (relative-position bias buckets, RMS norms, cross-attention,
tied head) as the pipeline's encoder/decoder stages, with loss and
gradient parity against the unpipelined two-program composition
(encode with rank 0's params, decode with rank 1's).

Reference: ModelType.encoder_and_decoder pipelines in
apex/transformer/pipeline_parallel/schedules/fwd_bwd_pipelining_without_interleaving.py:29-86
driven by T5-shaped models (tests/L0/run_transformer/).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.testing import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.t5 import T5Config, T5Model, t5_loss_fn
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_with_split,
    make_encoder_decoder_step,
)

M = 2   # microbatches
B = 2   # microbatch size
ENC_S, DEC_S = 6, 5


@pytest.fixture
def cfg():
    return T5Config(
        vocab_size=32, d_model=32, d_kv=16, d_ff=48, num_layers=1,
        num_decoder_layers=1, num_heads=2,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=10,
        compute_dtype=jnp.float32)


@pytest.mark.slow
def test_t5_model_split_pipeline_matches_two_program_composition(cfg):
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    parallel_state.destroy_model_parallel()
    rng = np.random.RandomState(0)
    mbs = {
        "enc_tokens": jnp.asarray(rng.randint(0, 32, (M, B, ENC_S))),
        "dec_tokens": jnp.asarray(rng.randint(0, 32, (M, B, DEC_S))),
        "dec_targets": jnp.asarray(rng.randint(0, 32, (M, B, DEC_S))),
    }
    model = T5Model(cfg)
    params = [
        model.init(jax.random.PRNGKey(r),
                   mbs["enc_tokens"][0], mbs["dec_tokens"][0])["params"]
        for r in range(2)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params)

    # -- unpipelined oracle: encode with rank0 params, decode with rank1
    def ref_total(stacked_):
        p0 = jax.tree_util.tree_map(lambda a: a[0], stacked_)
        p1 = jax.tree_util.tree_map(lambda a: a[1], stacked_)
        losses = []
        for m in range(M):
            memory = model.apply({"params": p0}, mbs["enc_tokens"][m],
                                 method=T5Model.encode)
            logits = model.apply({"params": p1}, mbs["dec_tokens"][m],
                                 memory, method=T5Model.decode_from_memory)
            losses.append(t5_loss_fn(logits, mbs["dec_targets"][m]))
        return sum(losses) / M, jnp.stack(losses)

    (_, ref_losses), ref_grads = jax.value_and_grad(
        ref_total, has_aux=True)(stacked)

    # -- pipelined run
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("pp",))
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2,
        pipeline_model_parallel_split_rank_=1,
        devices=jax.devices()[:2])

    def enc_fn(p, h, mb, is_first):
        del h, is_first  # single encoder stage: always embeds
        return model.apply({"params": p}, mb["enc_tokens"],
                           method=T5Model.encode)

    def dec_fn(p, h, memory, mb, is_split):
        del h, is_split  # single decoder stage: always embeds
        return model.apply({"params": p}, mb["dec_tokens"], memory,
                           method=T5Model.decode_hidden)

    step = make_encoder_decoder_step(enc_fn, dec_fn)

    def loss_func(p, payload, mb):
        logits = model.apply({"params": p}, payload["decoder"],
                             method=T5Model.head)
        return t5_loss_fn(logits, mb["dec_targets"])

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("pp"), P()), out_specs=(P("pp"), P("pp")))
    def run(p_stage, mbs_):
        p = jax.tree_util.tree_map(lambda a: a[0], p_stage)
        losses, grads = forward_backward_pipelining_with_split(
            step, loss_func, p, mbs_, num_microbatches=M,
            encoder_tensor_shape=(ENC_S, B, cfg.d_model),
            decoder_tensor_shape=(DEC_S, B, cfg.d_model),
            dtype=jnp.float32, pp_size=2)
        grads = jax.tree_util.tree_map(lambda a: a[None], grads)
        return losses[None], grads

    losses, grads = jax.jit(run)(stacked, mbs)
    parallel_state.destroy_model_parallel()

    np.testing.assert_allclose(np.asarray(losses)[1], np.asarray(ref_losses),
                               rtol=1e-4, atol=1e-5)
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(ref_grads)
    flat_got = dict(jax.tree_util.tree_flatten_with_path(grads)[0])
    for path, ref_leaf in flat_ref:
        np.testing.assert_allclose(
            np.asarray(flat_got[path]), np.asarray(ref_leaf),
            rtol=2e-3, atol=1e-4, err_msg=jax.tree_util.keystr(path))
