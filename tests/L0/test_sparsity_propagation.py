"""ASP cross-layer permutation propagation (reference
apex/contrib/sparsity/permutation_lib.py fx-walk parity).

End-to-end contract per the reference: after propagating a found channel
permutation across producer/consumer pairs, (a) the network function is
UNCHANGED (same logits up to dtype rounding), and (b) the magnitude
retained by the 2:4 mask on the searched weights improves vs no
permutation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.sparsity.propagation import (
    PermSpec,
    PermutationGroup,
    gpt_permutation_groups,
    propagate_permutations,
    resnet_permutation_groups,
    t5_permutation_groups,
)
from apex_tpu.transformer import parallel_state


@pytest.fixture(autouse=True)
def _single_device():
    parallel_state.destroy_model_parallel()
    yield
    parallel_state.destroy_model_parallel()


def _assert_improved(report):
    total_before = sum(r["kept_before"] for r in report.values())
    total_after = sum(r["kept_after"] for r in report.values())
    assert total_after > total_before, report
    moved = [n for n, r in report.items()
             if not np.array_equal(r["perm"], np.arange(len(r["perm"])))]
    assert moved, "no group found a non-identity permutation"


@pytest.mark.parametrize("activation", ["gelu", "swiglu"])
def test_gpt_propagation_preserves_function_and_improves_kept(activation):
    from apex_tpu.models import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=32, ffn_hidden_size=64,
        activation=activation,
        normalization="rmsnorm" if activation == "swiglu" else "layernorm",
        compute_dtype=jnp.float32, use_flash_attention=False)
    model = GPTModel(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
    variables = model.init(jax.random.PRNGKey(0), tokens)
    ref = model.apply(variables, tokens)

    groups = gpt_permutation_groups(cfg, variables)
    assert len(groups) == 2
    permuted, report = propagate_permutations(variables, groups)
    _assert_improved(report)

    out = model.apply(permuted, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_t5_propagation_preserves_function_and_improves_kept():
    from apex_tpu.models import T5Config, T5Model

    cfg = T5Config(vocab_size=48, d_model=32, d_kv=8, d_ff=64,
                   num_layers=1, num_decoder_layers=1, num_heads=4,
                   feed_forward_proj="gated-gelu", tie_word_embeddings=False,
                   compute_dtype=jnp.float32)
    model = T5Model(cfg)
    rng = np.random.RandomState(1)
    enc = jnp.asarray(rng.randint(0, 48, (2, 6)))
    dec = jnp.asarray(rng.randint(0, 48, (2, 5)))
    variables = model.init(jax.random.PRNGKey(1), enc, dec)
    ref = model.apply(variables, enc, dec)

    groups = t5_permutation_groups(cfg, variables)
    assert len(groups) == 2  # enc block + dec block
    # gated-gelu: wi_0/wi_1 jointly searched
    assert sum(s.search for s in groups[0].specs) == 2
    permuted, report = propagate_permutations(variables, groups)
    _assert_improved(report)

    out = model.apply(permuted, enc, dec)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_resnet_propagation_with_batch_stats():
    """Bottleneck interior chains (conv -> BN -> relu -> conv) permute
    with running statistics in tow; eval-mode outputs unchanged."""
    from apex_tpu.models import ResNet
    from apex_tpu.models.resnet import BottleneckBlock

    model = ResNet(stage_sizes=[1], block_cls=BottleneckBlock,
                   num_classes=10, num_filters=16, dtype=jnp.float32,
                   train=False)
    x = jnp.asarray(np.random.RandomState(2).randn(2, 32, 32, 3),
                    jnp.float32)
    variables = model.init(jax.random.PRNGKey(2), x)
    # randomize running stats so a wrong/missing stats permutation shows
    bs = jax.tree_util.tree_map(
        lambda a: a + jnp.asarray(
            np.random.RandomState(3).uniform(0.1, 0.5, a.shape), a.dtype),
        variables["batch_stats"])
    variables = {"params": variables["params"], "batch_stats": bs}
    ref = model.apply(variables, x)

    groups = resnet_permutation_groups(variables)
    # one bottleneck block: Conv_0->Conv_1 and Conv_1->Conv_2
    assert len(groups) == 2
    stats_paths = [s.path for g in groups for s in g.specs
                   if s.path[0] == "batch_stats"]
    assert stats_paths, "running stats must be co-permuted"
    permuted, report = propagate_permutations(variables, groups)
    _assert_improved(report)

    out = model.apply(permuted, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_mask_retention_improves_end_to_end():
    """The full ASP story: propagated permutation -> compute_sparse_masks
    -> retained magnitude on the producer weights beats the unpermuted
    masks (the entire point of the NeurIPS'21 method)."""
    from apex_tpu.contrib.sparsity import ASP
    from apex_tpu.models import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=32, ffn_hidden_size=64,
        compute_dtype=jnp.float32, use_flash_attention=False)
    model = GPTModel(cfg)
    tokens = jnp.asarray(np.random.RandomState(4).randint(0, 64, (2, 8)))
    variables = model.init(jax.random.PRNGKey(4), tokens)

    def kept(vars_):
        ASP.init_model_for_pruning(vars_["params"])
        masks = ASP.compute_sparse_masks(vars_["params"])
        pruned = ASP.apply_masks(vars_["params"], masks)
        pw = pruned["transformer"]["layer_0"]["mlp"][
            "dense_h_to_4h"]["weight"]
        return float(jnp.sum(jnp.abs(pw)))

    base = kept(variables)
    permuted, _ = propagate_permutations(
        variables, gpt_permutation_groups(cfg, variables))
    assert kept(permuted) > base


def test_gated_regions_follow_local_shard_width():
    """Packed [gate | up] regions come from the LEAF's width, not the
    global cfg.ffn_size — a tp shard holds 2*ffn/tp columns and a global
    region would straddle its gate/up boundary."""
    from apex_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=32, ffn_hidden_size=64,
        activation="swiglu", normalization="rmsnorm")
    # simulate one tp=2 rank: packed width 2*ffn/tp = 64
    variables = {"params": {"transformer": {"layer_0": {"mlp": {
        "dense_h_to_4h": {"weight": jnp.zeros((32, 64))},
        "dense_4h_to_h": {"weight": jnp.zeros((32, 32))},
    }}}}}
    (group,) = gpt_permutation_groups(cfg, variables)
    regions = [s.region for s in group.specs if s.search]
    assert regions == [(0, 32), (32, 32)]


def test_unknown_group_validation():
    with pytest.raises(ValueError, match="no search tensors"):
        propagate_permutations(
            {"params": {}},
            [PermutationGroup("bad", (PermSpec(("params",), 0),))])


@pytest.mark.parametrize("pos", ["learned", "rope"])
def test_gpt_attention_propagation_preserves_function(pos):
    """Per-head V-channel groups (plus joint Q/K where RoPE doesn't pin
    channels): outputs unchanged, retention improves, and the group set
    composes with the MLP groups."""
    from apex_tpu.contrib.sparsity.propagation import (
        gpt_attention_permutation_groups,
    )
    from apex_tpu.models import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=64, num_layers=1, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=32, ffn_hidden_size=64,
        position_embedding_type=pos,
        normalization="rmsnorm" if pos == "rope" else "layernorm",
        compute_dtype=jnp.float32, use_flash_attention=False)
    model = GPTModel(cfg)
    tokens = jnp.asarray(np.random.RandomState(5).randint(0, 64, (2, 8)))
    variables = model.init(jax.random.PRNGKey(5), tokens)
    ref = model.apply(variables, tokens)

    groups = gpt_attention_permutation_groups(cfg, variables)
    v_groups = [g for g in groups if "attn_v" in g.name]
    qk_groups = [g for g in groups if "attn_qk" in g.name]
    assert len(v_groups) == 4  # one per head
    assert len(qk_groups) == (0 if pos == "rope" else 4)

    groups = groups + gpt_permutation_groups(cfg, variables)
    permuted, report = propagate_permutations(variables, groups)
    _assert_improved(report)

    out = model.apply(permuted, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gpt_attention_groups_refuse_gqa():
    from apex_tpu.contrib.sparsity.propagation import (
        gpt_attention_permutation_groups,
    )
    from apex_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        hidden_size=64, num_layers=1, num_attention_heads=4,
        num_query_groups=2, vocab_size=64, max_position_embeddings=32,
        position_embedding_type="rope", normalization="rmsnorm",
        activation="swiglu")
    with pytest.raises(ValueError, match="MHA only"):
        gpt_attention_permutation_groups(cfg, {"params": {}})
