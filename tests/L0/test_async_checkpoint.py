"""Async overlapped checkpointing (AsyncCheckpointer).

Contract (VERDICT r3 item 9): training steps proceed while a checkpoint
is landing, and the landed checkpoint resumes to exactly the state at
save time — snapshot isolation against both later parameter updates and
buffer donation.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.checkpoint import AsyncCheckpointer, restore


def _train_state(seed=0):
    k = jax.random.PRNGKey(seed)
    return {"w": jax.random.normal(k, (64, 64)),
            "step": jnp.zeros((), jnp.int32)}


@jax.jit
def _step(state, x):
    g = x @ state["w"]
    return {"w": state["w"] - 1e-2 * jnp.mean(g) * jnp.ones_like(state["w"]),
            "step": state["step"] + 1}


def test_save_returns_before_write_and_steps_overlap(tmp_path):
    """The background write is gated open by the test; steps run to
    completion while the checkpoint is still in flight."""
    gate = threading.Event()
    # timeout: an assertion failure before gate.set() must fail the
    # test, not hang the non-daemon worker forever
    ck = AsyncCheckpointer(use_orbax=False,
                           _pre_write_hook=lambda: gate.wait(60))
    state = _train_state()
    x = jnp.ones((8, 64))

    t0 = time.perf_counter()
    ck.save(str(tmp_path), 0, state)
    t_save = time.perf_counter() - t0
    # returned without writing (the gate is still closed)
    assert not (tmp_path / "step_0000000000" / "state.pkl").exists()
    assert t_save < 5.0

    for _ in range(5):  # training continues while the write is blocked
        state = _step(state, x)
    assert int(state["step"]) == 5

    gate.set()
    ck.wait_until_finished()
    assert (tmp_path / "step_0000000000" / "state.pkl").exists()
    restored = restore(str(tmp_path))
    assert int(restored["step"]) == 0  # snapshot at save time, not 5
    ck.close()


def test_snapshot_isolated_from_donation(tmp_path):
    """A donated-buffer update right after save must not corrupt the
    in-flight checkpoint (the D2H snapshot happens before save returns)."""
    donate = jax.jit(lambda s: jax.tree_util.tree_map(lambda a: a * 0 - 7.0,
                                                      s),
                     donate_argnums=0)
    gate = threading.Event()
    ck = AsyncCheckpointer(use_orbax=False,
                           _pre_write_hook=lambda: gate.wait(60))
    state = {"w": jnp.arange(16.0)}
    ck.save(str(tmp_path), 3, state)
    state = donate(state)  # invalidates the old device buffers
    gate.set()
    ck.wait_until_finished()
    restored = restore(str(tmp_path), step=3)
    np.testing.assert_array_equal(restored["w"], np.arange(16.0))
    ck.close()


def test_resume_parity_with_blocking_path(tmp_path):
    """Async and blocking saves are interchangeable on disk."""
    state = _train_state(seed=5)
    with AsyncCheckpointer(use_orbax=False) as ck:
        ck.save(str(tmp_path), 7, state)
    restored = restore(str(tmp_path), step=7)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.asarray(state["w"]))


def test_single_inflight_and_error_propagation(tmp_path):
    calls = []

    def boom():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("disk full")

    ck = AsyncCheckpointer(use_orbax=False, _pre_write_hook=boom)
    ck.save(str(tmp_path), 0, {"a": jnp.ones(4)})
    with pytest.raises(RuntimeError, match="disk full"):
        ck.save(str(tmp_path), 1, {"a": jnp.ones(4)})  # joins previous
    # the failed future is consumed; a fresh save works
    ck.save(str(tmp_path), 2, {"a": jnp.ones(4)})
    ck.wait_until_finished()
    assert restore(str(tmp_path), step=2)["a"].shape == (4,)
    ck.close()


def test_orbax_async_roundtrip(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    state = {"w": jnp.full((8,), 2.5), "n": jnp.asarray(3)}
    with AsyncCheckpointer(use_orbax=True) as ck:
        ck.save(str(tmp_path), 11, state)
    restored = restore(str(tmp_path), step=11)
    np.testing.assert_allclose(np.asarray(restored["w"]), 2.5)
