"""tools/bench_trend — the cross-round regression gate (ROADMAP item
5 trend slice, ISSUE 11 satellite): consecutive BENCH_rNN.json rounds
of the same config are compared, and rate drops / comm-bytes growth
beyond a per-config noise band — or ANY compile-count growth — fail
loudly. bench_error rounds and cross-backend pairs are skipped, never
compared. Also covers the ``telemetry_report --trend`` wiring."""

import io
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import bench_trend  # noqa: E402
import telemetry_report  # noqa: E402


def _wrap(n, metric="gpt2_345m_tokens_per_sec_per_chip", value=100.0,
          comm=1000, compiles=1, backend="cpu-mesh", **extra):
    parsed = {"metric": metric, "value": value, "unit": "tokens/sec",
              "vs_baseline": 1.0, "tflops_per_sec": 1.0, "mfu": 0.1,
              "comm_bytes_per_step": comm, "compile_count": compiles,
              "backend": backend}
    parsed.update(extra)
    return {"n": n, "cmd": f"python bench.py x  # r{n}", "rc": 0,
            "tail": "", "parsed": parsed}


def _error_wrap(n):
    return {"n": n, "cmd": "python bench.py x", "rc": 2, "tail": "",
            "parsed": {"metric": "bench_error", "value": 0,
                       "unit": "error", "vs_baseline": 0.0,
                       "kind": "wedge", "comm_bytes_per_step": None}}


def _write(tmp_path, wrappers):
    for w in wrappers:
        (tmp_path / f"BENCH_r{w['n']:02d}.json").write_text(
            json.dumps(w))
    return str(tmp_path)


def _trend(tmp_path, wrappers, **kw):
    d = _write(tmp_path, wrappers)
    return bench_trend.build_trend(bench_trend.load_rounds([d]), **kw)


class TestTrendGate:
    def test_flat_series_passes(self, tmp_path):
        t = _trend(tmp_path, [_wrap(16, value=100.0),
                              _wrap(17, value=98.0),
                              _wrap(18, value=103.0)])
        assert t["regressions"] == []
        rounds = t["configs"]["gpt2_345m_tokens_per_sec_per_chip"]["rounds"]
        assert [r["n"] for r in rounds] == [16, 17, 18]

    def test_rate_drop_beyond_band_fails_loudly(self, tmp_path):
        t = _trend(tmp_path, [_wrap(16, value=100.0),
                              _wrap(17, value=50.0)])
        (g,) = t["regressions"]
        assert g["field"] == "value"
        assert g["round_a"] == 16 and g["round_b"] == 17
        assert g["delta_pct"] == -50.0
        assert "band" in g["kind"]

    def test_drop_within_band_is_noise(self, tmp_path):
        t = _trend(tmp_path, [_wrap(16, value=100.0),
                              _wrap(17, value=80.0)])  # -20% < 25%
        assert t["regressions"] == []

    def test_comm_bytes_growth_fails(self, tmp_path):
        t = _trend(tmp_path, [_wrap(16, comm=1000),
                              _wrap(17, comm=2000)])
        (g,) = t["regressions"]
        assert g["field"] == "comm_bytes_per_step"
        assert "comm bytes grew" in g["kind"]

    def test_any_compile_count_growth_fails(self, tmp_path):
        """Compile counts are exact — +1 compile is a regression even
        though +1 value would be far inside any band."""
        t = _trend(tmp_path, [_wrap(16, compiles=9),
                              _wrap(17, compiles=10)])
        (g,) = t["regressions"]
        assert g["field"] == "compile_count"
        assert g["old"] == 9 and g["new"] == 10
        # shrinking the ladder is NOT a regression
        t = _trend(tmp_path, [_wrap(16, compiles=9),
                              _wrap(17, compiles=8)])
        assert t["regressions"] == []

    def test_bench_error_rounds_are_skipped_not_compared(self, tmp_path):
        """r17 wedged: r16 -> r18 still compares (and catches the
        drop); the error round shows in the counts, not the series."""
        t = _trend(tmp_path, [_wrap(16, value=100.0), _error_wrap(17),
                              _wrap(18, value=40.0)])
        assert t["rounds_seen"] == 3
        assert t["rounds_successful"] == 2
        (g,) = t["regressions"]
        assert (g["round_a"], g["round_b"]) == (16, 18)

    def test_backend_switch_skips_the_pair(self, tmp_path):
        """cpu-mesh and tpu are different perf series: a 10x 'drop'
        crossing the boundary is not a regression; the next same-
        backend pair compares again."""
        t = _trend(tmp_path, [_wrap(16, value=1000.0, backend="tpu"),
                              _wrap(17, value=100.0,
                                    backend="cpu-mesh"),
                              _wrap(18, value=40.0,
                                    backend="cpu-mesh")])
        cfg = t["configs"]["gpt2_345m_tokens_per_sec_per_chip"]
        assert len(cfg["skipped"]) == 1
        assert "backend switch" in cfg["skipped"][0]["reason"]
        (g,) = t["regressions"]
        assert (g["round_a"], g["round_b"]) == (17, 18)

    def test_configs_tracked_independently(self):
        t = bench_trend.build_trend([
            {"file": "x", "n": 16,
             "parsed": _wrap(16, metric="a_steps_per_sec",
                             value=10.0)["parsed"]},
            {"file": "x", "n": 17,
             "parsed": _wrap(17, metric="a_steps_per_sec",
                             value=2.0)["parsed"]},
            {"file": "x", "n": 16,
             "parsed": _wrap(16, metric="serve_fleet_tokens_per_sec",
                             value=100.0)["parsed"]},
            {"file": "x", "n": 17,
             "parsed": _wrap(17, metric="serve_fleet_tokens_per_sec",
                             value=95.0)["parsed"]},
        ])
        assert [g["metric"] for g in t["regressions"]] == \
            ["a_steps_per_sec"]

    def test_per_metric_band_is_config_calibrated(self, tmp_path):
        """The serving configs carry a wider default band (wall-clock
        TTFT swings); a -30% serving drop is noise while the same drop
        on a training config is a regression."""
        t = _trend(tmp_path, [
            _wrap(16, metric="serve_fleet_tokens_per_sec", value=100.0),
            _wrap(17, metric="serve_fleet_tokens_per_sec", value=70.0)])
        assert t["regressions"] == []
        t = _trend(tmp_path, [_wrap(16, value=100.0),
                              _wrap(17, value=70.0)])
        assert [g["field"] for g in t["regressions"]] == ["value"]
        # explicit override wins over the table
        t = _trend(tmp_path, [
            _wrap(16, metric="serve_fleet_tokens_per_sec", value=100.0),
            _wrap(17, metric="serve_fleet_tokens_per_sec", value=70.0)],
            bands={"serve_fleet_tokens_per_sec": 0.1})
        assert [g["field"] for g in t["regressions"]] == ["value"]


class TestTrendCLI:
    def test_cli_exit_codes_and_loud_lines(self, tmp_path, capsys):
        _write(tmp_path, [_wrap(16, value=100.0),
                          _wrap(17, value=10.0)])
        rc = bench_trend.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "TREND REGRESSION" in out
        assert "gpt2_345m_tokens_per_sec_per_chip" in out

    def test_cli_clean_and_json(self, tmp_path, capsys):
        _write(tmp_path, [_wrap(16), _wrap(17)])
        assert bench_trend.main([str(tmp_path)]) == 0
        capsys.readouterr()
        assert bench_trend.main([str(tmp_path), "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["regressions"] == []

    def test_cli_band_override(self, tmp_path, capsys):
        _write(tmp_path, [_wrap(16, value=100.0),
                          _wrap(17, value=90.0)])
        assert bench_trend.main([str(tmp_path)]) == 0
        capsys.readouterr()
        assert bench_trend.main([str(tmp_path), "--band", "0.05"]) == 1
        capsys.readouterr()
        assert bench_trend.main(
            [str(tmp_path), "--band-for",
             "gpt2_345m_tokens_per_sec_per_chip=0.05"]) == 1

    def test_repo_root_records_pass(self, capsys):
        """The checked-in BENCH_r01-r06 records (all bench_error) must
        not trip the gate — errors are skipped, not compared."""
        assert bench_trend.main([ROOT]) == 0

    def test_render_marks_gaps(self, tmp_path):
        t = _trend(tmp_path, [_wrap(16, value=100.0), _error_wrap(17)])
        buf = io.StringIO()
        bench_trend.render(t, out=buf)
        assert "1/2 round(s)" in buf.getvalue()


class TestTelemetryReportTrendWiring:
    def test_report_trend_flag(self, tmp_path, capsys):
        """telemetry_report --trend DIR appends the cross-round trend
        table (and embeds it under --json)."""
        tel = tmp_path / "tel"
        tel.mkdir()
        (tel / "telemetry-rank0.jsonl").write_text(
            json.dumps({"kind": "summary", "counters": {},
                        "gauges": {}, "histograms": {}}) + "\n")
        bdir = tmp_path / "bench"
        bdir.mkdir()
        _write(bdir, [_wrap(16, value=100.0), _wrap(17, value=10.0)])
        rc = telemetry_report.main([str(tel), "--trend", str(bdir)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "bench trend" in out
        assert "REGRESSION" in out
        rc = telemetry_report.main([str(tel), "--json",
                                    "--trend", str(bdir)])
        report = json.loads(capsys.readouterr().out)
        assert report["trend"]["regressions"]

    def test_report_without_trend_unchanged(self, tmp_path, capsys):
        (tmp_path / "telemetry-rank0.jsonl").write_text(
            json.dumps({"kind": "summary", "counters": {},
                        "gauges": {}, "histograms": {}}) + "\n")
        assert telemetry_report.main([str(tmp_path)]) == 0
        assert "bench trend" not in capsys.readouterr().out


@pytest.mark.parametrize("bad", ["not json", '["list"]', '{"x": 1}'])
def test_unreadable_records_are_skipped(tmp_path, bad):
    (tmp_path / "BENCH_r16.json").write_text(bad)
    records = bench_trend.load_rounds([str(tmp_path)])
    assert records == []
