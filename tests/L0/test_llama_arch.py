"""Modern-LLM architecture knobs: RoPE, GQA, SwiGLU, RMSNorm.

Beyond the reference (GPT-2/BERT-era standalone models); these knobs make
the same parallel transformer stack cover Llama-family configs with the
existing TP/SP/pipeline machinery. Numerics vs hand computations, then a
full llama-style GPT through the 3D-parallel harness.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.transformer_lm import (
    ParallelAttention,
    ParallelMLP,
    TransformerConfig,
    apply_rotary_emb,
)
from apex_tpu.transformer import parallel_state


def _cfg(**kw):
    base = dict(hidden_size=32, num_layers=2, num_attention_heads=4,
                vocab_size=64, max_position_embeddings=32,
                compute_dtype=jnp.float32, use_flash_attention=False)
    base.update(kw)
    return TransformerConfig(**base)


class TestRotary:
    def test_preserves_norm(self):
        x = jnp.asarray(np.random.RandomState(0).randn(8, 2, 4, 16),
                        jnp.float32)
        r = apply_rotary_emb(x)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(r), axis=-1), rtol=1e-5)

    def test_relative_position_property(self):
        """q_i . k_j after rotation depends only on (i - j)."""
        rng = np.random.RandomState(1)
        d = 16
        q1 = jnp.asarray(np.tile(rng.randn(1, 1, 1, d), (8, 1, 1, 1)),
                         jnp.float32)
        k1 = jnp.asarray(np.tile(rng.randn(1, 1, 1, d), (8, 1, 1, 1)),
                         jnp.float32)
        qr, kr = apply_rotary_emb(q1), apply_rotary_emb(k1)
        qr, kr = np.asarray(qr)[:, 0, 0], np.asarray(kr)[:, 0, 0]
        # same offset, different absolute positions
        d1 = qr[3] @ kr[1]
        d2 = qr[6] @ kr[4]
        np.testing.assert_allclose(d1, d2, rtol=1e-4)

    def test_position_zero_identity(self):
        x = jnp.asarray(np.random.RandomState(2).randn(1, 2, 3, 8),
                        jnp.float32)
        np.testing.assert_allclose(np.asarray(apply_rotary_emb(x)),
                                   np.asarray(x), atol=1e-6)

    def test_per_batch_positions(self):
        """[s, b] positions (packed documents): column b rotates by its
        own indices, matching a per-column [s] call."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(6, 2, 2, 8), jnp.float32)
        pos = jnp.asarray([[0, 0], [1, 1], [2, 0], [3, 1], [4, 2], [5, 3]])
        out = apply_rotary_emb(x, positions=pos)
        for col in range(2):
            ref = apply_rotary_emb(x[:, col:col + 1], positions=pos[:, col])
            np.testing.assert_allclose(np.asarray(out[:, col:col + 1]),
                                       np.asarray(ref), rtol=1e-6)

    def test_gpt_rope_uses_position_ids(self):
        """GPTModel threads position_ids into rotary attention: shifting
        them changes the logits (they are not silently ignored)."""
        from apex_tpu.models import GPTModel

        parallel_state.destroy_model_parallel()
        cfg = _cfg(position_embedding_type="rope")
        model = GPTModel(cfg)
        tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        base = model.apply({"params": params}, tokens)
        shifted = model.apply({"params": params}, tokens,
                              jnp.arange(8)[None, :] + 3)
        assert not np.allclose(np.asarray(base), np.asarray(shifted))


class TestGQA:
    def test_gqa_attention_matches_manual(self):
        """GQA ParallelAttention output == hand-computed attention with
        each K/V group broadcast to its query heads. The fused projection
        lays columns out as [q heads | kv groups]."""
        parallel_state.destroy_model_parallel()
        cfg = _cfg(num_query_groups=2)
        attn = ParallelAttention(cfg)
        s, b, h = 8, 2, cfg.hidden_size
        x = jnp.asarray(np.random.RandomState(0).randn(s, b, h), jnp.float32)
        params = attn.init(jax.random.PRNGKey(0), x)["params"]
        out = attn.apply({"params": params}, x)

        kv = cfg.kv_channels
        proj = (np.asarray(x) @ np.asarray(params["query_key_value"]["weight"])
                + np.asarray(params["query_key_value"]["bias"]))
        q = proj[..., :4 * kv].reshape(s, b, 4, kv)
        kvp = proj[..., 4 * kv:].reshape(s, b, 2, 2 * kv)
        k, v = kvp[..., :kv], kvp[..., kv:]
        k = np.repeat(k, 2, axis=2)
        v = np.repeat(v, 2, axis=2)
        scores = np.einsum("sbnd,tbnd->bnst", q, k) / np.sqrt(kv)
        mask = np.triu(np.full((s, s), -np.inf), k=1)
        probs = jax.nn.softmax(jnp.asarray(scores + mask), axis=-1)
        ctx = np.einsum("bnst,tbnd->sbnd", np.asarray(probs), v)
        ref = (ctx.reshape(s, b, h) @ np.asarray(params["dense"]["weight"])
               + np.asarray(params["dense"]["bias"]))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_mha_default_unchanged_param_structure(self):
        parallel_state.destroy_model_parallel()
        attn = ParallelAttention(_cfg())
        x = jnp.ones((4, 1, 32))
        params = attn.init(jax.random.PRNGKey(0), x)["params"]
        assert "query_key_value" in params  # fused path preserved

    def test_bad_gqa_config_raises(self):
        import pytest

        with pytest.raises(ValueError, match="num_query_groups"):
            _cfg(num_query_groups=3)  # 4 heads not divisible by 3
        with pytest.raises(ValueError, match="num_query_groups"):
            _cfg(num_query_groups=8)  # more groups than heads

    def test_bad_position_embedding_type_raises(self):
        import pytest

        with pytest.raises(ValueError, match="position_embedding_type"):
            _cfg(position_embedding_type="rotary")


class TestSwiGLU:
    def test_swiglu_mlp_matches_manual(self):
        parallel_state.destroy_model_parallel()
        cfg = _cfg(activation="swiglu", ffn_hidden_size=48)
        mlp = ParallelMLP(cfg)
        x = jnp.asarray(np.random.RandomState(0).randn(8, 2, 32), jnp.float32)
        params = mlp.init(jax.random.PRNGKey(0), x)["params"]
        out = mlp.apply({"params": params}, x)

        w_gu = np.asarray(params["dense_h_to_4h"]["weight"])  # [32, 96]
        w_d = np.asarray(params["dense_4h_to_h"]["weight"])   # [48, 32]
        gu = np.asarray(x) @ w_gu
        gate, up = gu[..., :48], gu[..., 48:]
        ref = (np.asarray(jax.nn.silu(jnp.asarray(gate))) * up) @ w_d
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)
        assert "bias" not in params["dense_h_to_4h"]  # llama-style no bias


class TestTiedEmbeddings:
    def test_tied_head_uses_embedding_table(self):
        from apex_tpu.models import GPTModel

        parallel_state.destroy_model_parallel()
        cfg = _cfg(tie_word_embeddings=True)
        model = GPTModel(cfg)
        tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        assert "lm_head" not in params  # no separate head
        table = np.asarray(params["word_embeddings"]["weight"])  # [v, h]

        # logits == final hidden @ table.T: verify by zeroing... simpler:
        # gradient of loss w.r.t. the table is nonzero from BOTH uses
        # (lookup + head), and logits dimensionality matches the vocab.
        logits = model.apply({"params": params}, tokens)
        assert logits.shape == (2, 8, 64)

        from apex_tpu.models.gpt import gpt_loss_fn

        g = jax.grad(lambda p: gpt_loss_fn(
            model.apply({"params": p}, tokens),
            jnp.roll(tokens, -1, -1)))(params)
        gt = np.asarray(g["word_embeddings"]["weight"])
        # head-path grads touch every vocab row (softmax pulls all logits
        # down), unlike lookup-only grads which are nonzero only for used
        # token ids — so a fully-dense table grad proves the tied head.
        assert (np.abs(gt).sum(axis=1) > 0).all()
        assert table.shape == (64, 32)

    def test_tied_trains_and_generates(self):
        from apex_tpu.models import GPTModel
        from apex_tpu.models.generation import generate

        parallel_state.destroy_model_parallel()
        cfg = _cfg(tie_word_embeddings=True,
                   position_embedding_type="rope")
        model = GPTModel(cfg)
        prompt = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 5)))
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        out = generate(GPTModel(cfg, decode=True), params, prompt,
                       max_new_tokens=4)
        assert out.shape == (2, 9)

    def test_tied_requires_embedding_stage(self):
        import pytest

        from apex_tpu.models import GPTModel

        parallel_state.destroy_model_parallel()
        cfg = _cfg(tie_word_embeddings=True)
        model = GPTModel(cfg, pre_process=False)
        h = jnp.ones((8, 2, 32))
        with pytest.raises(ValueError, match="untie"):
            model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32),
                       hidden_input=h)


def test_llama_style_gpt_trains():
    """RMSNorm + RoPE + SwiGLU + GQA end to end: loss decreases."""
    from apex_tpu.models import GPTModel
    from apex_tpu.models.gpt import gpt_loss_fn
    from apex_tpu.optimizers import FusedAdam

    parallel_state.destroy_model_parallel()
    cfg = _cfg(normalization="rmsnorm", position_embedding_type="rope",
               activation="swiglu", num_query_groups=2,
               ffn_hidden_size=64)
    model = GPTModel(cfg)
    rng = np.random.RandomState(0)
    data = jnp.asarray(rng.randint(0, 64, (4, 17)))
    tokens, labels = data[:, :-1], data[:, 1:]
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    assert "position_embeddings" not in params  # rope: no learned table
    opt = FusedAdam(lr=1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, o):
        loss, g = jax.value_and_grad(
            lambda q: gpt_loss_fn(model.apply({"params": q}, tokens),
                                  labels))(p)
        p, o = opt.step(g, o, p)
        return p, o, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


@pytest.mark.slow
def test_llama_style_3d_parallel_step():
    """Llama-style config through the full pipelined pp x dp x tp harness
    (SP on): one training step, finite losses."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.amp.grad_scaler import GradScaler
    from apex_tpu.transformer.testing.gpt_3d import build_gpt_3d_harness

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2,
        devices=jax.devices()[:8])
    cfg = TransformerConfig(
        hidden_size=32, num_layers=4, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=32,
        compute_dtype=jnp.bfloat16, sequence_parallel=True,
        use_flash_attention=False, normalization="rmsnorm",
        position_embedding_type="rope", activation="swiglu",
        num_query_groups=2, ffn_hidden_size=64)
    SEQ, MB, M = 16, 2, 2
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (MB * M * 2, SEQ)))
    labels = jnp.asarray(rng.randint(0, 64, (MB * M * 2, SEQ)))
    opt = FusedAdam(lr=1e-3)
    scaler = GradScaler(enabled=True)
    init_state, step = build_gpt_3d_harness(
        cfg, mesh, opt, scaler, pp=2, seq=SEQ, microbatch=MB,
        num_microbatches=M)
    state = init_state(jax.random.PRNGKey(0), tokens, labels)
    out = step(*state, tokens, labels)
    losses = np.asarray(out[3])
    assert np.isfinite(losses).all()
