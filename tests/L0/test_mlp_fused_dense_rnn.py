"""MLP / FusedDense / RNN tests.

Mirrors reference tests/L0/run_mlp (MLP vs torch sequential) and the RNN
module surface.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.fused_dense import (
    FusedDense,
    FusedDenseGeluDense,
)
from apex_tpu.mlp import MLP, mlp_function
from apex_tpu.RNN import GRU, LSTM, Tanh, mLSTM


class TestMLP:
    def test_matches_torch_sequential(self, rng):
        sizes = [16, 32, 8]
        m = MLP(mlp_sizes=sizes, activation="relu")
        x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)

        seq = torch.nn.Sequential(
            torch.nn.Linear(16, 32), torch.nn.ReLU(),
            torch.nn.Linear(32, 8), torch.nn.ReLU())
        with torch.no_grad():
            seq[0].weight.copy_(torch.tensor(np.asarray(params["params"]["weight_0"])))
            seq[0].bias.copy_(torch.tensor(np.asarray(params["params"]["bias_0"])))
            seq[2].weight.copy_(torch.tensor(np.asarray(params["params"]["weight_1"])))
            seq[2].bias.copy_(torch.tensor(np.asarray(params["params"]["bias_1"])))
            ref = seq(torch.tensor(np.asarray(x)))
        np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_mlp_function_no_bias(self, rng):
        x = jnp.asarray(rng.randn(3, 8).astype(np.float32))
        w0 = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        w1 = jnp.asarray(rng.randn(4, 16).astype(np.float32))
        y = mlp_function(False, "none", x, w0, w1)
        ref = np.asarray(x) @ np.asarray(w0).T @ np.asarray(w1).T
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)

    def test_bad_activation_raises(self, rng):
        m = MLP(mlp_sizes=[4, 4], activation="tanh")
        with pytest.raises(TypeError):
            m.init(jax.random.PRNGKey(0), jnp.zeros((2, 4)))


class TestFusedDense:
    def test_dense(self, rng):
        m = FusedDense(in_features=8, out_features=4)
        x = jnp.asarray(rng.randn(3, 8).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        w = np.asarray(params["params"]["weight"])
        b = np.asarray(params["params"]["bias"])
        np.testing.assert_allclose(np.asarray(y), np.asarray(x) @ w.T + b,
                                   rtol=1e-5, atol=1e-5)

    def test_gelu_dense(self, rng):
        m = FusedDenseGeluDense(in_features=8, intermediate_features=16,
                                out_features=4)
        x = jnp.asarray(rng.randn(3, 8).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == (3, 4)
        assert np.isfinite(np.asarray(y)).all()


class TestRNN:
    @pytest.mark.parametrize("factory", [LSTM, GRU, Tanh, mLSTM])
    def test_forward_shapes(self, rng, factory):
        m = factory(8, 16, num_layers=2) if factory is not mLSTM else factory(8, 16)
        xs = jnp.asarray(rng.randn(5, 3, 8).astype(np.float32))  # [s, b, f]
        params = m.init(jax.random.PRNGKey(0), xs)
        ys, _ = m.apply(params, xs)
        assert ys.shape == (5, 3, 16)
        assert np.isfinite(np.asarray(ys)).all()

    def test_lstm_matches_torch(self, rng):
        m = LSTM(4, 8, num_layers=1)
        xs = jnp.asarray(rng.randn(6, 2, 4).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), xs)
        ys, _ = m.apply(params, xs)

        cell_p = params["params"]["layer_0"]["ScanRNNCell_0"] \
            if "ScanRNNCell_0" in params["params"]["layer_0"] else \
            list(params["params"]["layer_0"].values())[0]
        w_ih = np.asarray(cell_p["w_ih"])  # [in, 4h] i,f,g,o
        w_hh = np.asarray(cell_p["w_hh"])
        b = np.asarray(cell_p["bias"])

        t = torch.nn.LSTM(4, 8)
        # torch gate order: i, f, g, o — matches ours
        with torch.no_grad():
            t.weight_ih_l0.copy_(torch.tensor(w_ih.T))
            t.weight_hh_l0.copy_(torch.tensor(w_hh.T))
            t.bias_ih_l0.copy_(torch.tensor(b))
            t.bias_hh_l0.zero_()
            ref, _ = t(torch.tensor(np.asarray(xs)))
        np.testing.assert_allclose(np.asarray(ys), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)
