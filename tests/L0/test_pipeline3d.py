"""3-D (data, model, pipe) pipeline parallelism (ISSUE 17).

Evidence layers:

- **Schedule**: the host-side 1F1B tick table executes every
  (rank, microbatch) forward exactly once, its backward after it, and
  never stashes more than the plan's ``min(M, 2P-1)`` bound; the
  analytic bubble model matches its idle-slot count.
- **Training math**: the stage-partitioned step on the 2x2x2 mesh
  reproduces the pp=1 (2x2x1) losses — the ppermute chain and the
  pipe-psummed tied-edge grads are exact, not approximations.
- **Guard**: a NaN injected at one (stage, microbatch) coordinate
  skips the step on EVERY rank (the flag ORs over all three axes) and
  reverts params AND the DP-scoped EF residual bit-exactly.
- **Elastic 3-D ZeRO**: the canonical flat ([stage-owned layers in
  model order] + [tied edge once]) is pp-invariant — 2x2x2 restores
  bit-identically to 2x2x1 and 1x2x2 and back, the pipe-replicated
  tail's stage-invariance is verified not assumed.
- **Supervisor**: the shrink policy gives up the pipe axis first,
  the model axis second.
- **Compat**: the retired ``transformer.pipeline_parallel`` modules
  re-export the new subsystem with ONE DeprecationWarning per process.
"""

import os
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.parallel import mesh2d, pipeline

_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

HID, HEADS, VOCAB, SEQ, M = 32, 4, 32, 8, 4

multi8 = pytest.mark.skipif(
    len(jax.devices()) < 8 or len(jax.devices()) % 8,
    reason="needs 8 devices (2x2x2 mesh)")


def _model(hidden=HID, layers=2, **kw):
    return mesh2d.gpt2_init(hidden=hidden, layers=layers, heads=HEADS,
                            vocab=VOCAB, max_seq=SEQ, **kw)


# ---------------------------------------------------------------------------
# host-side: the 1F1B schedule table
# ---------------------------------------------------------------------------

class TestSchedule:
    @pytest.mark.parametrize("pp,m", [(2, 4), (4, 8), (4, 2), (1, 3)])
    def test_ticks_cover_every_unit_once_in_order(self, pp, m):
        plan = pipeline.pipeline_schedule_plan(pp, m)
        ticks = pipeline.schedule_ticks(pp, m)
        assert len(ticks) == plan["total"] == m + 2 * pp - 2
        fwd_at, bwd_at = {}, {}
        for tk in ticks:
            for r, i in tk["fwd"]:
                assert (r, i) not in fwd_at
                fwd_at[(r, i)] = tk["tick"]
            for r, i in tk["bwd"]:
                assert (r, i) not in bwd_at
                bwd_at[(r, i)] = tk["tick"]
        units = {(r, i) for r in range(pp) for i in range(m)}
        assert set(fwd_at) == units and set(bwd_at) == units
        for r in range(pp):
            for i in range(m):
                # bwd of (r, i) strictly after its fwd, and after the
                # DOWNSTREAM stage's fwd of the same microbatch
                assert bwd_at[(r, i)] >= fwd_at[(r, i)] + (r < pp - 1)
                if r + 1 < pp:
                    # the ppermute chain: stage r+1 consumes (r, i)'s
                    # activation exactly one tick later
                    assert fwd_at[(r + 1, i)] == fwd_at[(r, i)] + 1

    @pytest.mark.parametrize("pp,m", [(2, 4), (4, 8), (4, 2)])
    def test_stash_bound_holds(self, pp, m):
        plan = pipeline.pipeline_schedule_plan(pp, m)
        ticks = pipeline.schedule_ticks(pp, m)
        in_flight = {r: 0 for r in range(pp)}
        peak = 0
        for tk in ticks:
            for r, _ in tk["fwd"]:
                in_flight[r] += 1
            peak = max(peak, max(in_flight.values()))
            for r, _ in tk["bwd"]:
                in_flight[r] -= 1
        assert peak <= plan["stash"]
        assert all(v == 0 for v in in_flight.values())

    def test_analytic_bubble_fraction(self):
        assert pipeline.analytic_bubble_fraction(1, 7) == 0.0
        assert pipeline.analytic_bubble_fraction(2, 4) == \
            pytest.approx(1 / 5)
        assert pipeline.analytic_bubble_fraction(4, 12) == \
            pytest.approx(3 / 15)
        # the schedule's own idle-slot count IS the model: per phase
        # half (fwd, bwd), pp-1 of m+pp-1 slots run no unit
        pp, m = 4, 12
        ticks = pipeline.schedule_ticks(pp, m)
        fwd_slots = sum(1 for tk in ticks for r in range(pp)
                        if any(u[0] == r for u in tk["fwd"]))
        idle = (m + pp - 1) * pp - fwd_slots
        assert idle / ((m + pp - 1) * pp) == \
            pytest.approx(pipeline.analytic_bubble_fraction(pp, m))


# ---------------------------------------------------------------------------
# host-side: the elastic 3-D ZeRO shard table
# ---------------------------------------------------------------------------

class TestZero3D:
    def _segments(self):
        sp = _model()
        return pipeline.pipeline_zero_segments(sp)

    def _full_dict(self, rng, segs, dp, tp, pp):
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _flat_size,
        )

        n = _flat_size(segs)
        return {"format": 3, "optimizer": "DistributedFusedAdam",
                "dp_world": dp, "tp_world": tp, "pp_world": pp,
                "shared_tail_elements": _flat_size(segs[-1:]),
                "n_elements": n, "block_size": 256,
                "grad_compress": "int8", "param_compress": "bf16",
                "step": np.int32(7),
                "master": rng.randn(n).astype(np.float32),
                "exp_avg": rng.randn(n).astype(np.float32),
                "exp_avg_sq": np.abs(rng.randn(n))
                .astype(np.float32),
                "grad_residual": (rng.randn(n) * 1e-3)
                .astype(np.float32)}

    @pytest.mark.parametrize("mid_world", [(2, 2, 1), (1, 2, 2)])
    @pytest.mark.parametrize("overlap", [False, True])
    def test_roundtrip_222_via_shrunk_world_bit_identical(
            self, mid_world, overlap):
        """2x2x2 -> (2x2x1 | 1x2x2) -> 2x2x2: the supervisor's two
        shrink choices, both restoring bit-identically through the
        pp-invariant canonical flat."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            consolidate_zero_state_3d, reshard_zero_state_3d,
        )

        segs, dims = self._segments()
        rng = np.random.RandomState(3)
        full0 = self._full_dict(rng, segs, 2, 2, 2)
        dp, tp, pp = mid_world
        mid_states = reshard_zero_state_3d(
            full0, segs, dims, dp_world=dp, tp_world=tp, pp_world=pp,
            grad_compress="int8", param_compress="bf16",
            block_size=256, overlap=overlap)
        assert len(mid_states) == pp
        mid = consolidate_zero_state_3d(
            mid_states, segs, dims, dp_world=dp, tp_world=tp,
            pp_world=pp, grad_compress="int8", param_compress="bf16",
            block_size=256, optimizer="DistributedFusedAdam")
        back_states = reshard_zero_state_3d(
            mid, segs, dims, dp_world=2, tp_world=2, pp_world=2,
            grad_compress="int8", param_compress="bf16",
            block_size=256, overlap=overlap)
        back = consolidate_zero_state_3d(
            back_states, segs, dims, dp_world=2, tp_world=2,
            pp_world=2, grad_compress="int8", param_compress="bf16",
            block_size=256, optimizer="DistributedFusedAdam")
        for key in ("master", "exp_avg", "exp_avg_sq",
                    "grad_residual"):
            np.testing.assert_array_equal(back[key], full0[key])
        assert int(back["step"]) == 7
        opt = DistributedFusedAdam(compress=True)
        assert opt  # the method route, same math
        st = opt.load_state_dict_resharded(full0, segs,
                                           world=mid_world,
                                           partition_dims=dims)
        again = opt.state_dict_full(st, segs, world=mid_world,
                                    partition_dims=dims)
        np.testing.assert_array_equal(again["master"], full0["master"])

    def test_pp1_format2_dict_restores_on_222(self):
        """A checkpoint written at pp == 1 (format 2, no pipe fields)
        restores onto the 3-D world — the canonical flat layouts are
        identical by construction."""
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _flat_size, consolidate_zero_state_3d,
            reshard_zero_state_3d,
        )

        segs, dims = self._segments()
        rng = np.random.RandomState(4)
        full0 = self._full_dict(rng, segs, 2, 2, 1)
        full0["format"] = 2
        del full0["pp_world"], full0["shared_tail_elements"]
        sts = reshard_zero_state_3d(
            full0, segs, dims, dp_world=2, tp_world=2, pp_world=2,
            grad_compress="int8", block_size=256)
        back = consolidate_zero_state_3d(
            sts, segs, dims, dp_world=2, tp_world=2, pp_world=2,
            grad_compress="int8", block_size=256)
        np.testing.assert_array_equal(back["master"], full0["master"])
        assert back["format"] == 3
        assert back["shared_tail_elements"] == _flat_size(segs[-1:])

    def test_pipe_tail_divergence_refuses(self):
        """Stage-invariance of the tied edge is VERIFIED: a stage
        whose pipe-replicated tail diverged must fail consolidation,
        not silently pick one."""
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            consolidate_zero_state_3d, reshard_zero_state_3d,
            split_params_for_model_axis, split_params_for_pipe_axis,
        )

        segs, dims = self._segments()
        rng = np.random.RandomState(5)
        full0 = self._full_dict(rng, segs, 2, 2, 2)
        sts = reshard_zero_state_3d(
            full0, segs, dims, dp_world=2, tp_world=2, pp_world=2,
            grad_compress="int8", block_size=256)
        stage_p = split_params_for_pipe_axis(segs, 2)
        stage_d = split_params_for_pipe_axis(dims, 2)
        # poison the last LOGICAL element (the tied edge's tail) on
        # BOTH model ranks of stage 1 — the stage's own 2-D
        # replicated-leaf check must pass so the pipe check is what
        # fires
        for t in range(2):
            n_t = sum(l.size for l in jax.tree_util.tree_leaves(
                split_params_for_model_axis(stage_p[1], stage_d[1],
                                            2)[t]))
            bad = dict(sts[1][t])
            m = np.asarray(bad["master_shard"]).copy()
            m[n_t - 1] += 1.0
            bad["master_shard"] = m
            sts[1][t] = bad
        with pytest.raises(ValueError, match="pipe-replicated tail"):
            consolidate_zero_state_3d(
                sts, segs, dims, dp_world=2, tp_world=2, pp_world=2,
                grad_compress="int8", block_size=256)

    def test_segments_and_dims_cover_the_layout(self):
        segs, dims = self._segments()
        # [per-layer segments in model order] + [the tied edge once]
        assert len(segs) == 3
        assert set(segs[-1]) == {"embed", "ln_f", "head"}
        assert dims[0]["attn"]["wq"] == 1
        assert dims[-1]["head"]["w"] is None
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            split_params_for_pipe_axis,
        )

        stages = split_params_for_pipe_axis(segs, 2)
        assert [len(s) for s in stages] == [2, 2]  # 1 layer + tail
        assert stages[0][-1] is segs[-1] is stages[1][-1]
        with pytest.raises(ValueError, match="do not split"):
            split_params_for_pipe_axis(segs, 4)


# ---------------------------------------------------------------------------
# on-mesh: pp=2 parity with pp=1, guard skip-revert
# ---------------------------------------------------------------------------

@multi8
class TestPipelineStep3D:
    @pytest.mark.slow  # tier-1 budget (round 23): guard revert + schedule units cover the 3-D step
    def test_pp2_matches_pp1_losses(self):
        sp = _model()
        losses = {}
        for pipe in (1, 2):
            mesh = pipeline.mesh_3d(2, 2, pipe)
            step, state = pipeline.build_pipeline_step(
                mesh, sp, hidden=HID, heads=HEADS, microbatches=M)
            tokens, labels = pipeline.make_batch_3d(
                mesh, microbatches=M, batch_per_replica=2, seq=SEQ,
                vocab=VOCAB)
            out = step(*state, tokens, labels)
            out = step(*out[:3], tokens, labels)
            losses[pipe] = [float(out[3])]
            out = step(*out[:3], tokens, labels)
            losses[pipe].append(float(out[3]))
        np.testing.assert_allclose(losses[2], losses[1], rtol=2e-5,
                                   atol=2e-6)
        assert losses[2][1] < losses[2][0]  # it trains

    def test_guard_nan_skip_reverts_bit_exact(self):
        """NaN at (step 1, stage 1, microbatch 2): the flag ORs over
        (data, model, pipe), every rank skips, and params + EF
        residual revert bit-exactly."""
        mesh = pipeline.mesh_3d(2, 2, 2)
        sp = _model()
        step, state = pipeline.build_pipeline_step(
            mesh, sp, hidden=HID, heads=HEADS, microbatches=M,
            mode="guarded", guard_nan=(1, 1, 2))
        tokens, labels = pipeline.make_batch_3d(
            mesh, microbatches=M, batch_per_replica=2, seq=SEQ,
            vocab=VOCAB)
        out = step(*state, jnp.zeros((), jnp.int32), tokens, labels)
        assert int(out[3].total_skips) == 0
        assert np.isfinite(float(out[4]))
        before = jax.tree_util.tree_map(np.asarray,
                                        (out[0], out[1], out[2]))
        out2 = step(out[0], out[1], out[2], out[3],
                    jnp.ones((), jnp.int32), tokens, labels)
        assert int(out2[3].total_skips) == 1
        for b, a in zip(
                jax.tree_util.tree_leaves(before),
                jax.tree_util.tree_leaves((out2[0], out2[1],
                                           out2[2]))):
            np.testing.assert_array_equal(b, np.asarray(a))


# ---------------------------------------------------------------------------
# host-side: supervisor 3-D shrink policy
# ---------------------------------------------------------------------------

class TestSupervisor3D:
    def test_half_world_gives_up_pipe_then_model(self):
        from apex_tpu.resilience.supervisor import _half_world

        assert _half_world((2, 2, 2)) == (2, 2, 1)
        assert _half_world((2, 2, 1)) == (2, 1, 1)
        assert _half_world((2, 1, 1)) == (1, 1, 1)
        assert _half_world((1, 1, 1)) == (1, 1, 1)
        assert _half_world((2, 2, 4)) == (2, 2, 2)


# ---------------------------------------------------------------------------
# compat: the retired transformer.pipeline_parallel surface
# ---------------------------------------------------------------------------

class TestCompatShims:
    def test_shims_reexport_and_warn_once(self):
        import importlib

        import apex_tpu.transformer.pipeline_parallel.p2p_communication \
            as p2p
        import apex_tpu.transformer.pipeline_parallel.schedules \
            as schedules

        assert schedules.pipeline_schedule_plan \
            is pipeline.pipeline_schedule_plan
        assert schedules.get_forward_backward_func \
            is pipeline.get_forward_backward_func
        assert p2p.send_forward is pipeline.send_forward
        assert p2p.recv_forward is pipeline.recv_forward
        # one DeprecationWarning per process, total, across both shims
        prev = pipeline._MOVED_WARNED
        try:
            pipeline._MOVED_WARNED = False
            with warnings.catch_warnings(record=True) as rec:
                warnings.simplefilter("always")
                importlib.reload(schedules)
                importlib.reload(p2p)
            dep = [w for w in rec
                   if issubclass(w.category, DeprecationWarning)
                   and "apex_tpu.parallel.pipeline" in str(w.message)]
            assert len(dep) == 1
        finally:
            pipeline._MOVED_WARNED = prev
