"""External numerics oracle: apex_tpu WhisperModel vs HuggingFace
Whisper.

A randomly-initialized ``transformers`` WhisperForConditionalGeneration
(no download) is converted with tools/convert_hf_whisper; identical
weights must produce matching logits — validating the conv frontend,
sinusoidal encoder positions, biased scaled attention (zero K bias),
cross-attention, and the tied head against an independent
implementation end to end.
"""

import sys

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, ".")  # repo root for tools/


def _tiny_whisper(seed=0):
    cfg = transformers.WhisperConfig(
        vocab_size=96, d_model=48, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=96, decoder_ffn_dim=96, num_mel_bins=8,
        max_source_positions=16, max_target_positions=12,
        dropout=0.0, attention_dropout=0.0, activation_dropout=0.0,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
        decoder_start_token_id=1, suppress_tokens=None,
        begin_suppress_tokens=None)
    torch.manual_seed(seed)
    return transformers.WhisperForConditionalGeneration(cfg).eval(), cfg


def _fresh():
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()


def test_logits_match_hf_whisper():
    from tools.convert_hf_whisper import convert_whisper

    from apex_tpu.models.whisper import WhisperModel

    _fresh()
    hf, hf_cfg = _tiny_whisper()
    cfg, params = convert_whisper(hf.state_dict(), hf_cfg)

    rng = np.random.RandomState(0)
    # mel features: [b, num_mel_bins, 2 * max_source_positions] frames
    feats = rng.randn(2, 8, 32).astype(np.float32)
    dec = rng.randint(0, 96, size=(2, 7))
    with torch.no_grad():
        ref = hf(input_features=torch.asarray(feats),
                 decoder_input_ids=torch.asarray(dec)).logits.numpy()
    ours = WhisperModel(cfg).apply({"params": params},
                                   jnp.asarray(feats), jnp.asarray(dec))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow  # tier-1 budget (round 23): cached_generate_matches_oracle + logits_match cover it
def test_whisper_greedy_matches_hf_manual_loop():
    """Token parity against a manual HF greedy loop (hf.generate applies
    Whisper-specific token suppression that is tokenizer policy, not
    model numerics)."""
    from tools.convert_hf_whisper import convert_whisper

    from apex_tpu.models.whisper import (WhisperModel,
                                         whisper_greedy_generate)

    _fresh()
    hf, hf_cfg = _tiny_whisper(seed=2)
    cfg, params = convert_whisper(hf.state_dict(), hf_cfg)
    feats = np.random.RandomState(2).randn(2, 8, 32).astype(np.float32)

    dec = np.full((2, 1), 1, np.int64)  # decoder_start_token_id
    with torch.no_grad():
        for _ in range(6):
            logits = hf(input_features=torch.asarray(feats),
                        decoder_input_ids=torch.asarray(dec)).logits
            nxt = logits[:, -1, :].argmax(-1, keepdim=True).numpy()
            dec = np.concatenate([dec, nxt], axis=1)

    ours = whisper_greedy_generate(
        WhisperModel(cfg), params, jnp.asarray(feats), max_new_tokens=6,
        decoder_start_token_id=1)
    np.testing.assert_array_equal(np.asarray(ours), dec)


def test_whisper_frontend_refuses_wrong_frame_count():
    import jax

    from apex_tpu.models.whisper import WhisperConfig, WhisperModel

    _fresh()
    cfg = WhisperConfig(vocab_size=32, d_model=32, encoder_layers=1,
                        decoder_layers=1, num_heads=4,
                        encoder_ffn_dim=64, decoder_ffn_dim=64,
                        num_mel_bins=8, max_source_positions=16,
                        max_target_positions=8,
                        compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="post-conv frames"):
        WhisperModel(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8, 20)),
                               jnp.zeros((1, 4), jnp.int32))


def test_whisper_cached_generate_matches_oracle():
    """KV-cache decode is token-exact vs the full-rerun oracle (which is
    itself token-exact vs HF, above)."""
    from tools.convert_hf_whisper import convert_whisper

    from apex_tpu.models.whisper import (WhisperModel,
                                         whisper_cached_generate,
                                         whisper_greedy_generate)

    _fresh()
    hf, hf_cfg = _tiny_whisper(seed=4)
    cfg, params = convert_whisper(hf.state_dict(), hf_cfg)
    feats = np.random.RandomState(4).randn(2, 8, 32).astype(np.float32)
    model = WhisperModel(cfg)
    oracle = whisper_greedy_generate(model, params, jnp.asarray(feats),
                                     max_new_tokens=7,
                                     decoder_start_token_id=1)
    cached = whisper_cached_generate(model, params, jnp.asarray(feats),
                                     max_new_tokens=7,
                                     decoder_start_token_id=1)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(oracle))


def test_whisper_decode_step_without_prefill_raises():
    import jax

    from apex_tpu.models.whisper import WhisperConfig, WhisperModel

    _fresh()
    cfg = WhisperConfig(vocab_size=32, d_model=32, encoder_layers=1,
                        decoder_layers=1, num_heads=4,
                        encoder_ffn_dim=64, decoder_ffn_dim=64,
                        num_mel_bins=8, max_source_positions=16,
                        max_target_positions=8, compute_dtype=jnp.float32)
    model = WhisperModel(cfg)
    feats = jnp.zeros((1, 8, 32))
    dec = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), feats, dec)["params"]
    with pytest.raises(ValueError, match="decode_step before"):
        model.apply({"params": params}, jnp.zeros((1, 1), jnp.int32),
                    mutable=["cache"], method=WhisperModel.decode_step)
