"""Serving fleet (apex_tpu.serving.fleet + ISSUE 11).

Covers:

- fleet config validation + tier resolution (tier defaults fill the
  PR-7 per-request deadline fields; request-level overrides win);
- load-aware dispatch over stub replicas (most-free-slots routing,
  per-replica queue caps, interactive-before-batch priority,
  impossible shapes rejected at the fleet, not retried forever);
- the replica health state machine: healthy -> degraded ->
  quarantined -> respawning -> healthy off ServeHealth counter
  deltas, with drain + migration on quarantine;
- request migration bookkeeping: tokens emitted on a dead replica are
  carried into the continuation (re-prefill from prompt + emitted),
  stitched back on completion, zero silent losses — a continuation
  too long for every prefill ladder is a LOUD loss;
- ``inject_replica_loss`` (hard loss): everything migrates at once,
  the replica respawns with a fresh generation name;
- elastic autoscale: sustained pending depth spawns into idle slots,
  sustained idle retires the least-loaded replica gracefully;
- the 8-device chaos e2e acceptance (tier-1, cheap): a 2-replica x
  4-device fleet, one replica killed mid-trace -> every in-flight
  request of the dead replica finishes on the survivor with greedy
  outputs token-identical to the unkilled run, goodput >= 90% of
  clean, zero watcher recompiles, per-replica compile_count == the
  ladder size;
- the ``bench.py serve_fleet`` contract (slow — two fleets on the
  smoke model) + round-16 schema gating (cheap, dict-level).

Pure-policy paths run against stub engines via ``engine_factory`` (no
compiles — the router is host-side by design); the acceptance shares
one tiny real model per module scope.
"""

import io
import json
import os
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.resilience import faults
from apex_tpu.serving import (
    FleetConfig,
    Request,
    RobustConfig,
    Scheduler,
    ServeConfig,
    ServeFleet,
    TierConfig,
    diurnal_trace,
)
from apex_tpu.telemetry import CompileWatcher
from apex_tpu.telemetry.registry import MetricsRegistry, use_registry
from apex_tpu.transformer import parallel_state

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def tiny():
    parallel_state.destroy_model_parallel()
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=128,
        compute_dtype=jnp.float32, use_flash_attention=False)
    model = GPTModel(cfg, decode=True)
    params = GPTModel(cfg).init(jax.random.PRNGKey(0),
                                jnp.zeros((1, 4), jnp.int32))["params"]
    return cfg, model, params


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.disarm_replica_loss()


class _StubEngine:
    """Duck-typed engine for pure router-policy tests: no jax, no
    compiles. ``finite_fn(slot_ids, call)`` shapes the quarantine
    flags so health-counter transitions can be scripted."""

    def __init__(self, num_slots=4, finite_fn=None, prefill_buckets=(64,),
                 batch_buckets=(2, 4)):
        self.config = types.SimpleNamespace(
            num_slots=num_slots, batch_buckets=tuple(batch_buckets),
            prefill_buckets=tuple(prefill_buckets),
            eos_token_id=None, pad_token_id=0)
        self.max_len = 10_000
        self.decode_retries_total = 0
        self._decode_calls = 0
        self.compile_count = 6
        self.spec = types.SimpleNamespace(
            bytes_per_slot=lambda: 0, cache_dtype_name=lambda: "stub")
        self._finite_fn = finite_fn

    def kv_cache_bytes(self):
        return 0

    def prefill(self, slot_ids, prompts, *, pad_slot_ids=None):
        return np.ones(len(prompts), np.int32)

    def decode(self, slot_ids, tokens, *, pad_slot_ids=None,
               retries=0, backoff_s=0.0, backoff_cap_s=0.0):
        call = self._decode_calls
        self._decode_calls += 1
        n = len(slot_ids)
        finite = (np.ones(n, bool) if self._finite_fn is None
                  else np.asarray(self._finite_fn(slot_ids, call)))
        return np.ones(n, np.int32), finite


def _stub_fleet(config=None, *, num_slots=4, finite_fns=None,
                prefill_buckets=(64,), batch_buckets=(2, 4),
                registry=None):
    """Fleet over stub engines; ``finite_fns[idx]`` scripts replica
    idx's quarantine flags (consulted per spawn generation)."""
    finite_fns = finite_fns or {}
    generations = {}

    def factory(idx, mesh, name):
        gen = generations.get(idx, 0)
        generations[idx] = gen + 1
        fn = finite_fns.get(idx) if gen == 0 else None
        return _StubEngine(num_slots=num_slots, finite_fn=fn,
                           prefill_buckets=prefill_buckets,
                           batch_buckets=batch_buckets)

    return ServeFleet(engine_factory=factory,
                      config=config or FleetConfig(),
                      registry=registry)


def _req(rid, plen=3, max_new=4, arrival=0.0, **kw):
    return Request(rid=rid, prompt=np.arange(plen, dtype=np.int32) % 7,
                   max_new_tokens=max_new, arrival=arrival, **kw)


# ---------------------------------------------------------------------------
# config + tier resolution
# ---------------------------------------------------------------------------

class TestFleetConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="num_replicas"):
            FleetConfig(num_replicas=0)
        with pytest.raises(ValueError, match="max_replicas"):
            FleetConfig(num_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="min_replicas"):
            FleetConfig(num_replicas=2, min_replicas=3)
        with pytest.raises(ValueError, match="unknown tier"):
            FleetConfig(tiers={"premium": TierConfig()})
        with pytest.raises(ValueError, match="quarantine_after"):
            FleetConfig(degraded_after=3, quarantine_after=1)
        with pytest.raises(ValueError, match="oscillate"):
            FleetConfig(scale_up_pending=2, scale_down_pending=4)
        assert FleetConfig(num_replicas=2).resolved_max_replicas == 2
        assert FleetConfig(num_replicas=2,
                           max_replicas=4).resolved_max_replicas == 4

    def test_tier_defaults_fill_deadlines(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=1, tiers={
            "interactive": TierConfig(ttft_deadline_s=5.0,
                                      total_deadline_s=20.0),
            "batch": TierConfig(total_deadline_s=500.0)}))
        assert fleet.submit(_req(0))                        # default tier
        assert fleet.submit(_req(1, tier="batch"))
        assert fleet.submit(_req(2, tier="interactive",
                                 ttft_deadline_s=1.0))      # override wins
        by_rid = {r.rid: r for r in fleet.pending}
        assert by_rid[0].tier == "interactive"
        assert by_rid[0].ttft_deadline_s == 5.0
        assert by_rid[1].ttft_deadline_s is None
        assert by_rid[1].total_deadline_s == 500.0
        assert by_rid[2].ttft_deadline_s == 1.0

    def test_unknown_tier_and_duplicate_rid_reject(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=1))
        assert not fleet.submit(_req(0, tier="premium"))
        assert fleet.submit(_req(1))
        assert not fleet.submit(_req(1))
        assert [r.reason for r in fleet.rejected] == \
            ["unknown_tier", "duplicate_rid"]

    def test_per_tier_accounting(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=2))
        reqs = [_req(i, tier="batch" if i % 2 else "interactive",
                     max_new=3) for i in range(6)]
        fleet.run(reqs)
        s = fleet.stats()
        assert s["by_tier"]["interactive"]["requests"] == 3
        assert s["by_tier"]["batch"]["requests"] == 3
        assert s["by_tier"]["interactive"]["ok"] == 3
        assert s["ttft_p99_ms_interactive"] is not None
        assert s["ttft_p99_ms_batch"] is not None


# ---------------------------------------------------------------------------
# dispatch policy
# ---------------------------------------------------------------------------

class TestDispatch:
    def test_load_aware_spread(self):
        """A burst spreads across replicas instead of piling onto
        one: the router picks the replica with the most free slots."""
        fleet = _stub_fleet(FleetConfig(num_replicas=2), num_slots=4)
        fleet.run([_req(i, max_new=3) for i in range(8)])
        s = fleet.stats()
        dispatched = [r["dispatched"] for r in s["replicas"]]
        assert sorted(dispatched) == [4, 4]

    def test_queue_cap_leaves_backlog_at_fleet(self):
        fleet = _stub_fleet(
            FleetConfig(num_replicas=1, replica_queue_depth=2),
            num_slots=2)
        for i in range(12):
            assert fleet.submit(_req(i, max_new=4))
        fleet._dispatch()
        rep = fleet.replicas[0]
        # capacity this tick: 2 free slots + queue cap 2 — the other 8
        # wait at the fleet, where autoscale can see them
        assert len(rep.sched.pending) == 4
        assert len(fleet.pending) == 8
        done = fleet.run()
        assert len(done) == 12
        assert all(c.finish_reason == "length" for c in done)

    def test_impossible_prompt_rejects_at_fleet(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=1),
                            prefill_buckets=(8,))
        assert fleet.submit(_req(0, plen=99))    # fleet can't know yet
        done = fleet.run(max_steps=10)
        assert done == []
        assert [r.reason for r in fleet.rejected] == ["prompt_too_long"]

    def test_interactive_jumps_batch_when_capacity_contended(self):
        """Capacity for 2 dispatches this tick, 3 requests queued:
        the interactive one makes the cut even though it was
        submitted last; a batch request waits at the fleet."""
        fleet = _stub_fleet(
            FleetConfig(num_replicas=1, replica_queue_depth=1),
            num_slots=1, batch_buckets=(1,))
        fleet.submit(_req(0, tier="batch", max_new=2))
        fleet.submit(_req(1, tier="batch", max_new=2))
        fleet.submit(_req(2, tier="interactive", max_new=2))
        fleet._dispatch()
        dispatched = {r.rid for r in fleet.replicas[0].sched.pending}
        assert 2 in dispatched
        assert [r.rid for r in fleet.pending] in ([0], [1])
        assert fleet.pending[0].tier == "batch"


# ---------------------------------------------------------------------------
# health state machine
# ---------------------------------------------------------------------------

class TestHealthStateMachine:
    def test_degraded_then_recovered(self):
        """One poisoned slot degrades the replica; clean ticks heal
        it back to healthy."""
        def finite_fn(slot_ids, call):
            ok = np.ones(len(slot_ids), bool)
            if call == 0:
                ok[0] = False
            return ok

        fleet = _stub_fleet(
            FleetConfig(num_replicas=1, degraded_after=1,
                        quarantine_after=10, recover_after_ticks=2),
            finite_fns={0: finite_fn})
        fleet.run([_req(i, max_new=8, arrival=float(i))
                   for i in range(6)])
        rep = fleet.replicas[0]
        assert rep.state == "healthy"            # recovered by run end
        s = fleet.stats()
        assert s["requests_by_reason"].get("poisoned") == 1
        assert s["replicas_quarantined"] == 0

    def test_bad_counters_quarantine_and_respawn(self):
        """Accumulated poisoned-slot evictions cross quarantine_after:
        the replica drains, migrates, respawns with a fresh
        generation — and the poisoned terminals stay non-silent."""
        def finite_fn(slot_ids, call):
            ok = np.ones(len(slot_ids), bool)
            if call < 3:
                ok[0] = False
            return ok

        fleet = _stub_fleet(
            FleetConfig(num_replicas=2, degraded_after=1,
                        quarantine_after=3, respawn_delay_ticks=1),
            finite_fns={0: finite_fn})
        done = fleet.run([_req(i, max_new=8, arrival=float(i) * 0.3)
                          for i in range(10)])
        s = fleet.stats()
        assert s["replicas_quarantined"] >= 1
        assert s["replicas_respawned"] >= 1
        assert s["lost_requests"] == 0
        reasons = [c.finish_reason for c in done]
        assert reasons.count("poisoned") == 3
        assert s["requests_ok"] == 7
        # the respawned replica slot is serving again
        assert fleet.replicas[0].state == "healthy"
        assert fleet.replicas[0].generation == 2

    def test_replica_state_events_land(self, tmp_path):
        with use_registry(MetricsRegistry(jsonl_dir=str(tmp_path))) \
                as reg:
            fleet = _stub_fleet(FleetConfig(num_replicas=1))
            with faults.inject_replica_loss(0, 1):
                fleet.run([_req(i, max_new=6) for i in range(3)])
            reg.flush()
        events = []
        for p in tmp_path.glob("telemetry-rank*.jsonl"):
            events += [json.loads(l) for l in p.read_text().splitlines()]
        fe = [e for e in events if e["kind"] == "fleet"]
        names = {e["name"] for e in fe}
        assert {"fleet_start", "replica_state", "migration",
                "respawn", "fleet_report"} <= names
        states = [(e["old"], e["new"]) for e in fe
                  if e["name"] == "replica_state"]
        assert ("idle", "healthy") in states
        assert ("healthy", "quarantined") in states
        assert ("quarantined", "respawning") in states
        assert ("respawning", "healthy") in states


# ---------------------------------------------------------------------------
# replica loss + migration bookkeeping
# ---------------------------------------------------------------------------

class TestReplicaLossMigration:
    def test_loss_migrates_and_stitches_tokens(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=2,
                                        respawn_delay_ticks=1))
        with faults.inject_replica_loss(0, 2) as st:
            done = fleet.run([_req(i, max_new=5, arrival=float(i) * 0.4)
                              for i in range(8)])
        assert st["fired"] == 1
        s = fleet.stats()
        assert s["lost_requests"] == 0
        assert s["migrated_requests"] >= 1
        assert s["replicas_respawned"] == 1
        assert s["rebalance_latency_ms"] is not None
        assert len(done) == 8
        # every request got its FULL token budget despite the kill —
        # the continuation carried the emitted prefix
        assert all(len(c.tokens) == 5 for c in done)
        assert all(c.finish_reason == "length" for c in done)

    def test_loss_without_respawn_leaves_survivors_serving(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=2, respawn=False))
        with faults.inject_replica_loss(0, 1):
            done = fleet.run([_req(i, max_new=4, arrival=float(i) * 0.2)
                              for i in range(6)])
        assert len(done) == 6
        assert fleet.stats()["replicas_respawned"] == 0
        assert fleet.replicas[0].state == "quarantined"
        assert fleet.replicas[1].state == "healthy"

    def test_oversized_continuation_is_loud_loss(self):
        """A continuation prompt (orig + emitted) that no ladder can
        re-prefill lands terminal ``failed`` + fleet/lost_requests —
        never a silent disappearance."""
        fleet = _stub_fleet(FleetConfig(num_replicas=2,
                                        respawn_delay_ticks=1),
                            prefill_buckets=(8,))
        # plen 6 + a few emitted tokens > bucket 8 once decode started
        with faults.inject_replica_loss(0, 3):
            done = fleet.run([_req(i, plen=6, max_new=8,
                                   arrival=0.0) for i in range(4)])
        s = fleet.stats()
        assert len(done) == 4
        failed = [c for c in done if c.finish_reason == "failed"]
        assert len(failed) == s["lost_requests"] >= 1
        # the partial tokens ride on the failed record (evidence)
        assert all(len(c.tokens) > 0 for c in failed)

    def test_extract_unfinished_scopes(self):
        """The scheduler migration seam: active-only extraction leaves
        the queue for the drain window and vice versa."""
        sched = Scheduler(_StubEngine(num_slots=2))
        for i in range(4):
            sched.submit(_req(i, max_new=8))
        sched.step()                              # 2 admitted, 2 queued
        assert len(sched.active) == 2 and len(sched.pending) == 2
        pending = sched.extract_unfinished(which="pending")
        assert [r["where"] for r in pending] == ["pending"] * 2
        assert [r["tokens"] for r in pending] == [[], []]
        assert len(sched.active) == 2
        active = sched.extract_unfinished(which="active")
        assert [r["where"] for r in active] == ["active"] * 2
        assert all(len(r["tokens"]) >= 1 for r in active)
        assert sorted(sched.free) == [0, 1]
        assert not sched.active and not sched.pending
        with pytest.raises(ValueError, match="which"):
            sched.extract_unfinished(which="everything")

    def test_replica_loss_plan_arming(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_FAULT_PLAN, "replica_loss@4:1")
        faults.disarm_replica_loss()
        assert faults.replica_loss_for(3) is None
        assert faults.replica_loss_for(4) == 1
        assert faults.replica_loss_for(4) is None   # one-shot


# ---------------------------------------------------------------------------
# elastic autoscale
# ---------------------------------------------------------------------------

class TestAutoscale:
    def test_scale_up_on_sustained_depth_and_down_when_idle(self):
        fleet = _stub_fleet(
            FleetConfig(num_replicas=1, max_replicas=3, min_replicas=1,
                        scale_up_pending=3, scale_down_pending=0,
                        scale_sustain_ticks=2),
            num_slots=2)
        fleet.run([_req(i, max_new=6) for i in range(16)])
        s = fleet.stats()
        assert s["scale_ups"] >= 1
        assert s["requests_ok"] == 16
        # the spawned replicas actually took traffic
        assert sum(1 for r in s["replicas"] if r["dispatched"]) >= 2
        # the tail of the run retired back toward min_replicas
        assert s["scale_downs"] >= 1

    def test_no_thresholds_no_scaling(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=1, max_replicas=3),
                            num_slots=2)
        fleet.run([_req(i, max_new=4) for i in range(10)])
        s = fleet.stats()
        assert s["scale_ups"] == 0 and s["scale_downs"] == 0
        assert [r["state"] for r in s["replicas"]] == \
            ["healthy", "idle", "idle"]

    def test_scale_events_land(self, tmp_path):
        with use_registry(MetricsRegistry(jsonl_dir=str(tmp_path))) \
                as reg:
            fleet = _stub_fleet(
                FleetConfig(num_replicas=1, max_replicas=2,
                            scale_up_pending=2, scale_sustain_ticks=2),
                num_slots=2)
            fleet.run([_req(i, max_new=6) for i in range(12)])
            reg.flush()
        events = []
        for p in tmp_path.glob("telemetry-rank*.jsonl"):
            events += [json.loads(l) for l in p.read_text().splitlines()]
        ups = [e for e in events if e["kind"] == "fleet"
               and e["name"] == "scale_up"]
        assert ups and ups[0]["pending_depth"] > 2


# ---------------------------------------------------------------------------
# the 8-device chaos e2e acceptance (tier-1: the cheap one)
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestFleetChaosE2E:
    @pytest.mark.slow  # duplicate coverage: the oneproc fleet smoke
    # drives the same kill-mid-trace path (tier-1 budget, 14s)
    def test_kill_replica_mid_trace_token_identity(self, tiny):
        """ISSUE-11 acceptance: a 2-replica x 4-device fleet on the
        8-device CPU mesh, replica 0 killed mid-Poisson-trace ->
        every in-flight request of the dead replica finishes on the
        survivor, greedy outputs token-identical to an unkilled run,
        fleet goodput >= 90% of clean, zero watcher recompiles in
        steady state (the respawned ladder registers under a fresh
        generation name), per-replica compile_count == the ladder."""
        cfg, model, params = tiny
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        serve_cfg = ServeConfig(batch_buckets=(2, 4),
                                prefill_buckets=(16,), num_slots=4)

        def trace():
            return diurnal_trace(
                10, seed=5, prompt_lens=(3, 5), max_new=(4, 6),
                vocab_size=cfg.vocab_size, burst_at=0.0, burst_n=3,
                base_interarrival=0.6)

        def build():
            watcher = CompileWatcher(enabled=True)
            fleet = ServeFleet(
                model, params, serve_cfg,
                FleetConfig(num_replicas=2, devices_per_replica=4,
                            respawn_delay_ticks=1),
                watcher=watcher)
            return fleet, watcher

        fleet_a, _ = build()
        # the two replicas genuinely sit on distinct device slices
        devs0 = {d.id for d in fleet_a.replicas[0].devices}
        devs1 = {d.id for d in fleet_a.replicas[1].devices}
        assert len(devs0) == len(devs1) == 4 and not (devs0 & devs1)
        clean = fleet_a.run(trace())
        stats_a = fleet_a.stats()
        assert stats_a["requests_ok"] == 13       # 10 + 3 burst
        assert stats_a["lost_requests"] == 0
        clean_tokens = {c.rid: list(map(int, c.tokens)) for c in clean}

        fleet_b, watcher = build()
        with faults.inject_replica_loss(0, 3) as st:
            chaos = fleet_b.run(trace())
        stats_b = fleet_b.stats()
        assert st["fired"] == 1
        assert stats_b["lost_requests"] == 0
        assert stats_b["migrated_requests"] >= 1
        assert stats_b["replicas_respawned"] == 1
        assert stats_b["rebalance_latency_ms"] is not None
        chaos_tokens = {c.rid: list(map(int, c.tokens)) for c in chaos}
        assert chaos_tokens == clean_tokens       # greedy identity
        assert stats_b["goodput_tokens"] >= 0.9 * stats_a["goodput_tokens"]
        assert watcher.recompile_count() == 0
        ladder = 2 * 1 + 2                        # (2,4) x (16,) + decode
        for row in stats_b["replicas"]:
            if row["compile_count"] is not None:
                assert row["compile_count"] == ladder
        # per-tier SLO rollup present for both tiers (diurnal trace
        # mixes interactive/batch)
        assert stats_b["ttft_p99_ms_interactive"] is not None
        assert stats_b["ttft_p99_ms_batch"] is not None


# ---------------------------------------------------------------------------
# bench + schema contract
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestServeFleetBench:
    def test_serve_fleet_bench_contract(self, monkeypatch, capsys):
        monkeypatch.setenv("APEX_TPU_SERVE_SMOKE", "1")
        monkeypatch.syspath_prepend(ROOT)
        import bench

        ret = bench.bench_serve_fleet(8, 3)
        line = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert line["metric"] == "serve_fleet_tokens_per_sec"
        assert line["value"] > 0
        assert ret["lost_requests"] == 0
        assert ret["token_identical"]
        assert ret["replicas_respawned"] >= 1
        assert ret["goodput_ratio"] >= 0.9
        assert ret["recompiles_chaos"] == 0
        assert line["rebalance_latency_ms"] is not None
        for key in ("ttft_p99_ms_interactive", "ttft_p99_ms_batch",
                    "rebalance_latency_ms", "replicas_respawned"):
            assert key in line
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        import bench_schema_check as bsc

        assert bsc.check_metric_line(line, round_n=16, errors=[]) == []
        errs = bsc.check_metric_line(line, round_n=15, errors=[])
        assert any("only defined from round 16" in e for e in errs)


class TestSchemaGateRound16:
    def test_fleet_fields_gated_at_round16(self):
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        import bench_schema_check as bsc

        base = {"metric": "serve_fleet_tokens_per_sec",
                "value": 1.0, "unit": "tokens/sec", "vs_baseline": 1.0,
                "tflops_per_sec": 0.0, "mfu": 0.0,
                "comm_bytes_per_step": 0,
                "measured_comm_bytes_per_step": None,
                "model_flops_per_step_xla": None,
                "peak_hbm_bytes": None, "hbm_headroom_pct": None,
                "compile_count": 4, "lint_violations": None,
                "backend": "cpu-mesh"}
        errs = bsc.check_metric_line(dict(base), round_n=16, errors=[])
        assert sum("serve_fleet line missing" in e for e in errs) == 4
        full = dict(base, ttft_p99_ms_interactive=2.0,
                    ttft_p99_ms_batch=5.0, rebalance_latency_ms=1.5,
                    replicas_respawned=1)
        assert bsc.check_metric_line(dict(full), round_n=16,
                                     errors=[]) == []
        # nullable: a clean run with no rebalance is still valid
        assert bsc.check_metric_line(
            dict(full, rebalance_latency_ms=None, ttft_p99_ms_batch=None),
            round_n=16, errors=[]) == []
        # a pre-16 record carrying them is flagged
        errs = bsc.check_metric_line(dict(full), round_n=15, errors=[])
        assert any("only defined from round 16" in e for e in errs)
        # typed when present
        errs = bsc.check_metric_line(
            dict(full, replicas_respawned="one"), round_n=16, errors=[])
        assert any("must be numeric or null" in e for e in errs)
        # other configs never need them
        other = dict(base, metric="gpt2_345m_tokens_per_sec_per_chip")
        assert bsc.check_metric_line(other, round_n=16, errors=[]) == []


# ---------------------------------------------------------------------------
# telemetry_report: the fleet kind
# ---------------------------------------------------------------------------

class TestFleetReportKind:
    def test_report_aggregates_fleet_events(self, tmp_path, capsys):
        """tools/telemetry_report learns ``kind: fleet``: replica
        table + per-tier rollup + migration/respawn timeline from a
        real fleet run's JSONL."""
        with use_registry(MetricsRegistry(jsonl_dir=str(tmp_path))) \
                as reg:
            fleet = _stub_fleet(FleetConfig(num_replicas=2,
                                            respawn_delay_ticks=1))
            with faults.inject_replica_loss(0, 2):
                fleet.run([_req(i, max_new=5,
                                tier="batch" if i % 4 == 3 else None,
                                arrival=float(i) * 0.4)
                           for i in range(8)])
            reg.flush()
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        import telemetry_report

        paths = [str(p) for p in tmp_path.glob("telemetry-rank*.jsonl")]
        report = telemetry_report.aggregate(
            telemetry_report.load_events(paths))
        f = report["fleet"]
        assert f["respawns"] == 1
        assert f["migrated_requests"] >= 1
        assert f["lost_requests"] == 0
        assert f["last_report"] is not None
        assert f["last_report"]["requests_ok"] == 8
        rows = f["last_report"]["replicas"]
        assert [r["replica"] for r in rows] == [0, 1]
        assert f["last_report"]["by_tier"]["batch"]["requests"] == 2
        events = [row["event"] for row in f["timeline"]]
        assert "replica_state" in events and "migration" in events
        assert "respawn" in events and "rebalance" in events
        # unknown-kind forward-compat footer untouched
        assert report["unknown_kinds"] == {}
        buf = io.StringIO()
        telemetry_report.print_report(report, out=buf)
        text = buf.getvalue()
        assert "serving fleet (apex_tpu.serving.fleet):" in text
        assert "tier batch" in text
        assert "event timeline" in text

    def test_report_rolls_up_kv_handoff_events(self, tmp_path):
        """ISSUE-18: the fleet kind learns the KV-state handoff
        events — kv_handoff totals (count + bytes carried), the
        per-reason kv_fallback split, and the injector's
        kv_corrupt_injected — in the rollup, the timeline, and the
        rendered report."""
        with use_registry(MetricsRegistry(jsonl_dir=str(tmp_path))) \
                as reg:
            reg.event("fleet", "kv_handoff", rid=1, replica=1, slot=0,
                      length=14, cut=13, bytes=65540, tick=3)
            reg.event("fleet", "kv_handoff", rid=2, replica=1, slot=1,
                      length=10, cut=9, bytes=65540, tick=3)
            reg.event("fleet", "kv_fallback", rid=3, replica=1,
                      reason="checksum_mismatch", tick=3)
            reg.event("fleet", "kv_corrupt_injected", replica=0,
                      slot=0, tick=3)
            reg.flush()
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        import telemetry_report

        paths = [str(p) for p in tmp_path.glob("telemetry-rank*.jsonl")]
        report = telemetry_report.aggregate(
            telemetry_report.load_events(paths))
        f = report["fleet"]
        assert f["kv_handoffs"] == 2
        assert f["kv_handoff_bytes"] == 131080
        assert f["kv_fallbacks"] == {"checksum_mismatch": 1}
        assert f["kv_corrupt_injected"] == 1
        events = [row["event"] for row in f["timeline"]]
        assert "kv_handoff" in events and "kv_fallback" in events
        row = next(r for r in f["timeline"]
                   if r["event"] == "kv_handoff")
        assert row["detail"]["bytes"] == 65540
        assert row["detail"]["cut"] == 13
        buf = io.StringIO()
        telemetry_report.print_report(report, out=buf)
        text = buf.getvalue()
        assert "kv handoffs: 2" in text
        assert "checksum_mismatch=1" in text
        assert "1 corrupt injection(s)" in text


# ---------------------------------------------------------------------------
# misc edges
# ---------------------------------------------------------------------------

class TestFleetEdges:
    def test_max_steps_exhaustion_is_non_silent(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=1), num_slots=2)
        for i in range(4):
            fleet.submit(_req(i, max_new=1000))
        with pytest.warns(UserWarning, match="max_steps"):
            done = fleet.run(max_steps=3)
        assert len(done) == 4
        assert all(c.finish_reason == "max_steps" for c in done)

    def test_needs_model_or_factory(self):
        with pytest.raises(ValueError, match="engine_factory"):
            ServeFleet(config=FleetConfig(num_replicas=1))

    def test_robust_config_passes_through(self):
        """The per-replica scheduler inherits the fleet's
        RobustConfig (decode retries, quarantine policy)."""
        rc = RobustConfig(decode_retries=7)
        fleet = _stub_fleet(FleetConfig(num_replicas=1, robust=rc))
        assert fleet.replicas[0].sched.robust.decode_retries == 7

    def test_diurnal_trace_is_deterministic_and_tiered(self):
        a = diurnal_trace(12, seed=3, burst_at=2.0, burst_n=3)
        b = diurnal_trace(12, seed=3, burst_at=2.0, burst_n=3)
        assert len(a) == len(b) == 15
        for x, y in zip(a, b):
            assert x.arrival == y.arrival and x.rid == y.rid
            np.testing.assert_array_equal(x.prompt, y.prompt)
        tiers = {r.tier for r in a}
        assert tiers == {"interactive", "batch"}
        assert a[0].arrival == 0.0
        assert all(a[i].arrival <= a[i + 1].arrival
                   for i in range(len(a) - 1))
        burst = [r for r in a if r.rid >= 12]
        assert len(burst) == 3
        assert len({r.arrival for r in burst}) == 1

    def test_health_counters_and_gauges(self, tmp_path):
        with use_registry(MetricsRegistry(jsonl_dir=str(tmp_path))) \
                as reg:
            fleet = _stub_fleet(FleetConfig(num_replicas=2))
            with faults.inject_replica_loss(1, 1):
                fleet.run([_req(i, max_new=4, arrival=float(i) * 0.2)
                           for i in range(6)])
            assert reg.counter_value("fleet/dispatched") >= 6
            assert reg.counter_value("fleet/migrated") >= 0
            assert reg.counter_value("fleet/respawns") == 1
            assert reg.counter_value("fleet/replicas_quarantined") == 1


# ---------------------------------------------------------------------------
# KV-state migration (ISSUE 18): capture, handoff, corruption fallback
# ---------------------------------------------------------------------------

class TestKVMigrationPolicy:
    def test_stats_carry_migration_fields(self):
        fleet = _stub_fleet(FleetConfig(num_replicas=2))
        fleet.run([_req(0), _req(1)])
        s = fleet.stats()
        assert s["kv_handoffs"] == 0
        assert s["kv_handoff_bytes"] == 0
        assert s["kv_fallback_reprefills"] == 0
        # stubs have no prefix cache -> no fleet-wide store
        assert s["fleet_prefix_hit_rate"] is None

    def test_capture_is_empty_for_stub_engines(self):
        """Engines without ``extract_kv_state`` (stubs, legacy)
        degrade to the token re-prefill migration — no handoff, no
        crash, zero lost."""
        fleet = _stub_fleet(FleetConfig(num_replicas=2,
                                        respawn_delay_ticks=1))
        with faults.inject_replica_loss(0, 1) as st:
            fleet.run([_req(i, max_new=6) for i in range(4)])
        s = fleet.stats()
        assert st["fired"] == 1
        assert s["lost_requests"] == 0
        assert s["kv_handoffs"] == 0

    def test_model_parallel_fleet_partition(self):
        with pytest.raises(ValueError, match="model_parallel"):
            FleetConfig(model_parallel=0)
        fleet = _stub_fleet(FleetConfig(num_replicas=2,
                                        model_parallel=2))
        for rep in fleet.replicas:
            assert rep.mesh is not None
            assert rep.mesh.axis_names == ("data", "tp")
            assert dict(zip(rep.mesh.axis_names,
                            rep.mesh.devices.shape))["tp"] == 2


@pytest.mark.multi_device
@pytest.mark.slow
class TestFleetTPMigrationE2E:
    """ISSUE-18 chaos acceptance: TP-sharded replicas under the fleet,
    constant-cost KV-state migration, loud checksum fallback."""

    def _cfg(self):
        return TransformerConfig(
            hidden_size=64, num_layers=2, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=64,
            compute_dtype=jnp.bfloat16, use_flash_attention=False,
            normalization="rmsnorm", position_embedding_type="rope",
            activation="swiglu", num_query_groups=4,
            ffn_hidden_size=128)

    def _params(self, cfg):
        parallel_state.destroy_model_parallel()
        return GPTModel(cfg).init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 8), jnp.int32))["params"]

    def _serve_cfg(self):
        return ServeConfig(batch_buckets=(2,), prefill_buckets=(4, 16),
                           num_slots=4, eos_token_id=None,
                           temperature=0.0, prefix_cache=True,
                           prefix_min_len=2)

    def _trace(self, vocab):
        rs = np.random.RandomState(7)
        return [Request(rid=i,
                        prompt=rs.randint(0, vocab, 12).astype(np.int32),
                        max_new_tokens=8, arrival=0.0)
                for i in range(4)]

    def _run(self, cfg, params, *, kill=None, corrupt=None,
             jsonl_dir=None):
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2, devices=jax.devices()[:2])
        model = GPTModel(cfg, decode=True)
        reg = MetricsRegistry(enabled=True, jsonl_dir=jsonl_dir)
        watcher = CompileWatcher(enabled=True)
        fleet = ServeFleet(model, params, self._serve_cfg(),
                           FleetConfig(num_replicas=2, model_parallel=2,
                                       respawn_delay_ticks=1),
                           registry=reg, watcher=watcher)
        try:
            if kill is not None:
                faults.arm_replica_loss(*kill)
            if corrupt is not None:
                faults.arm_kv_corrupt(*corrupt)
            done = fleet.run(self._trace(cfg.vocab_size))
        finally:
            faults.disarm_replica_loss()
            faults.disarm_kv_corrupt()
            parallel_state.destroy_model_parallel()
        return ({c.rid: list(map(int, c.tokens)) for c in done},
                fleet.stats(), watcher)

    def test_tp_kill_migrates_kv_token_identical(self, tmp_path):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        cfg = self._cfg()
        params = self._params(cfg)
        clean, s0, _ = self._run(cfg, params)
        assert s0["lost_requests"] == 0
        chaos, s1, watcher = self._run(
            cfg, params, kill=(0, 3), jsonl_dir=str(tmp_path))
        assert s1["lost_requests"] == 0
        assert s1["migrated_requests"] >= 1
        assert s1["kv_handoffs"] >= 1
        assert s1["kv_handoff_bytes"] > 0
        assert s1["kv_fallback_reprefills"] == 0
        assert chaos == clean                     # greedy identity
        assert s1["fleet_prefix_hit_rate"] is not None
        assert watcher.recompile_count() == 0
        events = []
        for p in tmp_path.glob("*.jsonl"):
            events += [json.loads(l) for l in p.open()]
        handoffs = [e for e in events if e.get("name") == "kv_handoff"]
        assert len(handoffs) == s1["kv_handoffs"]
        for e in handoffs:
            assert e["bytes"] > 0 and e["cut"] > 0

    def test_kv_corrupt_falls_back_loudly_once(self, tmp_path):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        cfg = self._cfg()
        params = self._params(cfg)
        got, s, _ = self._run(cfg, params, kill=(0, 3),
                              corrupt=(0, 3), jsonl_dir=str(tmp_path))
        assert s["lost_requests"] == 0
        assert s["requests_ok"] == 4              # streams complete
        assert s["kv_fallback_reprefills"] == 1   # exactly one, loud
        events = []
        for p in tmp_path.glob("*.jsonl"):
            events += [json.loads(l) for l in p.open()]
        fb = [e for e in events if e.get("name") == "kv_fallback"]
        assert len(fb) == 1
        assert fb[0]["reason"] == "checksum_mismatch"
        assert any(e.get("name") == "kv_corrupt_injected"
                   for e in events)

    def test_fleet_wide_prefix_beats_single_replica(self):
        """A system prompt prefilled by one replica hits on the other:
        the shared store's fleet-wide hit rate is never below what a
        single replica achieves on the same trace."""
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        cfg = self._cfg()
        params = self._params(cfg)
        rs = np.random.RandomState(11)
        system = rs.randint(0, cfg.vocab_size, 8).astype(np.int32)

        def trace():
            return [Request(
                rid=i,
                prompt=np.concatenate(
                    [system,
                     rs.randint(0, cfg.vocab_size, 3).astype(np.int32)]),
                max_new_tokens=4, arrival=0.0) for i in range(6)]

        def run(n_replicas):
            parallel_state.destroy_model_parallel()
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size_=2,
                devices=jax.devices()[:2])
            model = GPTModel(cfg, decode=True)
            fleet = ServeFleet(model, params, self._serve_cfg(),
                               FleetConfig(num_replicas=n_replicas,
                                           model_parallel=2))
            rs.seed(11); rs.randint(0, cfg.vocab_size, 8)  # re-sync tails
            done = fleet.run(trace())
            s = fleet.stats()
            parallel_state.destroy_model_parallel()
            assert len(done) == 6
            return s["fleet_prefix_hit_rate"]

        single = run(1)
        fleet_wide = run(2)
        assert single is not None and fleet_wide is not None
        assert fleet_wide >= single > 0
