"""_logging: RankInfoFormatter emits the rank tuple; the rank-info
provider is cached after first resolution (satellite of ISSUE 2 — the
formatter used to re-run the import machinery on EVERY log record)."""

import logging

from apex_tpu import _logging


def _format_one(msg="hello"):
    fmt = _logging.RankInfoFormatter("%(rank_info)s %(message)s")
    record = logging.LogRecord("apex_tpu.test", logging.INFO, __file__,
                               1, msg, None, None)
    return fmt.format(record)


def test_formatter_emits_rank_tuple():
    out = _format_one()
    assert out.endswith(" hello")
    rank = out[:-len(" hello")]
    # uninitialized model parallel on a single process -> the jax
    # process-index fallback, a 1-tuple
    assert rank == "(0,)"


def test_provider_cached_after_first_record(monkeypatch):
    _format_one()
    # both providers resolved (module objects or False), never None again
    assert _logging._PARALLEL_STATE is not None
    assert _logging._JAX is not None

    # a poisoned import path must not matter anymore: caching means no
    # re-import happens on later records
    import builtins

    real_import = builtins.__import__

    def exploding_import(name, *a, **kw):
        if "parallel_state" in name or name == "jax":
            raise ImportError(f"re-import of {name} on the hot path")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", exploding_import)
    assert _format_one("again").endswith(" again")


def test_rank_info_tracks_model_parallel_init():
    """Caching the module must not freeze the ANSWER: once model
    parallel initializes, records pick up the full rank tuple."""
    from apex_tpu.transformer import parallel_state

    _format_one()  # cache the provider pre-init
    parallel_state.initialize_model_parallel(1, 1)
    try:
        rank = _logging._get_rank_info()
        assert len(rank) > 1  # (dp, tp, pp, ...) tuple, not the fallback
    finally:
        parallel_state.destroy_model_parallel()
