"""Sequence-parallel gradient sync vs a tp=1 oracle.

Verifies ``allreduce_sequence_parallel_grads`` + the model path predicate:
under SP, row-parallel output biases (added after the reduce-scatter) have
seq-partial grads that need the tp psum, while column-parallel biases are
per-rank shards whose grads are already complete and must NOT be touched
(reference: sequence_parallel_enabled tagging, apex/transformer/layers/
layer_norm.py:26-99 and tensor_parallel/layers.py).
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    allreduce_sequence_parallel_grads,
)
from apex_tpu.models.transformer_lm import is_sequence_parallel_param

H, FFN, S, B = 8, 16, 8, 2


class TinyParallelMLP(nn.Module):
    sequence_parallel: bool = False

    @nn.compact
    def __call__(self, x):
        x = ColumnParallelLinear(
            H, FFN, bias=True, gather_output=False,
            sequence_parallel_enabled=self.sequence_parallel,
            name="dense_h_to_4h")(x)
        x = jax.nn.gelu(x)
        x = RowParallelLinear(
            FFN, H, bias=True, input_is_parallel=True,
            sequence_parallel_enabled=self.sequence_parallel,
            name="dense_4h_to_h")(x)
        return x


@pytest.fixture
def tp2_mesh():
    return Mesh(np.asarray(jax.devices()[:2]), ("tp",))


def test_sp_grads_match_tp1_oracle(tp2_mesh, rng):
    x = jnp.asarray(rng.randn(S, B, H).astype(np.float32))
    w = jnp.asarray(rng.randn(S, B, H).astype(np.float32))

    # ---- tp=1 oracle -------------------------------------------------
    parallel_state.destroy_model_parallel()
    model1 = TinyParallelMLP(sequence_parallel=False)
    params1 = model1.init(jax.random.PRNGKey(0), x)["params"]

    def loss1(p):
        return jnp.sum(model1.apply({"params": p}, x) * w)

    g_ref = jax.grad(loss1)(params1)

    # ---- tp=2 + SP ---------------------------------------------------
    parallel_state.set_tensor_model_parallel_world_size(2)
    model2 = TinyParallelMLP(sequence_parallel=True)

    def shard(params1, rank):
        col_k = params1["dense_h_to_4h"]["weight"]  # [H, FFN] -> [H, FFN/2]
        col_b = params1["dense_h_to_4h"]["bias"]
        row_k = params1["dense_4h_to_h"]["weight"]  # [FFN, H] -> [FFN/2, H]
        row_b = params1["dense_4h_to_h"]["bias"]    # replicated
        f = FFN // 2
        return {
            "dense_h_to_4h": {"weight": col_k[:, rank * f:(rank + 1) * f],
                              "bias": col_b[rank * f:(rank + 1) * f]},
            "dense_4h_to_h": {"weight": row_k[rank * f:(rank + 1) * f],
                              "bias": row_b},
        }

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), shard(params1, 0), shard(params1, 1))

    @functools.partial(jax.shard_map, mesh=tp2_mesh,
                       in_specs=(P("tp"), P("tp"), P("tp")),
                       out_specs=P("tp"), check_vma=False)
    def grads_sp(stacked_params, x_shard, w_shard):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)

        def loss(p):
            # local summand only: cross-rank terms reach this rank's param
            # grads through the collectives' transposes (a psum here would
            # double-seed the replicated loss)
            out = model2.apply({"params": p}, x_shard)  # [S/2, B, H]
            return jnp.sum(out * w_shard)

        g = jax.grad(loss)(params)
        g = allreduce_sequence_parallel_grads(g, is_sequence_parallel_param)
        return jax.tree_util.tree_map(lambda a: a[None], g)

    g2 = grads_sp(stacked, x, w)

    # column shards must equal the oracle slices (NOT summed over tp)
    f = FFN // 2
    for r in range(2):
        np.testing.assert_allclose(
            np.asarray(g2["dense_h_to_4h"]["bias"][r]),
            np.asarray(g_ref["dense_h_to_4h"]["bias"][r * f:(r + 1) * f]),
            rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(g2["dense_h_to_4h"]["weight"][r]),
            np.asarray(g_ref["dense_h_to_4h"]["weight"][:, r * f:(r + 1) * f]),
            rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(g2["dense_4h_to_h"]["weight"][r]),
            np.asarray(g_ref["dense_4h_to_h"]["weight"][r * f:(r + 1) * f]),
            rtol=1e-4, atol=1e-4)
        # row bias is replicated: after the SP psum each rank holds the
        # full grad
        np.testing.assert_allclose(
            np.asarray(g2["dense_4h_to_h"]["bias"][r]),
            np.asarray(g_ref["dense_4h_to_h"]["bias"]),
            rtol=1e-4, atol=1e-4)


def test_predicate_classification():
    assert is_sequence_parallel_param("layers_0/input_layernorm/scale")
    assert is_sequence_parallel_param("position_embeddings/weight")
    assert is_sequence_parallel_param("layers_0/attention/dense/bias")
    assert is_sequence_parallel_param("layers_0/mlp/dense_4h_to_h/bias")
    assert not is_sequence_parallel_param(
        "layers_0/attention/query_key_value/bias")
    assert not is_sequence_parallel_param("layers_0/mlp/dense_h_to_4h/bias")
    assert not is_sequence_parallel_param("layers_0/mlp/dense_4h_to_h/kernel")


def test_pp_boundary_payload_is_tp_sharded_under_sp(mesh8):
    """VERDICT round-1 'missing #4': pin the pipelined p2p payload to the
    sequence-sharded (1/tp) layout under SP — the layout-level equivalent
    of the reference's scatter-gather p2p compression
    (p2p_communication.py:117-400)."""
    import dataclasses

    import jax.numpy as jnp

    from apex_tpu.models.transformer_lm import TransformerConfig
    from apex_tpu.transformer.testing.gpt_3d import boundary_tensor_shape

    cfg = TransformerConfig(
        hidden_size=64, num_layers=4, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32,
        compute_dtype=jnp.bfloat16, sequence_parallel=True)
    tp = mesh8.shape["tp"]
    assert boundary_tensor_shape(cfg, mesh8, 16, 2) == (16 // tp, 2, 64)
    dense = dataclasses.replace(cfg, sequence_parallel=False)
    assert boundary_tensor_shape(dense, mesh8, 16, 2) == (16, 2, 64)
