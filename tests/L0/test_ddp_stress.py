"""DDP stress test — the TPU analog of the reference's race test.

Parity: reference tests/distributed/DDP/ddp_race_condition_test.py stresses
the grad-hook/stream overlap machinery and checks gradient values. On TPU
the failure surface is different: bucket boundary bookkeeping (flatten /
psum / split) and buffer donation under jit. This stresses both: many
odd-shaped mixed-dtype leaves at randomized bucket caps must always match
the per-leaf path, and donated training steps must stay correct across
iterations.
"""

import pytest
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.distributed import (
    all_reduce_gradients,
    all_reduce_gradients_bucketed,
    plan_buckets,
)


def _random_tree(rng, n_leaves=37):
    tree = {}
    for i in range(n_leaves):
        shape = tuple(rng.randint(1, 7, size=rng.randint(1, 4)))
        dtype = [np.float32, np.float32, np.float16][i % 3]
        tree[f"p{i:02d}"] = jnp.asarray(rng.randn(*shape).astype(dtype))
    return tree


@pytest.mark.slow
def test_bucketed_matches_per_leaf_across_random_caps(rng):
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))
    grads = _random_tree(rng)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(),
                       out_specs=P(), check_vma=False)
    def per_leaf(g):
        return all_reduce_gradients(g, "dp")

    expected = per_leaf(grads)
    for cap in [1, 3, 17, 64, 1000, 10 ** 9]:
        @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(),
                           out_specs=P(), check_vma=False)
        def bucketed(g, cap=cap):
            return all_reduce_gradients_bucketed(g, "dp", message_size=cap)

        out = bucketed(grads)
        for k in grads:
            np.testing.assert_allclose(
                np.asarray(out[k], np.float32),
                np.asarray(expected[k], np.float32),
                rtol=1e-3, atol=1e-3,
                err_msg=f"cap={cap} leaf={k}")


def test_plan_buckets_partitions_every_leaf_exactly_once(rng):
    leaves = jax.tree_util.tree_leaves(_random_tree(rng, n_leaves=50))
    for cap in [1, 10, 100, 10 ** 8]:
        buckets = plan_buckets(leaves, message_size=cap)
        seen = sorted(i for b in buckets for i in b)
        assert seen == list(range(len(leaves))), f"cap={cap}"
        # same-bucket leaves share a dtype
        for b in buckets:
            dts = {jnp.dtype(leaves[i].dtype) for i in b}
            assert len(dts) == 1


def test_donated_train_step_stays_correct(rng):
    """Donated buffers must not corrupt later iterations (the aliasing
    analog of the reference's stream-lifetime `record_stream` pinning)."""
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("dp",))
    x = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    y = jnp.asarray(rng.randn(16, 1).astype(np.float32))
    w = {"w": jnp.zeros((8, 1), jnp.float32)}

    def step_fn(w, x, y):
        loss, grads = jax.value_and_grad(
            lambda p: jnp.mean((x @ p["w"] - y) ** 2))(w)
        grads = all_reduce_gradients_bucketed(grads, "dp", message_size=4)
        return jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, w, grads), loss

    sharded = jax.shard_map(step_fn, mesh=mesh,
                            in_specs=(P(), P("dp"), P("dp")),
                            out_specs=(P(), P()), check_vma=False)
    donated = jax.jit(sharded, donate_argnums=(0,))
    plain = jax.jit(sharded)

    copy = lambda t: jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), t)  # noqa: E731
    w_d, w_p = copy(w), copy(w)
    for _ in range(10):
        w_d, loss_d = donated(w_d, x, y)
        w_p, loss_p = plain(w_p, x, y)
    np.testing.assert_allclose(np.asarray(w_d["w"]), np.asarray(w_p["w"]),
                               rtol=1e-6, atol=1e-6)
