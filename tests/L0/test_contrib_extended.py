"""Extended contrib tests: multihead attn, transducer, sparsity/ASP, halo
exchange, spatial bottleneck, groupbn.

Mirrors reference apex/contrib/test/{multihead_attn,transducer,sparsity,
peer_memory}/test_*.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from apex_tpu.testing import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
from apex_tpu.contrib.peer_memory import PeerHaloExchanger1d, halo_exchange_1d
from apex_tpu.contrib.sparsity import ASP, m4n2_1d
from apex_tpu.contrib.transducer import TransducerJoint, TransducerLoss


class TestSelfMultiheadAttn:
    def test_matches_torch_mha(self, rng):
        """Vs torch.nn.MultiheadAttention with copied weights (the
        reference's own oracle, apex/contrib/test/multihead_attn)."""
        s, b, h, nh = 6, 2, 16, 4
        x = rng.randn(s, b, h).astype(np.float32)
        m = SelfMultiheadAttn(embed_dim=h, num_heads=nh, bias=False,
                              impl="default")
        params = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
        # force the unfused (einsum) path with an all-false mask
        y = m.apply(params, jnp.asarray(x),
                    attn_mask=jnp.zeros((s, s), bool))

        qkv_w = np.asarray(params["params"]["qkv_weight"])  # [h, 3h]
        out_w = np.asarray(params["params"]["out_proj_weight"])  # [h, h]
        t = torch.nn.MultiheadAttention(h, nh, bias=False)
        with torch.no_grad():
            t.in_proj_weight.copy_(torch.tensor(qkv_w.T))
            t.out_proj.weight.copy_(torch.tensor(out_w.T))
            ref, _ = t(torch.tensor(x), torch.tensor(x), torch.tensor(x))
        np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-3,
                                   atol=1e-4)

    def test_norm_add(self, rng):
        m = SelfMultiheadAttn(embed_dim=16, num_heads=4,
                              include_norm_add=True, impl="default")
        x = jnp.asarray(rng.randn(4, 2, 16).astype(np.float32))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x, attn_mask=jnp.zeros((4, 4), bool))
        assert y.shape == x.shape


class TestTransducer:
    def test_joint(self, rng):
        f = jnp.asarray(rng.randn(2, 3, 8).astype(np.float32))
        g = jnp.asarray(rng.randn(2, 4, 8).astype(np.float32))
        joint = TransducerJoint(relu=True)
        out = joint(f, g)
        assert out.shape == (2, 3, 4, 8)
        ref = np.maximum(np.asarray(f)[:, :, None] + np.asarray(g)[:, None], 0)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)

    def test_loss_matches_torchaudio_style_reference(self, rng):
        """Check vs a brute-force DP reference (the role of the
        reference's _transducer_ref.py)."""
        B, T, U, V = 2, 4, 3, 5
        x = rng.randn(B, T, U + 1, V).astype(np.float32)
        labels = rng.randint(1, V, size=(B, U)).astype(np.int32)
        f_len = np.array([T, T - 1], np.int32)
        y_len = np.array([U, U - 1], np.int32)

        loss = TransducerLoss()(jnp.asarray(x), jnp.asarray(labels),
                                jnp.asarray(f_len), jnp.asarray(y_len))

        # brute-force alpha recursion in numpy
        def ref_one(xb, lab, tl, ul):
            lp = torch.log_softmax(torch.tensor(xb), dim=-1).numpy()
            alpha = np.full((tl, ul + 1), -np.inf)
            alpha[0, 0] = 0.0
            for t in range(tl):
                for u in range(ul + 1):
                    if t == 0 and u == 0:
                        continue
                    cands = []
                    if t > 0:
                        cands.append(alpha[t - 1, u] + lp[t - 1, u, 0])
                    if u > 0:
                        cands.append(alpha[t, u - 1] + lp[t, u - 1, lab[u - 1]])
                    alpha[t, u] = np.logaddexp.reduce(cands)
            return -(alpha[tl - 1, ul] + lp[tl - 1, ul, 0])

        for i in range(B):
            expected = ref_one(x[i], labels[i], f_len[i], y_len[i])
            np.testing.assert_allclose(float(loss[i]), expected, rtol=1e-4)

    def test_loss_gradients_finite(self, rng):
        B, T, U, V = 1, 3, 2, 4
        x = jnp.asarray(rng.randn(B, T, U + 1, V).astype(np.float32))
        labels = jnp.asarray(rng.randint(1, V, size=(B, U)))
        g = jax.grad(lambda x_: jnp.sum(TransducerLoss()(
            x_, labels, jnp.asarray([T]), jnp.asarray([U]))))(x)
        assert np.isfinite(np.asarray(g)).all()


class TestSparsity:
    def test_m4n2_keeps_two_of_four(self, rng):
        w = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        mask = m4n2_1d(w)
        groups = np.asarray(mask).reshape(8, 4, 4)
        np.testing.assert_array_equal(groups.sum(-1), np.full((8, 4), 2))

    def test_mask_keeps_largest(self, rng):
        w = jnp.asarray([[1.0, 5.0, 0.1, 3.0]])
        mask = m4n2_1d(w)
        np.testing.assert_array_equal(np.asarray(mask), [[0, 1, 0, 1]])

    def test_asp_roundtrip(self, rng):
        params = {"dense": {"kernel": jnp.asarray(
            rng.randn(32, 32).astype(np.float32))},
            "norm": {"scale": jnp.ones((32,))}}
        ASP.init_model_for_pruning(params)
        masks = ASP.compute_sparse_masks(params)
        assert ASP.is_sparsity_enabled()
        pruned = ASP.apply_masks(params, masks)
        k = np.asarray(pruned["dense"]["kernel"])
        assert (k == 0).mean() == pytest.approx(0.5, abs=0.01)
        # norm params untouched
        np.testing.assert_array_equal(np.asarray(pruned["norm"]["scale"]),
                                      np.ones((32,)))


class TestHaloExchange:
    def test_halo_values(self, rng):
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("spatial",))
        x = jnp.asarray(np.arange(4 * 4 * 2 * 3,
                                  dtype=np.float32).reshape(4, 4, 2, 3))
        # shard H=4*4 rows over 4 devices -> local [1(batch?)...]
        # use [N=1, H=16, W=2, C=3] sharded on H
        x = x.reshape(1, 16, 2, 3)

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=P(None, "spatial"), out_specs=P(None, "spatial"))
        def f(x_local):
            ex = PeerHaloExchanger1d(half_halo=1)(x_local)
            # returns [N, local_H + 2, W, C]; strip halos again for output
            return ex[:, 1:-1]

        out = f(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_interior_halo_correct(self):
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("spatial",))
        x = jnp.arange(16.0).reshape(1, 16, 1, 1)

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=P(None, "spatial"),
                           out_specs=P(None, "spatial"))
        def f(x_local):
            top, bottom = halo_exchange_1d(x_local, 1, "spatial", dim=1)
            # return the received halos appended in the local frame
            return jnp.concatenate(
                [top[:, :1], bottom[:, :1]], axis=3)

        out = np.asarray(f(x))  # [1, 4, 1, 2]: one row per device
        # device 1 (rows 4..7): top halo = row 3, bottom halo = row 8
        assert out[0, 1, 0, 0] == 3.0
        assert out[0, 1, 0, 1] == 8.0


class TestBottleneck:
    def test_bottleneck_forward(self, rng):
        m = Bottleneck(in_channels=8, bottleneck_channels=4, out_channels=16,
                       dtype=jnp.float32)
        x = jnp.asarray(rng.randn(2, 8, 8, 8).astype(np.float32))
        variables = m.init(jax.random.PRNGKey(0), x, train=True)
        y, _ = m.apply(variables, x, train=True, mutable=["batch_stats"])
        assert y.shape == (2, 8, 8, 16)

    @pytest.mark.slow
    def test_spatial_matches_dense(self, rng):
        """Spatial-parallel bottleneck == single-device bottleneck on the
        gathered input (reference
        test_peer_halo_exchange_module.py's oracle)."""
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("spatial",))
        m = SpatialBottleneck(in_channels=6, bottleneck_channels=4,
                              out_channels=6, dtype=jnp.float32)
        x = jnp.asarray(rng.randn(1, 16, 4, 6).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), P(None, "spatial")),
                           out_specs=P(None, "spatial"))
        def run(variables, x_local):
            y, _ = m.apply(variables, x_local, train=True,
                           mutable=["batch_stats"])
            return y

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                           out_specs=P())
        def init_fn(key, x_local):
            return m.init(key, x_local, train=True)

        variables = init_fn(jax.random.PRNGKey(0),
                            jnp.zeros((1, 4, 4, 6), jnp.float32))
        y_sharded = run(variables, x)
        assert y_sharded.shape == x.shape
        assert np.isfinite(np.asarray(y_sharded)).all()
