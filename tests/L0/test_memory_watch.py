"""HBM budget accounting + OOM post-mortems (ISSUE 5 tentpole):
step_memory reports, capacity resolution, the live-buffer census,
preflight, the oom_guard/guarded_call post-mortem path with the
deterministic alloc-failure injector, ZeRO state-bytes accounting, the
ddp_memwatch bench e2e, and the tools/memory_report.py renderer."""

import glob
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import resilience
from apex_tpu.resilience import faults
from apex_tpu.telemetry import memory
from apex_tpu.telemetry.registry import MetricsRegistry, use_registry

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tools"))


# -- step_memory ------------------------------------------------------------

class TestStepMemory:
    def test_report_fields(self):
        f = jax.jit(lambda x: jnp.tanh(x @ x))
        rep = memory.step_memory(f, jnp.ones((32, 32)))
        assert rep is not None
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "generated_code_bytes", "alias_bytes", "peak_bytes",
                    "capacity_bytes", "headroom_frac", "backend"):
            assert key in rep
        assert rep["argument_bytes"] == 32 * 32 * 4
        assert rep["output_bytes"] == 32 * 32 * 4
        assert rep["peak_bytes"] >= rep["argument_bytes"]
        assert 0.0 < rep["headroom_frac"] <= 1.0

    def test_traceable_fn_is_jitted_on_the_fly(self):
        rep = memory.step_memory(lambda x: x * 2, jnp.ones((8,)))
        assert rep is not None and rep["argument_bytes"] == 32

    def test_capacity_env_override(self, monkeypatch):
        monkeypatch.setenv(memory.ENV_HBM_GB, "2.5")
        assert memory.hbm_capacity_bytes() == int(2.5e9)
        monkeypatch.delenv(memory.ENV_HBM_GB)
        assert memory.hbm_capacity_bytes("cpu") == \
            memory._HBM_DEFAULTS_BYTES["cpu"]

    def test_gauge_and_event_and_trend(self, tmp_path):
        memory.reset_trend()
        reg = MetricsRegistry(jsonl_dir=str(tmp_path))
        with use_registry(reg):
            f = jax.jit(lambda x: x + 1)
            memory.step_memory(f, jnp.ones((16,)))
        snap = reg.snapshot()
        assert "memory/hbm_headroom" in snap["gauges"]
        assert "memory/peak_hbm_bytes" in snap["gauges"]
        assert len(memory.headroom_trend()) == 1
        events = []
        for path in glob.glob(str(tmp_path / "*.jsonl")):
            with open(path) as f_:
                events.extend(json.loads(l) for l in f_ if l.strip())
        mems = [e for e in events if e["kind"] == "memory"
                and e["name"] == "step_memory"]
        assert mems and mems[0]["peak_bytes"] > 0

    def test_record_false_leaves_no_trace(self):
        memory.reset_trend()
        f = jax.jit(lambda x: x - 1)
        memory.step_memory(f, jnp.ones((8,)), record=False)
        assert memory.headroom_trend() == []

    def test_donated_args_discount_alias_bytes(self):
        @jax.jit
        def plain(x):
            return x * 2

        import functools

        @functools.partial(jax.jit, donate_argnums=(0,))
        def donated(x):
            return x * 2

        x = jnp.ones((256,))
        rep_p = memory.step_memory(plain, x, record=False)
        rep_d = memory.step_memory(donated, x, record=False)
        assert rep_d["alias_bytes"] > 0
        assert rep_d["peak_bytes"] < rep_p["peak_bytes"]


# -- census -----------------------------------------------------------------

class TestCensus:
    def test_labels_and_grouping(self):
        params = {"w": jnp.ones((64, 64)), "b": jnp.ones((64,))}
        # top_k=0 = untruncated: this test pins label MATCHING — under
        # a full suite run enough unrelated arrays are live (compiled
        # executables' constants, cached engines) that a 16 KiB labeled
        # group cannot be guaranteed a top-10-by-bytes seat
        census = memory.live_buffer_census(top_k=0,
                                           labels={"params": params})
        assert census["total_arrays"] >= 2
        assert census["total_bytes"] > 0
        labeled = [g for g in census["groups"] if g["label"] == "params"]
        assert labeled, census["groups"]
        assert labeled[0]["bytes"] >= labeled[0]["count"]

    def test_top_k_truncation_accounts_dropped(self):
        arrays = [jnp.full((i + 1,), 1.0) for i in range(6)]  # noqa: F841
        census = memory.live_buffer_census(top_k=2)
        assert len(census["groups"]) == 2
        assert census["dropped_groups"] >= 1
        # top-K is by bytes, descending
        assert census["groups"][0]["bytes"] >= census["groups"][1]["bytes"]


# -- preflight --------------------------------------------------------------

class TestPreflight:
    def test_within_budget_is_quiet(self):
        rep = memory.preflight(jax.jit(lambda x: x + 1), jnp.ones((8,)))
        assert rep is not None and not rep["over_budget"]

    def test_over_budget_warns(self, monkeypatch):
        monkeypatch.setenv(memory.ENV_HBM_GB, "1e-6")  # 1000 bytes
        with pytest.warns(UserWarning, match="exceeds"):
            rep = memory.preflight(jax.jit(lambda x: x @ x),
                                   jnp.ones((64, 64)))
        assert rep["over_budget"]

    def test_strict_raises_before_dispatch(self, monkeypatch):
        monkeypatch.setenv(memory.ENV_HBM_GB, "1e-6")
        with pytest.raises(memory.MemoryBudgetError, match="RESOURCE"):
            memory.preflight(jax.jit(lambda x: x @ x),
                             jnp.ones((64, 64)), strict=True)


# -- the OOM post-mortem path -----------------------------------------------

class TestOomPostmortem:
    def test_is_oom_error_matches_xla_and_synthetic(self):
        assert memory.is_oom_error(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                         "1073741824 bytes"))
        with pytest.raises(faults.SyntheticResourceExhausted) as ei:
            faults.inject_alloc_failure(3, 3)
        assert memory.is_oom_error(ei.value)
        assert not memory.is_oom_error(ValueError("shape mismatch"))

    def test_injector_is_identity_when_unarmed(self, monkeypatch):
        monkeypatch.delenv(faults.ENV_ALLOC_STEP, raising=False)
        faults.inject_alloc_failure(3)          # env unarmed: no-op
        faults.inject_alloc_failure(3, 5)       # wrong step: no-op

    def test_injector_env_gating(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_ALLOC_STEP, "2")
        faults.inject_alloc_failure(1)
        with pytest.raises(faults.SyntheticResourceExhausted,
                           match="RESOURCE_EXHAUSTED"):
            faults.inject_alloc_failure(2)

    def test_oom_guard_writes_postmortem_and_reraises(self, tmp_path):
        memory.reset_trend()
        params = {"w": jnp.ones((32, 32))}
        memory.step_memory(jax.jit(lambda p: p["w"] * 2), params,
                           record=True)  # seed the trend
        with pytest.raises(memory.HBMExhaustedError) as ei:
            with memory.oom_guard(str(tmp_path),
                                  labels={"params": params}):
                faults.inject_alloc_failure(0, 0)
        assert isinstance(ei.value.__cause__,
                          faults.SyntheticResourceExhausted)
        path = tmp_path / "memory-postmortem-rank0.json"
        assert path.exists()
        with open(path) as f:
            pm = json.load(f)
        assert pm["reason"] == "resource_exhausted"
        assert pm["census"]["total_bytes"] > 0
        assert len(pm["headroom_trend"]) == 1
        assert pm["last_step_memory"]["peak_bytes"] > 0
        assert "RESOURCE_EXHAUSTED" in pm["error"]
        assert memory.last_postmortem()["path"] == str(path)

    def test_oom_guard_passes_other_errors_through(self, tmp_path):
        with pytest.raises(ValueError, match="not an OOM"):
            with memory.oom_guard(str(tmp_path)):
                raise ValueError("not an OOM")
        assert not (tmp_path / "memory-postmortem-rank0.json").exists()

    def test_guarded_call_wires_through_resilience(self, tmp_path):
        def dispatch(i):
            faults.inject_alloc_failure(i, 1)
            return i * 2

        assert resilience.guarded_call(dispatch, 0,
                                       oom_dir=str(tmp_path)) == 0
        with pytest.raises(resilience.HBMExhaustedError,
                           match="post-mortem"):
            resilience.guarded_call(dispatch, 1, oom_dir=str(tmp_path))
        assert (tmp_path / "memory-postmortem-rank0.json").exists()

    def test_postmortem_event_lands_in_registry(self, tmp_path):
        reg = MetricsRegistry(jsonl_dir=str(tmp_path))
        with use_registry(reg):
            memory.oom_postmortem(RuntimeError("RESOURCE_EXHAUSTED: x"),
                                  str(tmp_path))
        events = []
        for path in glob.glob(str(tmp_path / "*.jsonl")):
            with open(path) as f:
                events.extend(json.loads(l) for l in f if l.strip())
        pms = [e for e in events if e["kind"] == "memory"
               and e["name"] == "postmortem"]
        assert pms and pms[0]["path"].endswith(
            "memory-postmortem-rank0.json")


# -- ZeRO state bytes -------------------------------------------------------

class TestZeroStateBytes:
    def _params(self):
        rng = np.random.RandomState(0)
        return {"w": jnp.asarray(rng.randn(300, 4), jnp.float32),
                "b": jnp.zeros((4,), jnp.float32)}

    def test_adam_sharded_vs_unsharded(self):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        opt = DistributedFusedAdam()
        rep = opt.state_bytes(self._params(), world=8)
        n, padded = rep["n_elements"], rep["padded_elements"]
        assert n == 1204 and padded % 8 == 0
        assert rep["unsharded_state_bytes"] == 3 * padded * 4
        assert rep["sharded_state_bytes"] == 3 * (padded // 8) * 4
        assert rep["residual_bytes"] == 0
        assert rep["savings_ratio"] == pytest.approx(8.0)

    def test_int8_residual_is_full_length_and_honest(self):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        opt = DistributedFusedAdam(compress=True)
        rep = opt.state_bytes(self._params(), world=8)
        padded = rep["padded_elements"]
        assert padded % (8 * opt.compress_block_size) == 0
        assert rep["residual_bytes"] == padded * 4
        assert rep["sharded_state_bytes"] == \
            3 * (padded // 8) * 4 + padded * 4
        # the residual floors the saving below the clean 8x
        assert 1.0 < rep["savings_ratio"] < 8.0

    def test_lamb_matches_adam_layout(self):
        from apex_tpu.contrib.optimizers import (
            DistributedFusedAdam,
            DistributedFusedLAMB,
        )

        p = self._params()
        adam = DistributedFusedAdam().state_bytes(p, world=4)
        lamb = DistributedFusedLAMB().state_bytes(p, world=4)
        for key in ("padded_elements", "unsharded_state_bytes",
                    "sharded_state_bytes", "savings_ratio"):
            assert adam[key] == lamb[key]
        assert lamb["optimizer"] == "DistributedFusedLAMB"

    def test_records_memory_event(self, tmp_path):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        reg = MetricsRegistry(jsonl_dir=str(tmp_path))
        with use_registry(reg):
            DistributedFusedAdam().state_bytes(self._params(), world=8)
        assert reg.snapshot()["gauges"][
            "memory/zero_state_sharded_bytes"] > 0
        events = []
        for path in glob.glob(str(tmp_path / "*.jsonl")):
            with open(path) as f:
                events.extend(json.loads(l) for l in f if l.strip())
        assert [e for e in events if e["kind"] == "memory"
                and e["name"] == "zero_state_bytes"]


# -- DDP wiring -------------------------------------------------------------

class TestDdpMemoryReport:
    def test_report_tagged_with_sync_config(self):
        from apex_tpu.parallel import DistributedDataParallel

        ddp = DistributedDataParallel(axis_name="dp", compress="int8")
        f = jax.jit(lambda x: x * 2)
        rep = ddp.memory_report(f, jnp.ones((16,)))
        assert rep["compress"] == "int8"
        assert rep["axis_name"] == "dp"
        assert rep["peak_bytes"] > 0


# -- the ddp_memwatch bench e2e (ISSUE 5 acceptance) ------------------------

@pytest.mark.multi_device
class TestDdpMemwatchBench:
    def test_injected_alloc_failure_produces_postmortem(
            self, tmp_path, monkeypatch, capsys):
        import bench

        memory.reset_trend()
        monkeypatch.setenv(memory.ENV_DIR, str(tmp_path))
        ret = bench.bench_ddp_memwatch(2, 6, hidden=32, depth=2,
                                       alloc_step=3)
        capsys.readouterr()
        path = ret["oom_postmortem_path"]
        assert path and os.path.exists(path)
        with open(path) as f:
            pm = json.load(f)
        assert pm["census"]["total_bytes"] > 0
        assert pm["census"]["groups"]
        assert len(pm["headroom_trend"]) >= 1
        # the injected OOM cost one step, not the run
        assert np.isfinite(ret["final_loss"])

    def test_uninjected_run_reports_headroom_and_one_compile(
            self, tmp_path, monkeypatch, capsys):
        import bench

        memory.reset_trend()
        monkeypatch.setenv(memory.ENV_DIR, str(tmp_path))
        ret = bench.bench_ddp_memwatch(2, 5, hidden=32, depth=2,
                                       alloc_step=-1)
        line = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert ret["oom_postmortem_path"] is None
        assert ret["compile_count"] == 1
        assert ret["recompiles"] == 0
        assert line["compile_count"] == 1
        assert line["hbm_headroom_pct"] is not None
        assert line["peak_hbm_bytes"] > 0
        # round-10 capture contract
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        import bench_schema_check as schema

        assert schema.check_metric_line(line, round_n=10, errors=[]) == []


# -- tools/memory_report.py -------------------------------------------------

class TestMemoryReportTool:
    def _seed_dir(self, d):
        pm = {"t": 1.0, "reason": "resource_exhausted", "rank": 0,
              "error": "RESOURCE_EXHAUSTED: injected",
              "census": {"total_arrays": 2, "total_bytes": 4096,
                         "groups": [{"label": "params",
                                     "shape": [32, 32],
                                     "dtype": "float32", "count": 1,
                                     "bytes": 4096}],
                         "dropped_groups": 0, "dropped_bytes": 0},
              "last_step_memory": {"peak_bytes": 4096,
                                   "capacity_bytes": 16000000000},
              "headroom_trend": [{"t": 1.0, "peak_bytes": 4096,
                                  "headroom_frac": 0.99}]}
        with open(os.path.join(d, "memory-postmortem-rank0.json"),
                  "w") as f:
            json.dump(pm, f)
        events = [
            {"t": 1.0, "kind": "memory", "name": "step_memory",
             "peak_bytes": 4096, "headroom_frac": 0.99, "step": "s"},
            {"t": 1.1, "kind": "memory", "name": "zero_state_bytes",
             "optimizer": "DistributedFusedAdam", "world": 8,
             "unsharded_state_bytes": 800, "sharded_state_bytes": 100,
             "savings_ratio": 8.0},
            {"t": 1.2, "kind": "compile", "name": "train_step",
             "compiles": 2, "recompile": True, "call_seconds": 0.5,
             "changed": [{"arg": "args/0", "old": "f32[4]",
                          "new": "f32[8]"}]},
        ]
        with open(os.path.join(d, "telemetry-rank0.jsonl"), "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")

    def test_human_report(self, tmp_path, capsys):
        import memory_report

        self._seed_dir(str(tmp_path))
        assert memory_report.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "train_step" in out
        assert "args/0: f32[4] -> f32[8]" in out
        assert "live buffers at death" in out
        assert "DistributedFusedAdam" in out
        assert "headroom trend" in out

    def test_json_report(self, tmp_path, capsys):
        import memory_report

        self._seed_dir(str(tmp_path))
        assert memory_report.main(["--json", str(tmp_path)]) == 0
        agg = json.loads(capsys.readouterr().out)
        assert agg["postmortems"][0]["census"]["total_bytes"] == 4096
        assert agg["compiles"]["train_step"]["recompiles"] == 1
        assert agg["zero_state"][0]["savings_ratio"] == 8.0

    def test_empty_dir_is_not_fatal(self, tmp_path, capsys):
        import memory_report

        assert memory_report.main([str(tmp_path)]) == 0
        assert "nothing to report" in capsys.readouterr().out


# -- telemetry_report learns the new kinds (ISSUE 5 satellite) --------------

class TestTelemetryReportNewKinds:
    def test_compile_and_memory_kinds_not_unknown(self, tmp_path, capsys):
        import telemetry_report

        events = [
            {"t": 1.0, "kind": "compile", "name": "step", "compiles": 2,
             "recompile": True, "call_seconds": 1.5,
             "changed": [{"arg": "args/1", "old": "f32[2]",
                          "new": "f32[3]"}]},
            {"t": 1.1, "kind": "memory", "name": "step_memory",
             "peak_bytes": 1024, "headroom_frac": 0.5},
            {"t": 1.2, "kind": "memory", "name": "postmortem",
             "path": "/tmp/memory-postmortem-rank0.json"},
            {"t": 1.3, "kind": "memory", "name": "zero_state_bytes",
             "optimizer": "DistributedFusedLAMB", "world": 4,
             "unsharded_state_bytes": 400, "sharded_state_bytes": 100,
             "savings_ratio": 4.0},
            {"t": 1.4, "kind": "memory", "name": "preflight_over_budget",
             "peak_bytes": 99, "budget_bytes": 10},
        ]
        path = tmp_path / "telemetry-rank0.jsonl"
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        report = telemetry_report.aggregate(
            telemetry_report.load_events([str(path)]))
        assert report["unknown_kinds"] == {}
        assert report["malformed_events"] == 0
        assert report["compiles"]["step"]["recompiles"] == 1
        assert report["memory"]["headroom_trend"] == [
            {"peak_bytes": 1024, "headroom_frac": 0.5}]
        assert report["memory"]["postmortems"][0]["path"].endswith(
            "rank0.json")
        assert report["memory"]["preflight_warnings"] == 1
        assert report["memory"]["zero_state"][0]["world"] == 4
        telemetry_report.print_report(report)
        out = capsys.readouterr().out
        assert "compiles (watched functions)" in out
        assert "args/1: f32[2] -> f32[3]" in out
        assert "50.00% headroom" in out
        assert "OOM postmortem" in out
