"""Model-zoo shape and numerics smoke tests.

Regression coverage for the example models (the reference ships its models
inside examples/: dcgan main_amp.py, imagenet main_amp.py). The DCGAN
generator must emit exactly 64x64 so D(G(z)) is non-empty — a shape
mismatch here produced empty logits whose mean was silently NaN.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(scope="module")
def gan():
    from apex_tpu.models import Discriminator, Generator

    return Generator(), Discriminator()


def test_generator_emits_64x64(gan):
    netG, _ = gan
    z = jnp.zeros((2, 1, 1, 100))
    v = netG.init(jax.random.PRNGKey(0), z, train=True)
    fake, _ = netG.apply(v, z, train=True, mutable=["batch_stats"])
    assert fake.shape == (2, 64, 64, 3)
    assert fake.dtype == jnp.float32  # tanh output is fp32
    assert bool(jnp.isfinite(fake).all())


def test_discriminator_on_generator_output(gan):
    netG, netD = gan
    z = jnp.zeros((2, 1, 1, 100))
    vG = netG.init(jax.random.PRNGKey(0), z, train=True)
    fake, _ = netG.apply(vG, z, train=True, mutable=["batch_stats"])
    vD = netD.init(jax.random.PRNGKey(1), fake, train=True)
    out, _ = netD.apply(vD, fake, train=True, mutable=["batch_stats"])
    assert out.shape == (2, 1)  # non-empty: mean() of it must be finite
    assert bool(jnp.isfinite(out).all())


@pytest.mark.slow  # duplicate coverage: the dcgan/resnet amp-step tests
# compile the same conv stacks (tier-1 budget, 10s)
def test_resnet18_forward_shape():
    from apex_tpu.models import ResNet18

    model = ResNet18(num_classes=10, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 64, 64, 3))
    v = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(v, x, train=False)
    assert logits.shape == (2, 10)
    assert bool(jnp.isfinite(logits.astype(jnp.float32)).all())


@pytest.mark.slow
def test_dcgan_one_amp_step_finite(rng):
    """One O2 train step of the example's D loss stays finite."""
    from apex_tpu import amp
    from apex_tpu.models import Discriminator, Generator
    from apex_tpu.optimizers import FusedAdam

    netG, netD = Generator(ngf=8), Discriminator(ndf=8)
    z = jnp.asarray(rng.randn(2, 1, 1, 16).astype(np.float32))
    real = jnp.asarray(rng.randn(2, 64, 64, 3).astype(np.float32))
    vG = netG.init(jax.random.PRNGKey(0), z, train=True)
    vD = netD.init(jax.random.PRNGKey(1), real, train=True)
    pG, bsG = vG["params"], vG["batch_stats"]
    pD, bsD = vD["params"], vD["batch_stats"]
    (pD, pG), (optD, _) = amp.initialize(
        [pD, pG], [FusedAdam(lr=2e-4), FusedAdam(lr=2e-4)],
        opt_level="O2", num_losses=3, verbosity=0)
    sD = optD.init(pD)

    def bce(logits, t):
        x = logits.astype(jnp.float32)
        return jnp.mean(jnp.maximum(x, 0) - x * t +
                        jnp.log1p(jnp.exp(-jnp.abs(x))))

    def d_loss(pd):
        out_real, nbsD = netD.apply(
            {"params": pd, "batch_stats": bsD}, real, train=True,
            mutable=["batch_stats"])
        fake, _ = netG.apply({"params": pG, "batch_stats": bsG}, z,
                             train=True, mutable=["batch_stats"])
        out_fake, _ = netD.apply(
            {"params": pd, "batch_stats": nbsD["batch_stats"]},
            jax.lax.stop_gradient(fake), train=True,
            mutable=["batch_stats"])
        return bce(out_real, 1.0) + bce(out_fake, 0.0)

    scale = sD["scaler"].loss_scale
    loss, grads = jax.value_and_grad(lambda p: d_loss(p) * scale)(pD)
    assert bool(jnp.isfinite(loss))
    pD2, sD2 = optD.step(grads, sD, pD)
    gmax = max(float(jnp.abs(x).max())
               for x in jax.tree_util.tree_leaves(grads))
    assert np.isfinite(gmax)
    for leaf in jax.tree_util.tree_leaves(pD2):
        assert bool(jnp.isfinite(leaf.astype(jnp.float32)).all())


def test_gpt_flash_attention_path_jits(monkeypatch, rng):
    """The model-level flash path must survive jit+grad (regression: the
    attention layer once passed a traced jnp scale into the flash
    custom_vjp's static nondiff argument, blowing up only when
    use_flash_attention was actually enabled on TPU)."""
    import apex_tpu.contrib.fmha as fmha_mod
    import apex_tpu.models.transformer_lm as tlm

    monkeypatch.setattr(fmha_mod, "_INTERPRET", True)
    monkeypatch.setattr(fmha_mod, "_use_pallas", lambda: True)
    monkeypatch.setattr(tlm, "_flash_available", lambda s, d: True)

    from apex_tpu.models import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=1,
        vocab_size=128, max_position_embeddings=128,
        compute_dtype=jnp.float32, use_flash_attention=True)
    model = GPTModel(cfg)
    tokens = jnp.asarray(rng.randint(0, 128, (1, 128)))
    params = model.init(jax.random.PRNGKey(0), tokens)

    @jax.jit
    def loss_and_grad(p):
        def loss_fn(p):
            logits = model.apply(p, tokens).astype(jnp.float32)
            return jnp.mean(logits ** 2)
        return jax.value_and_grad(loss_fn)(p)

    loss, grads = loss_and_grad(params)
    assert bool(jnp.isfinite(loss))
    gmax = max(float(jnp.abs(x).max())
               for x in jax.tree_util.tree_leaves(grads))
    assert np.isfinite(gmax) and gmax > 0


def test_gpt_sliding_window_flash_matches_masked_path(monkeypatch, rng):
    """Model-level SWA through the flash kernel (window band block-skip)
    must match the masked-softmax fold of the same config."""
    import apex_tpu.contrib.fmha as fmha_mod
    import apex_tpu.models.transformer_lm as tlm

    from apex_tpu.models import GPTModel, TransformerConfig

    tokens = jnp.asarray(rng.randint(0, 128, (1, 128)))

    def logits(use_flash):
        cfg = TransformerConfig(
            hidden_size=64, num_layers=2, num_attention_heads=1,
            vocab_size=128, max_position_embeddings=128,
            compute_dtype=jnp.float32, use_flash_attention=use_flash,
            sliding_window=40)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0), tokens)
        return np.asarray(model.apply(params, tokens))

    masked = logits(use_flash=False)
    monkeypatch.setattr(fmha_mod, "_INTERPRET", True)
    monkeypatch.setattr(fmha_mod, "_use_pallas", lambda: True)
    monkeypatch.setattr(tlm, "_flash_available", lambda s, d: True)
    flash = logits(use_flash=True)
    np.testing.assert_allclose(flash, masked, rtol=2e-4, atol=2e-4)


def test_gpt_alibi_flash_matches_masked_path(monkeypatch, rng):
    """Model-level ALiBi through the flash kernel (in-kernel key-position
    bias) must match the masked-softmax score-bias path."""
    import apex_tpu.contrib.fmha as fmha_mod
    import apex_tpu.models.transformer_lm as tlm

    from apex_tpu.models import GPTModel, TransformerConfig

    tokens = jnp.asarray(rng.randint(0, 128, (1, 128)))

    def logits(use_flash):
        cfg = TransformerConfig(
            hidden_size=64, num_layers=2, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=128,
            compute_dtype=jnp.float32, use_flash_attention=use_flash,
            position_embedding_type="alibi")
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0), tokens)
        return np.asarray(model.apply(params, tokens))

    masked = logits(use_flash=False)
    monkeypatch.setattr(fmha_mod, "_INTERPRET", True)
    monkeypatch.setattr(fmha_mod, "_use_pallas", lambda: True)
    monkeypatch.setattr(tlm, "_flash_available", lambda s, d: True)
    flash = logits(use_flash=True)
    np.testing.assert_allclose(flash, masked, rtol=2e-4, atol=2e-4)
