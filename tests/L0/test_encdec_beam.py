"""External oracle: encoder-decoder beam search vs HuggingFace generate.

The shared beam engine (models/encdec_beam.py) drives the T5 and Whisper
KV-cache decode paths; the oracle is hf.generate(num_beams=k) token
output (for Whisper, the base GenerationMixin.generate with explicit
decoder_input_ids — Whisper's own generate override injects init-token
and length handling outside the beam algorithm). Cases cover beams that
never finish (pure max-likelihood), EOS firing mid-generation (chosen as
a token the model actually emits), non-unit length penalties, and
beam=1 degenerating to the cached greedy path.
"""

import sys

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, ".")  # repo root for tools/


def _fresh():
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()


def _tiny_t5(seed=0):
    cfg = transformers.T5Config(
        vocab_size=96, d_model=48, d_kv=16, d_ff=96, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=20, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=True,
        decoder_start_token_id=0, eos_token_id=95, pad_token_id=0)
    torch.manual_seed(seed)
    return transformers.T5ForConditionalGeneration(cfg).eval(), cfg


def _t5_pair(seed=0):
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models import T5Model

    _fresh()
    hf, hf_cfg = _tiny_t5(seed)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    return hf, T5Model(cfg), params


class TestT5Beam:
    # round 18/21: the HF-match mechanism is identical per
    # (beams, new, lp), and even the smallest shape costs ~17 s of
    # tier-1 wall clock — the whole matrix now rides the slow lane
    # (tier-1 keeps beam coverage via the greedy/score paths below)
    @pytest.mark.parametrize("beams,new,lp", [
        pytest.param(3, 8, 1.0, marks=pytest.mark.slow),
        pytest.param(4, 10, 2.0, marks=pytest.mark.slow),
        pytest.param(2, 6, 0.5, marks=pytest.mark.slow),
    ])
    def test_matches_hf_beam(self, beams, new, lp):
        from apex_tpu.models import t5_beam_generate

        hf, model, params = _t5_pair()
        enc = np.random.RandomState(0).randint(2, 94, size=(3, 10))
        with torch.no_grad():
            ref = hf.generate(torch.asarray(enc), max_new_tokens=new,
                              num_beams=beams, do_sample=False,
                              early_stopping=False,
                              length_penalty=lp).numpy()
        ours, scores = t5_beam_generate(
            model, params, jnp.asarray(enc), new, num_beams=beams,
            eos_token_id=95, pad_token_id=0, length_penalty=lp)
        ours = np.asarray(ours)
        np.testing.assert_array_equal(ours[:, :ref.shape[1]], ref)
        assert (ours[:, ref.shape[1]:] == 0).all()  # HF right-pad layout
        assert np.isfinite(np.asarray(scores)).all()

    @pytest.mark.slow  # tier-1 budget (round 23): matches_hf_beam + beam_eos_freezes cover eos semantics
    def test_matches_hf_with_eos_firing(self):
        """EOS chosen as a token the model actually emits, so beams
        finish mid-generation and the hypothesis pool + length
        normalization decide the winner."""
        from apex_tpu.models import t5_beam_generate, t5_cached_generate

        hf, model, params = _t5_pair(seed=4)
        enc = np.random.RandomState(4).randint(2, 94, size=(2, 8))
        greedy = np.asarray(t5_cached_generate(model, params,
                                               jnp.asarray(enc), 6))
        eos = int(greedy[0, 3])  # fires by construction
        with torch.no_grad():
            ref = hf.generate(torch.asarray(enc), max_new_tokens=8,
                              num_beams=3, do_sample=False,
                              early_stopping=False, length_penalty=1.0,
                              eos_token_id=eos, pad_token_id=0).numpy()
        ours, _ = t5_beam_generate(model, params, jnp.asarray(enc), 8,
                                   num_beams=3, eos_token_id=eos,
                                   pad_token_id=0)
        ours = np.asarray(ours)
        np.testing.assert_array_equal(ours[:, :ref.shape[1]], ref)
        assert (ours[:, ref.shape[1]:] == 0).all()

    def test_beam1_no_eos_equals_cached_greedy(self):
        from apex_tpu.models import t5_beam_generate, t5_cached_generate

        _, model, params = _t5_pair(seed=1)
        enc = jnp.asarray(np.random.RandomState(1).randint(2, 94, (2, 9)))
        greedy = t5_cached_generate(model, params, enc, 7)
        beams, _ = t5_beam_generate(model, params, enc, 7, num_beams=1)
        np.testing.assert_array_equal(np.asarray(beams), np.asarray(greedy))


class TestWhisperBeam:
    def _pair(self, seed=0):
        from tools.convert_hf_whisper import convert_whisper

        from apex_tpu.models import WhisperModel

        _fresh()
        cfg = transformers.WhisperConfig(
            vocab_size=96, d_model=48, encoder_layers=2, decoder_layers=2,
            encoder_attention_heads=4, decoder_attention_heads=4,
            encoder_ffn_dim=96, decoder_ffn_dim=96, num_mel_bins=8,
            max_source_positions=16, max_target_positions=12,
            dropout=0.0, attention_dropout=0.0, activation_dropout=0.0,
            pad_token_id=0, bos_token_id=1, eos_token_id=2,
            decoder_start_token_id=1, suppress_tokens=None,
            begin_suppress_tokens=None)
        torch.manual_seed(seed)
        hf = transformers.WhisperForConditionalGeneration(cfg).eval()
        mycfg, params = convert_whisper(hf.state_dict(), cfg)
        return hf, WhisperModel(mycfg), params

    @pytest.mark.parametrize("beams,new", [(3, 8), (2, 10)])
    def test_matches_hf_beam(self, beams, new):
        from transformers.generation import GenerationMixin

        from apex_tpu.models import whisper_beam_generate

        hf, model, params = self._pair()
        feats = np.random.RandomState(0).randn(2, 8, 32).astype(np.float32)
        with torch.no_grad():
            # base generate: Whisper's override injects its own init-token
            # and length handling around the beam algorithm
            ref = GenerationMixin.generate(
                hf, input_features=torch.asarray(feats),
                decoder_input_ids=torch.ones((2, 1), dtype=torch.long),
                max_new_tokens=new, num_beams=beams, do_sample=False,
                early_stopping=False, length_penalty=1.0).numpy()
        ours, _ = whisper_beam_generate(
            model, params, jnp.asarray(feats), new,
            decoder_start_token_id=1, num_beams=beams, eos_token_id=2,
            pad_token_id=0)
        ours = np.asarray(ours)
        np.testing.assert_array_equal(ours[:, :ref.shape[1]], ref)
        assert (ours[:, ref.shape[1]:] == 0).all()

    def test_beam1_no_eos_equals_cached_greedy(self):
        from apex_tpu.models import (
            whisper_beam_generate,
            whisper_cached_generate,
        )

        _, model, params = self._pair(seed=2)
        feats = jnp.asarray(
            np.random.RandomState(2).randn(2, 8, 32).astype(np.float32))
        greedy = whisper_cached_generate(model, params, feats, 8,
                                         decoder_start_token_id=1)
        beams, _ = whisper_beam_generate(model, params, feats, 8,
                                         decoder_start_token_id=1,
                                         num_beams=1)
        np.testing.assert_array_equal(np.asarray(beams), np.asarray(greedy))
