"""Encoder-decoder (split-rank) pipeline schedule correctness.

Mirrors the reference's ModelType.encoder_and_decoder pipeline coverage
(tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py runs T5-shaped
models through fwd_bwd_pipelining_without_interleaving with dual tensor
shapes, get_tensor_shapes at ...without_interleaving.py:29-86): pipelined
fwd+bwd of a small T5-style model, asserting loss and gradient parity
against the unpipelined single-device computation, with the encoder on
ranks < split_rank and the decoder at/after it.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.testing import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_with_split,
    make_encoder_decoder_step,
)
from apex_tpu.transformer.testing.standalone_t5 import (
    decoder_block,
    encoder_block,
    init_stage_params,
    t5_loss,
    t5_reference_loss,
    t5_test_config,
)

M = 4   # microbatches
B = 2   # microbatch size


def _make_batch(rng, cfg):
    v = cfg["vocab"]
    return {
        "enc_tokens": jnp.asarray(
            rng.randint(0, v, (M, B, cfg["enc_seq"]))),
        "dec_tokens": jnp.asarray(
            rng.randint(0, v, (M, B, cfg["dec_seq"]))),
        "dec_targets": jnp.asarray(
            rng.randint(0, v, (M, B, cfg["dec_seq"]))),
    }


def _reference(stage_params, mbs, split, cfg):
    """Unpipelined oracle: mean loss over microbatches + grads wrt the
    stacked per-rank params."""
    P_ = len(stage_params)

    def total(stacked):
        per_rank = [jax.tree_util.tree_map(lambda a: a[r], stacked)
                    for r in range(P_)]
        losses = []
        for m in range(M):
            mb = jax.tree_util.tree_map(lambda a: a[m], mbs)
            losses.append(t5_reference_loss(per_rank, mb, split, cfg=cfg))
        return sum(losses) / M, jnp.stack(losses)

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *stage_params)
    (_, losses), grads = jax.value_and_grad(total, has_aux=True)(stacked)
    return np.asarray(losses), grads, stacked


@pytest.mark.slow
@pytest.mark.parametrize("PP,split", [(2, 1), (4, 2)])
def test_split_pipeline_matches_unpipelined_reference(rng, PP, split):
    cfg = t5_test_config()
    mbs = _make_batch(rng, cfg)
    stage_params = [init_stage_params(rng, cfg) for _ in range(PP)]
    ref_losses, ref_grads, stacked = _reference(stage_params, mbs, split,
                                                cfg)

    mesh = Mesh(np.asarray(jax.devices()[:PP]), ("pp",))
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP,
        pipeline_model_parallel_split_rank_=split,
        devices=jax.devices()[:PP])
    # the schedule consumes the split rank installed in parallel_state
    assert parallel_state.get_pipeline_model_parallel_split_rank() == split

    step = make_encoder_decoder_step(
        functools.partial(encoder_block, cfg=cfg),
        functools.partial(decoder_block, cfg=cfg))

    def loss_func(p, payload, mb):
        return t5_loss(p, payload["decoder"], mb)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("pp"), P()), out_specs=(P("pp"), P("pp")))
    def run(p_stage, mbs_):
        p = jax.tree_util.tree_map(lambda a: a[0], p_stage)
        losses, grads = forward_backward_pipelining_with_split(
            step, loss_func, p, mbs_, num_microbatches=M,
            encoder_tensor_shape=(cfg["enc_seq"], B, cfg["hidden"]),
            decoder_tensor_shape=(cfg["dec_seq"], B, cfg["hidden"]),
            dtype=jnp.float32, pp_size=PP)
        grads = jax.tree_util.tree_map(lambda a: a[None], grads)
        return losses[None], grads

    losses, grads = jax.jit(run)(stacked, mbs)
    np.testing.assert_allclose(np.asarray(losses)[PP - 1], ref_losses,
                               rtol=1e-4, atol=1e-5)
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(ref_grads)
    flat_got = dict(jax.tree_util.tree_flatten_with_path(grads)[0])
    for path, ref_leaf in flat_ref:
        got = flat_got[path]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref_leaf), rtol=2e-3, atol=1e-4,
            err_msg=jax.tree_util.keystr(path))


def test_split_rank_helpers_consumed():
    """The split helpers (parallel_state.py:469-486 parity) govern stage
    placement: before/after/at-split must agree with the schedule's rank
    partition."""
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        pipeline_model_parallel_split_rank_=2,
        devices=jax.devices()[:4])
    assert parallel_state.is_pipeline_stage_before_split(rank=1)
    assert not parallel_state.is_pipeline_stage_before_split(rank=2)
    assert parallel_state.is_pipeline_stage_after_split(rank=2)
    assert not parallel_state.is_pipeline_stage_after_split(rank=1)


def test_split_requires_valid_split_rank():
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="split_rank"):
        forward_backward_pipelining_with_split(
            lambda *a: None, lambda *a: None, {}, {},
            num_microbatches=2, encoder_tensor_shape=(2, 2, 4),
            decoder_tensor_shape=(2, 2, 4), pp_size=2)


def test_selector_routes_split_rank_to_split_schedule():
    """get_forward_backward_func must hand encoder-decoder setups the
    split schedule (reference routes ModelType.encoder_and_decoder
    through the same selector)."""
    from apex_tpu.transformer.pipeline_parallel import (
        get_forward_backward_func,
        forward_backward_pipelining_without_interleaving as plain,
    )

    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        pipeline_model_parallel_split_rank_=2,
        devices=jax.devices()[:4])
    assert get_forward_backward_func() is forward_backward_pipelining_with_split
    with pytest.raises(ValueError, match="interleav"):
        get_forward_backward_func(virtual_pipeline_model_parallel_size=2)
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4, devices=jax.devices()[:4])
    assert get_forward_backward_func() is plain
