"""Fused optimizers vs torch.optim / manual references.

Mirrors reference tests/L0/run_optimizers/test_adam.py,
test_fused_optimizer.py, test_lamb.py (step-by-step comparisons vs
torch.optim references).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.optimizers import (
    FusedAdam,
    FusedAdagrad,
    FusedLAMB,
    FusedMixedPrecisionLamb,
    FusedNovoGrad,
    FusedSGD,
)


def make_params(rng):
    return {
        "w": jnp.asarray(rng.randn(4, 8).astype(np.float32)),
        "b": jnp.asarray(rng.randn(8).astype(np.float32)),
    }


def make_grads(rng, params):
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)), params)


def to_torch(tree):
    return [torch.tensor(np.asarray(l), requires_grad=True)
            for l in jax.tree_util.tree_leaves(tree)]


class TestFusedAdamVsTorch:
    @pytest.mark.parametrize("adam_w_mode,weight_decay", [
        (True, 0.0), (True, 0.01), (False, 0.0), (False, 0.01)])
    def test_matches_torch_adam(self, rng, adam_w_mode, weight_decay):
        params = make_params(rng)
        opt = FusedAdam(lr=1e-3, adam_w_mode=adam_w_mode,
                        weight_decay=weight_decay)
        state = opt.init(params)

        tparams = to_torch(params)
        cls = torch.optim.AdamW if adam_w_mode else torch.optim.Adam
        topt = cls(tparams, lr=1e-3, weight_decay=weight_decay)

        for _ in range(5):
            grads = make_grads(rng, params)
            for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
                tp.grad = torch.tensor(np.asarray(g))
            topt.step()
            params, state = opt.step(grads, state, params)

        for ours, theirs in zip(jax.tree_util.tree_leaves(params), tparams):
            np.testing.assert_allclose(np.asarray(ours),
                                       theirs.detach().numpy(), atol=1e-5)

    def test_overflow_skips_step(self, rng):
        params = make_params(rng)
        opt = FusedAdam(lr=1e-1)
        state = opt.init(params)
        grads = make_grads(rng, params)
        p1, s1 = opt.step(grads, state, params,
                          found_inf=jnp.ones((), jnp.float32))
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(s1["step"]) == 0

    def test_master_weights(self, rng):
        params16 = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), make_params(rng))
        opt = FusedAdam(lr=1e-3, master_weights=True)
        state = opt.init(params16)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.ones_like(p), params16)
        p1, s1 = opt.step(grads, state, params16)
        for l in jax.tree_util.tree_leaves(p1):
            assert l.dtype == jnp.bfloat16
        for l in jax.tree_util.tree_leaves(s1["master"]):
            assert l.dtype == jnp.float32


class TestFusedSGDVsTorch:
    @pytest.mark.parametrize("momentum,nesterov,weight_decay", [
        (0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0),
        (0.9, False, 1e-4)])
    def test_matches_torch_sgd(self, rng, momentum, nesterov, weight_decay):
        params = make_params(rng)
        opt = FusedSGD(lr=0.1, momentum=momentum, nesterov=nesterov,
                       weight_decay=weight_decay)
        state = opt.init(params)
        tparams = to_torch(params)
        topt = torch.optim.SGD(tparams, lr=0.1, momentum=momentum,
                               nesterov=nesterov, weight_decay=weight_decay)
        for _ in range(5):
            grads = make_grads(rng, params)
            for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
                tp.grad = torch.tensor(np.asarray(g))
            topt.step()
            params, state = opt.step(grads, state, params)
        for ours, theirs in zip(jax.tree_util.tree_leaves(params), tparams):
            np.testing.assert_allclose(np.asarray(ours),
                                       theirs.detach().numpy(), atol=1e-5)


class TestFusedAdagradVsTorch:
    def test_matches_torch_adagrad(self, rng):
        params = make_params(rng)
        opt = FusedAdagrad(lr=0.01, eps=1e-10)
        state = opt.init(params)
        tparams = to_torch(params)
        topt = torch.optim.Adagrad(tparams, lr=0.01, eps=1e-10)
        for _ in range(3):
            grads = make_grads(rng, params)
            for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
                tp.grad = torch.tensor(np.asarray(g))
            topt.step()
            params, state = opt.step(grads, state, params)
        for ours, theirs in zip(jax.tree_util.tree_leaves(params), tparams):
            np.testing.assert_allclose(np.asarray(ours),
                                       theirs.detach().numpy(), atol=1e-4)


class TestFusedLAMB:
    def test_decreases_loss(self, rng):
        """LAMB sanity: optimizing a quadratic decreases the loss
        (the reference compares against its own CUDA kernel; we assert
        optimizer behavior)."""
        params = make_params(rng)
        target = make_params(rng)
        opt = FusedLAMB(lr=0.05, weight_decay=0.01)
        state = opt.init(params)

        def loss_fn(p):
            return sum(jnp.sum((a - b) ** 2) for a, b in
                       zip(jax.tree_util.tree_leaves(p),
                           jax.tree_util.tree_leaves(target)))

        losses = []
        for _ in range(20):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = opt.step(grads, state, params)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5

    def test_trust_ratio_scale_invariance(self, rng):
        """LAMB's update direction is invariant to grad scale (layer-wise
        normalization property)."""
        params = make_params(rng)
        opt = FusedLAMB(lr=0.01, weight_decay=0.0, use_nvlamb=True,
                        max_grad_norm=0.0)
        grads = make_grads(rng, params)
        s1 = opt.init(params)
        p_a, _ = opt.step(grads, s1, params)
        s2 = opt.init(params)
        grads_scaled = jax.tree_util.tree_map(lambda g: g * 1000.0, grads)
        p_b, _ = opt.step(grads_scaled, s2, params)
        for a, b in zip(jax.tree_util.tree_leaves(p_a),
                        jax.tree_util.tree_leaves(p_b)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


class TestFusedNovoGrad:
    def test_decreases_loss(self, rng):
        params = make_params(rng)
        target = make_params(rng)
        opt = FusedNovoGrad(lr=0.3)
        state = opt.init(params)

        def loss_fn(p):
            return sum(jnp.sum((a - b) ** 2) for a, b in
                       zip(jax.tree_util.tree_leaves(p),
                           jax.tree_util.tree_leaves(target)))

        losses = []
        for _ in range(50):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = opt.step(grads, state, params)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5


class TestFusedMixedPrecisionLamb:
    def test_bf16_params_fp32_master(self, rng):
        params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), make_params(rng))
        opt = FusedMixedPrecisionLamb(lr=0.01)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(lambda p: jnp.ones_like(p), params)
        p1, s1 = opt.step(grads, state, params)
        for l in jax.tree_util.tree_leaves(p1):
            assert l.dtype == jnp.bfloat16
        for l in jax.tree_util.tree_leaves(s1["master"]):
            assert l.dtype == jnp.float32
        assert int(s1["step"]) == 1

    def test_found_inf_skips(self, rng):
        params = make_params(rng)
        opt = FusedMixedPrecisionLamb(lr=0.01)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, jnp.inf), params)
        # found_inf computed internally from grads via noop path: pass flag
        p1, s1 = opt.step(grads, state, params,
                          found_inf=jnp.ones((), jnp.float32))
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestOptaxInterop:
    def test_gradient_transformation(self, rng):
        import optax

        params = make_params(rng)
        opt = FusedAdam(lr=1e-3)
        tx = opt.as_gradient_transformation()
        state = tx.init(params)
        grads = make_grads(rng, params)
        updates, state = tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)
        # must equal direct step
        direct, _ = opt.step(grads, opt.init(params), params)
        for a, b in zip(jax.tree_util.tree_leaves(new_params),
                        jax.tree_util.tree_leaves(direct)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
