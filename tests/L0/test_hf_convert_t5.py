"""External numerics oracle: apex_tpu T5Model vs HuggingFace T5.

A randomly-initialized ``transformers`` T5ForConditionalGeneration (no
download) is converted with tools/convert_hf_t5; identical weights must
produce matching logits — validating the relative-position bucket
assignment (bidirectional + causal), unscaled attention scores, RMS
layernorms, cross-attention, (gated-)FFN, and the tied-head rescale
against an independent implementation end to end.
"""

import sys

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, ".")  # repo root for tools/


def _tiny_t5(seed=0, gated=False, tie=True, dec_layers=None):
    cfg = transformers.T5Config(
        vocab_size=96, d_model=48, d_kv=16, d_ff=96, num_layers=2,
        num_decoder_layers=dec_layers, num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=20,
        dropout_rate=0.0,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=tie, decoder_start_token_id=0,
        eos_token_id=95, pad_token_id=0)
    torch.manual_seed(seed)
    return transformers.T5ForConditionalGeneration(cfg).eval(), cfg


def _fresh():
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("gated,tie", [(False, True), (True, False)])
def test_logits_match_hf_t5(gated, tie):
    """relu+tied = original T5; gated-gelu+untied = t5 v1.1."""
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import T5Model

    _fresh()
    hf, hf_cfg = _tiny_t5(gated=gated, tie=tie)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    assert cfg.tie_word_embeddings == tie

    rng = np.random.RandomState(0)
    enc = rng.randint(0, 96, size=(2, 12))
    dec = rng.randint(0, 96, size=(2, 7))
    with torch.no_grad():
        ref = hf(input_ids=torch.asarray(enc),
                 decoder_input_ids=torch.asarray(dec)).logits.numpy()
    ours = T5Model(cfg).apply({"params": params}, jnp.asarray(enc),
                              jnp.asarray(dec))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_logits_match_hf_t5_asymmetric_depth_and_long_relpos():
    """Decoder deeper than encoder, and sequences past
    relative_attention_max_distance (exercises the log-spaced bucket
    branch and the shared last bucket)."""
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import T5Model

    _fresh()
    hf, hf_cfg = _tiny_t5(seed=3, dec_layers=3)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    assert cfg.decoder_layers == 3 and cfg.num_layers == 2

    rng = np.random.RandomState(3)
    enc = rng.randint(0, 96, size=(1, 30))  # > max_distance=20
    dec = rng.randint(0, 96, size=(1, 26))
    with torch.no_grad():
        ref = hf(input_ids=torch.asarray(enc),
                 decoder_input_ids=torch.asarray(dec)).logits.numpy()
    ours = T5Model(cfg).apply({"params": params}, jnp.asarray(enc),
                              jnp.asarray(dec))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def test_t5_encoder_padding_mask_matches_hf():
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import T5Model

    _fresh()
    hf, hf_cfg = _tiny_t5(seed=1)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)

    rng = np.random.RandomState(1)
    enc = rng.randint(1, 96, size=(2, 10))
    mask = np.ones((2, 10), np.int32)
    mask[0, 7:] = 0  # right padding on sequence 0
    enc = enc * mask
    dec = rng.randint(0, 96, size=(2, 5))
    with torch.no_grad():
        ref = hf(input_ids=torch.asarray(enc),
                 attention_mask=torch.asarray(mask),
                 decoder_input_ids=torch.asarray(dec)).logits.numpy()
    ours = T5Model(cfg).apply({"params": params}, jnp.asarray(enc),
                              jnp.asarray(dec), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_t5_greedy_generation_matches_hf():
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import T5Model, t5_greedy_generate

    _fresh()
    hf, hf_cfg = _tiny_t5(seed=2)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    enc = np.random.RandomState(2).randint(0, 95, size=(2, 9))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(enc), max_new_tokens=8,
                          do_sample=False, min_new_tokens=8).numpy()
    ours = t5_greedy_generate(T5Model(cfg), params, jnp.asarray(enc),
                              max_new_tokens=8,
                              decoder_start_token_id=0)
    np.testing.assert_array_equal(np.asarray(ours), ref)


@pytest.mark.slow
def test_t5_tp2_logits_match_tp1():
    """Cross-TP serving oracle: head-sharded relative bias, column/row
    parallel q/k/v/o and (gated) FFN, vocab-parallel tied head."""
    import functools

    import jax
    from jax.sharding import PartitionSpec as P

    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import T5Model
    from apex_tpu.models.tp_split import split_t5_params_for_tp
    from apex_tpu.transformer import parallel_state

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    _fresh()
    hf, hf_cfg = _tiny_t5(seed=4, gated=True, tie=False)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)

    rng = np.random.RandomState(4)
    enc = jnp.asarray(rng.randint(0, 96, size=(2, 8)))
    dec = jnp.asarray(rng.randint(0, 96, size=(2, 6)))
    ref = T5Model(cfg).apply({"params": params}, enc, dec)

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, devices=jax.devices()[:2])
    stacked = split_t5_params_for_tp(cfg, params, 2)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P("tp"), P(), P()), out_specs=P("tp"),
                       check_vma=False)
    def run(sp, e, d):
        p = jax.tree_util.tree_map(lambda a: a[0], sp)
        # vocab-parallel logits [b, s, vocab/tp]; leading stacked axis
        # re-added so the out_spec concatenates rank shards on axis 0
        return T5Model(cfg).apply({"params": p}, e, d)[None]

    out = run(stacked, enc, dec)  # [tp, b, s, vocab/tp]
    full = jnp.concatenate([out[0], out[1]], axis=-1)
    parallel_state.destroy_model_parallel()
    np.testing.assert_allclose(np.asarray(full), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow  # tier-1 budget: gated_and_masked covers the cache path
def test_t5_cached_generate_matches_oracle_and_hf():
    """KV-cache decode (prefill + O(1) steps, cross K/V never
    re-projected) is token-exact vs both the full-rerun oracle and HF."""
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import (T5Model, t5_cached_generate,
                                    t5_greedy_generate)

    _fresh()
    hf, hf_cfg = _tiny_t5(seed=6)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    enc = np.random.RandomState(6).randint(0, 95, size=(2, 9))
    model = T5Model(cfg)
    oracle = t5_greedy_generate(model, params, jnp.asarray(enc),
                                max_new_tokens=7)
    cached = t5_cached_generate(model, params, jnp.asarray(enc),
                                max_new_tokens=7)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(oracle))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(enc), max_new_tokens=7,
                          do_sample=False, min_new_tokens=7).numpy()
    np.testing.assert_array_equal(np.asarray(cached), ref)


@pytest.mark.slow  # tier-1 budget (round 18): cached-decode parity
# is covered by the greedy/beam cached tests; the gated+masked
# variant rides the full suite
def test_t5_cached_generate_gated_and_masked():
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import (T5Model, t5_cached_generate,
                                    t5_greedy_generate)

    _fresh()
    hf, hf_cfg = _tiny_t5(seed=7, gated=True, tie=False)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    rng = np.random.RandomState(7)
    enc = rng.randint(1, 95, size=(2, 8))
    mask = np.ones((2, 8), np.int32)
    mask[1, 5:] = 0
    enc = enc * mask
    model = T5Model(cfg)
    oracle = t5_greedy_generate(model, params, jnp.asarray(enc),
                                max_new_tokens=6,
                                enc_mask=jnp.asarray(mask))
    cached = t5_cached_generate(model, params, jnp.asarray(enc),
                                max_new_tokens=6,
                                enc_mask=jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(oracle))


def test_t5_decode_step_without_prefill_raises():
    import jax

    from apex_tpu.models.t5 import T5Config, T5Model

    _fresh()
    cfg = T5Config(vocab_size=32, d_model=32, d_kv=8, d_ff=32,
                   num_layers=1, num_heads=2, compute_dtype=jnp.float32)
    model = T5Model(cfg)
    enc = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), enc, enc)["params"]
    with pytest.raises(ValueError, match="decode_step before"):
        model.apply({"params": params}, jnp.zeros((1, 1), jnp.int32),
                    None, mutable=["cache"], method=T5Model.decode_step)


@pytest.mark.slow
def test_t5_tp2_cached_generate_matches_tp1():
    """Tensor-parallel T5 serving: tp=2 cached decode emits tokens
    identical to the tp=1 path (and hence to HF, by the oracle above)."""
    import jax

    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import (T5Model, t5_cached_generate,
                                    tensor_parallel_t5_generate)
    from apex_tpu.models.tp_split import split_t5_params_for_tp
    from apex_tpu.transformer import parallel_state

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    _fresh()
    hf, hf_cfg = _tiny_t5(seed=8, gated=True, tie=False)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    enc = jnp.asarray(np.random.RandomState(8).randint(0, 95, (2, 9)))

    model = T5Model(cfg)
    ref = t5_cached_generate(model, params, enc, max_new_tokens=6)

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, devices=jax.devices()[:2])
    stacked = split_t5_params_for_tp(cfg, params, 2)
    out = tensor_parallel_t5_generate(model, stacked, enc,
                                      max_new_tokens=6, mesh=mesh)
    parallel_state.destroy_model_parallel()
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_t5_cached_generate_eos_matches_hf():
    """EOS semantics: finished rows extend with pad, exactly as HF
    generate emits them (compared over HF's actual output length)."""
    from tools.convert_hf_t5 import convert_t5

    from apex_tpu.models.t5 import T5Model, t5_cached_generate

    _fresh()
    hf, hf_cfg = _tiny_t5(seed=9)
    cfg, params = convert_t5(hf.state_dict(), hf_cfg)
    enc = np.random.RandomState(9).randint(0, 95, size=(3, 7))
    with torch.no_grad():
        ref = hf.generate(torch.asarray(enc), max_new_tokens=12,
                          do_sample=False).numpy()  # stops at eos
    ours = np.asarray(t5_cached_generate(
        T5Model(cfg), params, jnp.asarray(enc), max_new_tokens=12,
        eos_token_id=95, pad_token_id=0))
    hf_len = ref.shape[1]
    np.testing.assert_array_equal(ours[:, :hf_len], ref)
    # beyond HF's stop point every row is pad (all rows were done)
    if hf_len < ours.shape[1]:
        assert (ours[:, hf_len:] == 0).all()
