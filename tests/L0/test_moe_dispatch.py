"""Sort/scatter/ragged MoE dispatch vs the dense one-hot einsum path.

The dense [T, E, C] formulation (compute_routing + einsum dispatch) is
O(T*E*C) — quadratic in tokens once C ~ T, the dropless capacity that
serves converted Mixtral/DeepSeek checkpoints. These tests pin the
linear-cost replacements to it:

- compute_routing_sorted reproduces the dense path's slot assignment
  (and therefore its capacity-drop decisions) bit-exactly,
- SwitchMLP 'scatter' and 'ragged' forward/backward match 'einsum' to
  bf16 rounding, for both expert shapes (swiglu and biased gelu),
- 'scatter' keeps the expert-parallel all_to_all layout working (ep=2
  under shard_map on the CPU mesh),
- 'auto' resolution: ragged only when genuinely dropless on one ep rank,
- dispatch FLOP accounting: the sorted path's per-token work is
  independent of T (linearity), while the dense path's grows ~T.

No reference equivalent (apex has no MoE); the bar is internal
consistency plus the HF-parity oracles in test_hf_convert*.py which ride
these paths through the converted models.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.moe import (
    SwitchMLP,
    compute_routing,
    compute_routing_sorted,
    moe_loss_from_variables,
)

E, K, H, F = 8, 2, 32, 64


def _dense_from_sorted(sr, T, capacity):
    """Rebuild [T, E, C] dispatch/combine tensors from SortedRouting."""
    d = np.zeros((T, E, capacity), np.float32)
    c = np.zeros((T, E, capacity), np.float32)
    tok, slot, gate = (np.asarray(sr.token_idx), np.asarray(sr.slot),
                       np.asarray(sr.gate))
    for i in range(len(tok)):
        if slot[i] < E * capacity:
            e, pos = divmod(int(slot[i]), capacity)
            d[tok[i], e, pos] = 1.0
            c[tok[i], e, pos] = gate[i]
    return d, c


class TestSortedRouting:
    def test_slot_assignment_matches_dense(self):
        T, cap = 64, 16  # tight capacity: ~11% of assignments drop
        logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
        dense = compute_routing(logits, K, cap, normalize_topk=True)
        srt = compute_routing_sorted(logits, K, cap, normalize_topk=True)
        d, c = _dense_from_sorted(srt, T, cap)
        np.testing.assert_array_equal(d, np.asarray(dense.dispatch_mask))
        np.testing.assert_allclose(c, np.asarray(dense.combine_weights),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(srt.aux_loss),
                                   np.asarray(dense.aux_loss), atol=1e-6)
        np.testing.assert_allclose(np.asarray(srt.dropped_fraction),
                                   np.asarray(dense.dropped_fraction),
                                   atol=1e-6)

    def test_dropless_keeps_every_assignment(self):
        T = 48
        logits = jax.random.normal(jax.random.PRNGKey(1), (T, E))
        srt = compute_routing_sorted(logits, K, None, normalize_topk=False)
        assert srt.slot is None
        assert int(np.asarray(srt.counts).sum()) == K * T
        assert float(srt.dropped_fraction) == 0.0
        # rows are expert-sorted and gates carry the raw softmax mass
        ex = np.asarray(srt.expert_idx)
        assert (np.diff(ex) >= 0).all()
        probs = np.asarray(srt.probs)
        tok = np.asarray(srt.token_idx)
        np.testing.assert_allclose(np.asarray(srt.gate),
                                   probs[tok, ex], atol=1e-6)

    def test_normalized_gates_sum_to_one_per_token(self):
        T = 32
        logits = jax.random.normal(jax.random.PRNGKey(2), (T, E))
        srt = compute_routing_sorted(logits, K, None, normalize_topk=True)
        sums = np.zeros(T)
        np.add.at(sums, np.asarray(srt.token_idx), np.asarray(srt.gate))
        np.testing.assert_allclose(sums, 1.0, atol=1e-5)


def _layer(mode, capf, act="swiglu", **kw):
    return SwitchMLP(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                     top_k=K, capacity_factor=capf, activation=act,
                     dispatch_mode=mode, warn_on_dropped_losses=False, **kw)


def _run(mode, capf, act="swiglu"):
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 4, H),
                          jnp.float32).astype(jnp.bfloat16)
    m = _layer(mode, capf, act)
    params = m.init(jax.random.PRNGKey(4), x)
    return m, params, x


class TestDispatchModeParity:
    @pytest.mark.parametrize("act", ["swiglu", "gelu"])
    def test_scatter_matches_einsum_with_drops(self, act):
        me, pe, x = _run("einsum", 1.25, act)
        ms, ps, _ = _run("scatter", 1.25, act)
        ye = np.asarray(me.apply(pe, x), np.float32)
        ys = np.asarray(ms.apply(ps, x), np.float32)
        np.testing.assert_allclose(ye, ys, atol=3e-2)

    @pytest.mark.parametrize("act", ["swiglu", "gelu"])
    def test_ragged_matches_einsum_dropless(self, act):
        me, pe, x = _run("einsum", float(E) / K, act)
        mr, pr, _ = _run("ragged", float(E) / K, act)
        ye = np.asarray(me.apply(pe, x), np.float32)
        yr = np.asarray(mr.apply(pr, x), np.float32)
        np.testing.assert_allclose(ye, yr, atol=3e-2)

    def test_param_trees_identical_across_modes(self):
        trees = [jax.tree.map(jnp.shape, _run(m, float(E) / K)[1])
                 for m in ("einsum", "scatter", "ragged")]
        assert trees[0] == trees[1] == trees[2]

    @pytest.mark.parametrize("mode,capf", [("scatter", 1.25),
                                           ("ragged", 4.0)])
    def test_grads_match_einsum(self, mode, capf):
        x = jax.random.normal(jax.random.PRNGKey(5), (16, 4, H),
                              jnp.float32).astype(jnp.bfloat16)
        tgt = jax.random.normal(jax.random.PRNGKey(6), (16, 4, H))

        def loss(params, m, x):
            y, var = m.apply(params, x, mutable=["moe_losses"])
            return (jnp.mean((y.astype(jnp.float32) - tgt) ** 2)
                    + moe_loss_from_variables(var))

        grads = {}
        for md in ("einsum", mode):
            m = _layer(md, capf)
            p = m.init(jax.random.PRNGKey(4), x)
            grads[md] = jax.grad(loss)(p, m, x)
        for ge, gm in zip(jax.tree.leaves(grads["einsum"]),
                          jax.tree.leaves(grads[mode])):
            scale = float(jnp.abs(ge).max()) + 1e-9
            np.testing.assert_allclose(np.asarray(gm) / scale,
                                       np.asarray(ge) / scale, atol=2e-2)


class TestAutoResolution:
    def test_auto_picks_ragged_only_when_dropless_single_rank(self):
        m = _layer("auto", float(E) / K)
        assert m._resolve_dispatch(ep=1, capacity=64, num_tokens=64) == \
            "ragged"
        assert m._resolve_dispatch(ep=1, capacity=16, num_tokens=64) == \
            "scatter"
        assert m._resolve_dispatch(ep=2, capacity=64, num_tokens=64) == \
            "scatter"

    def test_ragged_with_ep_rejected(self):
        with pytest.raises(ValueError, match="all_to_all"):
            _layer("ragged", 4.0)._resolve_dispatch(
                ep=2, capacity=64, num_tokens=64)

    def test_expert_choice_keeps_dense_path(self):
        m = _layer("auto", 1.0, router_type="expert_choice")
        assert m._resolve_dispatch(ep=1, capacity=64, num_tokens=64) == \
            "einsum"


class TestExpertParallelScatter:
    @pytest.mark.slow
    def test_scatter_under_ep4_matches_einsum(self):
        """Identical params + routing: the scatter dispatch's [E, C, h]
        slot layout must ride the expert-parallel all_to_all exactly like
        the einsum dispatch (test_moe.py TestExpertParallel fixture)."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.testing import shard_map
        from apex_tpu.transformer import parallel_state

        E_, ep, hidden, ffn = 4, 4, 16, 32
        if len(jax.devices()) < ep:
            pytest.skip("needs >=4 devices")
        rng = np.random.RandomState(7)
        params = {
            "router": {"gate_weight": jnp.asarray(
                rng.randn(hidden, E_) * 0.2, jnp.float32)},
            "experts": {
                "w1": jnp.asarray(rng.randn(E_, hidden, ffn) * 0.1,
                                  jnp.float32),
                "b1": jnp.zeros((E_, ffn), jnp.float32),
                "w2": jnp.asarray(rng.randn(E_, ffn, hidden) * 0.1,
                                  jnp.float32),
                "b2": jnp.zeros((E_, hidden), jnp.float32),
            },
        }
        x = jnp.asarray(rng.randn(8, ep, hidden), jnp.float32)
        parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=ep, devices=jax.devices()[:ep])
        mesh = parallel_state.get_mesh()
        pspec = {"router": {"gate_weight": P()},
                 "experts": {k: P("ep") for k in params["experts"]}}

        outs = {}
        for mode in ("einsum", "scatter"):
            layer = SwitchMLP(hidden_size=hidden, ffn_hidden_size=ffn,
                              num_experts=E_, top_k=2, capacity_factor=1.0,
                              dispatch_mode=mode,
                              compute_dtype=jnp.float32,
                              warn_on_dropped_losses=False)

            @shard_map(mesh=mesh, in_specs=(pspec, P(None, "ep", None)),
                       out_specs=P(None, "ep", None))
            def run(p, xs, layer=layer):
                return layer.apply({"params": p}, xs)

            outs[mode] = np.asarray(run(params, x))
        np.testing.assert_allclose(outs["scatter"], outs["einsum"],
                                   rtol=2e-4, atol=2e-4)


class TestLinearScaling:
    @pytest.mark.slow  # tier-1 budget: bench-flavored scaling sweep
    def test_sorted_dispatch_work_is_linear_in_tokens(self):
        """FLOP accounting via jax.jit(...).lower().compile().cost_analysis:
        the dense einsum dispatch/combine cost per token grows ~linearly
        with T (quadratic total); the ragged path's per-token cost stays
        flat. Asserted as a ratio bound rather than wall-clock so the
        test is deterministic on any backend."""
        def flops(mode, T):
            m = _layer(mode, float(E) / K)
            x = jnp.zeros((T, 1, H), jnp.bfloat16)
            p = m.init(jax.random.PRNGKey(0), x)
            c = jax.jit(lambda x: m.apply(p, x)).lower(x).compile()
            (an,) = [c.cost_analysis()] if isinstance(
                c.cost_analysis(), dict) else [c.cost_analysis()[0]]
            return an["flops"] / T

        per_tok = {mode: (flops(mode, 256), flops(mode, 1024))
                   for mode in ("einsum", "ragged")}
        # dense: per-token flops grow ~4x from T=256 -> 1024 (C ~ T)
        assert per_tok["einsum"][1] / per_tok["einsum"][0] > 2.5
        # ragged: flat (FFN work only), well under 1.5x
        assert per_tok["ragged"][1] / per_tok["ragged"][0] < 1.5
