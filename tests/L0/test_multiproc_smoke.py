"""Multi-host (DCN) bring-up smoke test: two REAL processes form a
jax.distributed cluster through the multiproc launcher and run a psum
across hosts.

Mirrors the reference's single-node multi-process strategy
(MultiProcessTestCase spawning NCCL workers, distributed_test_base.py:22-74)
— here each spawned process is one 'host' with one CPU device, launched
via apex_tpu.parallel.multiproc (the env hand-off path a scheduler would
use), and the cross-host collective rides the jax.distributed (DCN-analog)
backend.
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
import jax
# the tunneled-TPU plugin ignores the JAX_PLATFORMS env var; the config
# route must run before any backend/distributed init (see tests/conftest)
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["APEX_TPU_REPO"])
from apex_tpu.parallel.multiproc import initialize_distributed
initialize_distributed()  # reads APEX_TPU_* env set by the launcher
assert jax.process_count() == 2, jax.process_count()
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
import numpy as np
mesh = Mesh(np.asarray(jax.devices()), ("dp",))
@jax.jit
def allreduce(x):
    return jax.shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                         in_specs=P("dp"), out_specs=P())(x)
import jax.experimental.multihost_utils as mh
local = jnp.full((1, 4), float(jax.process_index() + 1))
x = mh.host_local_array_to_global_array(local, mesh, P("dp"))
out = allreduce(x)
got = np.asarray(mh.global_array_to_host_local_array(out, mesh, P()))
np.testing.assert_allclose(got, 3.0)  # 1 + 2 across the two hosts
print(f"RANK{jax.process_index()}_OK")
"""


@pytest.mark.slow
def test_two_process_cluster_psum(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env.update({"JAX_PLATFORMS": "cpu", "APEX_TPU_REPO": repo,
                "JAX_NUM_CPU_DEVICES": "1",
                "PALLAS_AXON_POOL_IPS": ""})
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "apex_tpu.parallel.multiproc",
             "--nnodes", "2", "--node_rank", str(r),
             "--coordinator", f"127.0.0.1:{port}", str(script)],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for r in range(2)
    ]
    outs = []
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    assert "RANK0_OK" in outs[0] + outs[1]
    assert "RANK1_OK" in outs[0] + outs[1]
