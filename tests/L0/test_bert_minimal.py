"""Minimal BERT end-to-end training under tensor + data parallelism.

Parity: reference tests/L0/run_transformer/test_bert_minimal.py — build the
in-package BERT via the provider, run real training steps under the
parallel runtime, assert the loss trends down. Here: tp=2 x dp=2 over 4
of the CPU-mesh devices, vocab-parallel MLM cross-entropy, FusedLAMB.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import TransformerConfig
from apex_tpu.optimizers import FusedLAMB
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.testing.standalone_bert import (
    bert_loss_fn,
    bert_model_provider,
)

TP, DP = 2, 2
SEQ = 16


@pytest.fixture
def bert_setup():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, devices=jax.devices()[:TP * DP])
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=32,
        compute_dtype=jnp.bfloat16, use_flash_attention=False,
        attn_mask_type=AttnMaskType.padding)
    yield mesh, cfg
    parallel_state.destroy_model_parallel()


@pytest.mark.slow
def test_bert_tp_dp_training_loss_decreases(bert_setup):
    mesh, cfg = bert_setup
    model = bert_model_provider(config=cfg)
    global_b = 4 * DP
    rng = np.random.RandomState(0)
    # learnable MLM task: every label is token+1 mod 32
    tokens = jnp.asarray(rng.randint(0, 32, size=(global_b, SEQ)))
    labels = (tokens + 1) % 32
    padding_mask = jnp.ones((global_b, SEQ), jnp.int32)
    loss_mask = jnp.ones((global_b, SEQ), jnp.float32)
    nsp_labels = jnp.asarray(rng.randint(0, 2, size=(global_b,)))

    opt = FusedLAMB(lr=1e-2)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(), P("dp"), P("dp")), out_specs=P(),
                       check_vma=False)
    def init_fn(key, tok, pm):
        return model.init(key, tok, pm, jnp.zeros_like(tok))

    params = init_fn(jax.random.PRNGKey(0), tokens, padding_mask)
    opt_state = opt.init(params)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
        out_specs=(P(), P(), P()),
        check_vma=False)
    def train_step(params, opt_state, tok, pm, lab, lmask, nsp):
        def loss_fn(p):
            mlm, nspl = model.apply(p, tok, pm, jnp.zeros_like(tok))
            return bert_loss_fn(mlm, nspl, lab, lmask, nsp)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # DP grad sync; TP grads of replicated params are already synced
        # by the collective-backward TP layers.
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "dp"), grads)
        new_params, new_opt_state = opt.step(grads, opt_state, params)
        return new_params, new_opt_state, jax.lax.pmean(loss, "dp")

    losses = []
    for _ in range(16):
        params, opt_state, loss = train_step(
            params, opt_state, tokens, padding_mask, labels, loss_mask,
            nsp_labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.8 * losses[0], losses


@pytest.mark.slow
def test_bert_tp2_output_shape_matches_tp1(bert_setup):
    """TP=2 vocab-sharded logits reassemble to the TP=1 output shape
    (value parity across tp sizes is covered at layer level in
    test_transformer_tp.py; inits differ across sharding here)."""
    mesh, cfg = bert_setup
    model = bert_model_provider(config=cfg)
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, 32, size=(2, SEQ)))
    pm = jnp.ones((2, SEQ), jnp.int32)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P(), P()),
                       out_specs=P(), check_vma=False)
    def init_fn(key, tok):
        return model.init(key, tok, jnp.ones_like(tok),
                          jnp.zeros_like(tok))

    params = init_fn(jax.random.PRNGKey(3), tokens)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P(), P(), P()),
                       out_specs=(P("tp"), P()), check_vma=False)
    def fwd_tp(params, tok, pm):
        mlm, nsp = model.apply(params, tok, pm, jnp.zeros_like(tok))
        return mlm.transpose(2, 0, 1), nsp  # vocab shard leading

    mlm_sharded, nsp = fwd_tp(params, tokens, pm)
    # gather vocab shards -> full logits [b, s, V]
    mlm_tp = jnp.transpose(mlm_sharded, (1, 2, 0))

    # TP=1 shape oracle (value parity across tp sizes is covered at layer
    # level in test_transformer_tp.py; here the gathered vocab-sharded
    # logits must reassemble to the TP=1 output shape).
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model1 = bert_model_provider(config=cfg)
    p1 = model1.init(jax.random.PRNGKey(3), tokens, pm,
                     jnp.zeros_like(tokens))
    mlm1, nsp1 = model1.apply(p1, tokens, pm, jnp.zeros_like(tokens))
    assert mlm_tp.shape == mlm1.shape
    assert nsp.shape == nsp1.shape
    assert bool(jnp.isfinite(mlm_tp).all())
