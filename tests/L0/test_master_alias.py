"""Master-weight buffer aliasing regression (VERDICT r3 item 5).

Root cause of the round-2/3 "ResNet donation INVALID_ARGUMENT":
``astype(fp32)`` is a no-op returning the SAME buffer for leaves already
fp32 (all norm params under amp O2), so fp32 masters aliased live params
and a step donating both presented one buffer twice to XLA's Execute().
Masters must be alias-free copies; the ``double-donation`` lint rule
(apex_tpu.analysis) now catches the aliasing at trace time —
tests/L0/test_analysis.py holds the rule-level regression that retired
the old tools/donation_repro.py bisection ladder.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


def _buffer_ids(tree):
    return {id(leaf) for leaf in jax.tree_util.tree_leaves(tree)}


def test_amp_o2_masters_do_not_alias_params():
    params = {"w": jnp.ones((8, 8), jnp.bfloat16),
              "norm": {"scale": jnp.ones((8,), jnp.float32)}}
    params, opt = amp.initialize(params, FusedAdam(lr=1e-3),
                                 opt_level="O2", verbosity=0)
    state = opt.init(params)
    masters = state["inner"].get("amp_master") or state["inner"].get(
        "master")
    assert masters is not None
    assert not (_buffer_ids(params) & _buffer_ids(masters)), (
        "fp32 masters alias live params — donation double-donates")


def test_fused_adam_master_weights_do_not_alias():
    params = {"a": jnp.ones((4,), jnp.float32)}
    opt = FusedAdam(lr=1e-3, master_weights=True)
    state = opt.init(params)
    assert not (_buffer_ids(params) & _buffer_ids(state["master"]))


def test_o2_donated_step_executes():
    """The donated amp-O2 train step (the bench shape, tiny) runs —
    the exact configuration that used to raise INVALID_ARGUMENT."""
    params = {"w": jnp.ones((16, 16), jnp.bfloat16),
              "ln": jnp.ones((16,), jnp.float32)}
    params, opt = amp.initialize(params, FusedAdam(lr=1e-3),
                                 opt_level="O2", verbosity=0)
    opt_state = opt.init(params)
    x = jnp.ones((4, 16), jnp.bfloat16)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x):
        def loss(p):
            return jnp.mean((x @ p["w"]).astype(jnp.float32) * p["ln"])

        scale = opt_state["scaler"].loss_scale
        g = jax.grad(lambda p: loss(p) * scale)(params)
        return opt.step(g, opt_state, params)

    for _ in range(3):
        params, opt_state = step(params, opt_state, x)
    assert np.isfinite(float(jnp.sum(params["ln"])))
