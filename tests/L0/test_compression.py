"""Block-quantized gradient collectives + error feedback (ISSUE 1).

Covers the tentpole acceptance criteria on the virtual 8-device CPU mesh:
int8 compressed allreduce matches the fp32 psum within the per-block
quantization bound; compress="bf16" is exact on bf16 grads; the ragged
tail (size not divisible by the block) round-trips within bound; the
Pallas quantize/dequantize kernel (interpreter mode) matches the jnp
oracle; and an int8-compressed DDP training run converges within 2% of
the uncompressed baseline thanks to the error-feedback residual.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import (
    DistributedDataParallel,
    all_reduce_gradients,
    all_reduce_gradients_bucketed,
    compression,
    init_residual,
)
from apex_tpu.testing import shard_map


class TestQuantizeDequantize:
    def test_roundtrip_bound_ragged_tail(self, rng):
        """n=1000 with block 256 -> 3 full blocks + a 232-ragged tail;
        every element round-trips within half the block's scale."""
        n = 1000
        x = jnp.asarray((rng.randn(n) * 3).astype(np.float32))
        q, s = compression.quantize_blockwise(x)
        assert q.dtype == jnp.int8 and q.shape == (4, 256)
        y = compression.dequantize_blockwise(q, s, n=n)
        err = np.abs(np.asarray(y) - np.asarray(x))
        bound = np.repeat(np.asarray(s).reshape(-1), 256)[:n] / 2
        assert (err <= bound * (1 + 1e-6) + 1e-8).all()

    def test_zero_block_is_exact(self):
        x = jnp.zeros((512,), jnp.float32)
        q, s = compression.quantize_blockwise(x)
        y = compression.dequantize_blockwise(q, s, n=512)
        np.testing.assert_array_equal(np.asarray(y), np.zeros(512))
        assert np.isfinite(np.asarray(s)).all()

    def test_pallas_kernel_matches_jnp(self, rng):
        """Interpreter-mode Pallas kernel vs the pure-jnp oracle: the
        int8 codes are identical and the dequantized values match."""
        n = 300  # ragged + forces row padding inside the kernel wrapper
        x = jnp.asarray((rng.randn(n) * 0.7).astype(np.float32))
        q_ref, s_ref = compression.quantize_blockwise(x)
        y_ref = compression.dequantize_blockwise(q_ref, s_ref, n=n)
        compression.force_interpret(True)
        try:
            q_pl, s_pl = compression.quantize_blockwise(x)
            y_pl = compression.dequantize_blockwise(q_pl, s_pl, n=n)
        finally:
            compression.force_interpret(False)
        np.testing.assert_array_equal(np.asarray(q_ref), np.asarray(q_pl))
        np.testing.assert_allclose(np.asarray(s_ref), np.asarray(s_pl))
        np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_pl))


@pytest.mark.multi_device
class TestCompressedAllReduce:
    def test_int8_matches_fp32_within_block_bound(self, rng, dp_mesh):
        """The acceptance parity check: compressed allreduce (average)
        vs fp32 psum, elementwise within shared-block-scale/2 — each
        replica's quantization error is <= s/2, and averaging the 8
        errors keeps the bound."""
        mesh = dp_mesh(8)
        n = 1000
        g = jnp.asarray(rng.randn(8, n).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=(P("dp"), P("dp")))
        def f(gs):
            out, res = all_reduce_gradients({"w": gs[0]}, "dp",
                                            compress="int8")
            return out["w"][None], res["w"][None]

        out, res = f(g)
        x = np.asarray(g)
        mean = x.mean(0)
        err = np.abs(np.asarray(out)[0] - mean)
        padded = np.pad(x, ((0, 0), (0, 1024 - n))).reshape(8, 4, 256)
        shared_scale = np.abs(padded).max(-1).max(0) / 127.0
        bound = np.repeat(shared_scale, 256)[:n] / 2
        assert (err <= bound * (1 + 1e-5) + 1e-8).all()
        # the residual is exactly the local quantization error: nonzero
        assert np.abs(np.asarray(res)).max() > 0

    def test_bf16_mode_exact_on_bf16_grads(self, rng, dp_mesh):
        """compress="bf16" on bf16 grads is a no-op cast: bitwise equal
        to the uncompressed psum (which also sums in bf16)."""
        mesh = dp_mesh(8)
        g = jnp.asarray(rng.randn(8, 512).astype(np.float32)
                        ).astype(jnp.bfloat16)

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=(P("dp"), P("dp")))
        def f(gs):
            a = all_reduce_gradients({"w": gs[0]}, "dp")["w"]
            b = all_reduce_gradients({"w": gs[0]}, "dp",
                                     compress="bf16")["w"]
            return a[None], b[None]

        a, b = f(g)
        assert b.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(a.astype(jnp.float32)),
            np.asarray(b.astype(jnp.float32)))

    def test_bucketed_int8(self, rng, dp_mesh):
        """Bucketed path: quantization runs per flat bucket; result
        within the global bound max|g|/127/2 and the residual pytree
        stays leaf-shaped."""
        mesh = dp_mesh(8)
        n = 1000
        g = jnp.asarray(rng.randn(8, n).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=(P("dp"), P("dp"), P("dp")))
        def f(gs):
            grads = {"a": gs[0, :600].reshape(30, 20), "b": gs[0, 600:]}
            out, res = all_reduce_gradients_bucketed(
                grads, "dp", message_size=350, compress="int8")
            return (out["a"].reshape(-1)[None], out["b"][None],
                    res["a"].reshape(-1)[None])

        oa, ob, ra = f(g)
        x = np.asarray(g)
        mean = x.mean(0)
        got = np.concatenate([np.asarray(oa)[0], np.asarray(ob)[0]])
        bound = np.abs(x).max() / 127.0 / 2
        assert np.abs(got - mean).max() <= bound * (1 + 1e-5)
        assert np.asarray(ra).shape == (8, 600)  # per-replica residuals

    def test_predivide_composes(self, rng, dp_mesh):
        """gradient_predivide_factor with int8: same average within the
        (rescaled) quantization bound."""
        mesh = dp_mesh(8)
        g = jnp.asarray(rng.randn(8, 256).astype(np.float32))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"))
        def f(gs):
            out, _ = all_reduce_gradients(
                {"w": gs[0]}, "dp", compress="int8",
                gradient_predivide_factor=4.0)
            return out["w"][None]

        out = f(g)
        x = np.asarray(g)
        bound = (np.abs(x / 4).max() / 127.0 / 2) * 4 * (1 + 1e-5)
        assert np.abs(np.asarray(out)[0] - x.mean(0)).max() <= bound


def _mlp_init(rng):
    return {
        "w1": jnp.asarray((rng.randn(16, 32) / 4).astype(np.float32)),
        "b1": jnp.zeros((32,), jnp.float32),
        "w2": jnp.asarray((rng.randn(32, 1) / 5).astype(np.float32)),
        "b2": jnp.zeros((1,), jnp.float32),
    }


def _mlp_loss(p, x, y):
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    out = h @ p["w2"] + p["b2"]
    return jnp.mean((out - y) ** 2)


@pytest.mark.multi_device
class TestErrorFeedbackConvergence:
    def test_toy_mlp_within_2pct(self, rng, dp_mesh):
        """The acceptance convergence check: 200 SGD steps on a toy MLP
        regression (noisy targets -> nonzero loss floor), int8-compressed
        DDP with error feedback vs fp32 psum; final losses within 2%."""
        mesh = dp_mesh(8)
        w_true = rng.randn(16, 1).astype(np.float32)
        x = rng.randn(256, 16).astype(np.float32)
        y = x @ w_true + 0.1 * rng.randn(256, 1).astype(np.float32)
        xj, yj = jnp.asarray(x), jnp.asarray(y)
        params0 = _mlp_init(rng)

        def train(compress):
            ddp = DistributedDataParallel(axis_name="dp",
                                          compress=compress)
            params = jax.tree_util.tree_map(lambda a: a, params0)
            residual = init_residual(params) if compress else None

            def step(p, res, xb, yb):
                loss, grads = jax.value_and_grad(_mlp_loss)(p, xb, yb)
                if compress == "int8":
                    grads, res = ddp.sync(grads, res)
                else:
                    grads = ddp.sync(grads)
                p = jax.tree_util.tree_map(lambda w, g: w - 0.05 * g,
                                           p, grads)
                return p, res, loss

            sharded = shard_map(step, mesh=mesh,
                                in_specs=(P(), P(), P("dp"), P("dp")),
                                out_specs=(P(), P(), P()))
            jitted = jax.jit(sharded)
            loss = None
            for _ in range(200):
                params, residual, loss = jitted(params, residual, xj, yj)
            return float(loss)

        loss_fp32 = train(None)
        loss_int8 = train("int8")
        assert loss_int8 == pytest.approx(loss_fp32, rel=0.02), \
            f"int8+EF {loss_int8} vs fp32 {loss_fp32}"


class TestByteAccounting:
    def test_int8_cuts_bytes_3x(self):
        n = 25_600_000  # ~ResNet-50 parameter count
        fp32 = compression.estimate_allreduce_bytes(n, world=8)
        int8 = compression.estimate_allreduce_bytes(n, world=8,
                                                    compress="int8")
        bf16 = compression.estimate_allreduce_bytes(n, world=8,
                                                    compress="bf16")
        assert fp32 / int8 >= 3.0
        assert fp32 / bf16 == pytest.approx(2.0)
        assert compression.estimate_allreduce_bytes(n, world=1) == 0
