"""apex_tpu.resilience chaos suite (ISSUE 3 acceptance).

Every fault here is injected deterministically by resilience.faults —
NaN grads at a chosen step, checkpoint writes that die after partial
bytes, torn/corrupted landed checkpoints, simulated SIGTERM — so each
chaos scenario is a plain regression test:

- NaN at step N  -> exactly that step skipped, training stays finite
  and lands within tolerance of the uninjected run;
- kill mid-write -> the step never becomes selectable; a landed torn
  write is rejected by manifest verification and ``restore`` falls
  back to the last verified step with a loud warning;
- the guard adds zero host syncs to the compiled step (no callback
  custom-calls in the lowered HLO, same assertion as test_telemetry).

The clip_grad / LossScaler satellite regressions live here too: both
fixes exist because of the guard story (non-finite handling must not
silently poison or silently floor).
"""

import os
import pickle
import signal

import concurrent.futures

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import checkpoint, resilience
from apex_tpu.checkpoint import (
    AsyncCheckpointer,
    CheckpointCorruptError,
    latest_step,
    restore,
    save,
    verify_checkpoint,
)
from apex_tpu.resilience import (
    GuardState,
    NonFiniteError,
    PreemptionGuard,
    check_guard,
    faults,
    guarded_update,
    init_guard_state,
    nonfinite_flag,
)
from apex_tpu.telemetry import MetricsRegistry, use_registry


# ---------------------------------------------------------------------------
# guard: flag derivation
# ---------------------------------------------------------------------------

def test_nonfinite_flag_detects_nan_and_inf():
    clean = {"w": jnp.ones((4,)), "n": jnp.arange(3)}  # ints ignored
    assert float(nonfinite_flag(clean)) == 0.0
    assert float(nonfinite_flag({"w": jnp.array([1.0, jnp.nan])})) == 1.0
    assert float(nonfinite_flag({"w": jnp.array([jnp.inf, 0.0])})) == 1.0
    # integer-only trees have nothing to be non-finite
    assert float(nonfinite_flag({"n": jnp.arange(5)})) == 0.0


# ---------------------------------------------------------------------------
# guard: skip semantics
# ---------------------------------------------------------------------------

def _sgd(lr=0.1):
    def update(grads, params):
        return jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                      params, grads)
    return update


def test_guarded_update_commits_finite_and_skips_poisoned():
    params = {"w": jnp.ones((4,))}
    gst = init_guard_state()

    good = {"w": jnp.full((4,), 2.0)}
    params1, gst = guarded_update(good, _sgd(), params, gst)
    np.testing.assert_allclose(params1["w"], 1.0 - 0.1 * 2.0)
    assert int(gst.total_skips) == 0
    assert int(gst.last_skipped) == 0

    bad = {"w": jnp.array([1.0, jnp.nan, 1.0, 1.0])}
    params2, gst = guarded_update(bad, _sgd(), params1, gst)
    # skipped step: state bit-identical
    np.testing.assert_array_equal(params2["w"], params1["w"])
    assert (int(gst.consecutive_skips), int(gst.total_skips),
            int(gst.last_skipped)) == (1, 1, 1)

    # a clean step resets the streak but not the lifetime total
    params3, gst = guarded_update(good, _sgd(), params2, gst)
    assert not np.array_equal(params3["w"], params2["w"])
    assert (int(gst.consecutive_skips), int(gst.total_skips)) == (0, 1)


def test_guarded_update_works_under_jit():
    @jax.jit
    def step(params, grads, gst):
        return guarded_update(grads, _sgd(), params, gst)

    params = {"w": jnp.ones((4,))}
    gst = init_guard_state()
    params, gst = step(params, {"w": jnp.full((4,), jnp.nan)}, gst)
    np.testing.assert_array_equal(params["w"], 1.0)
    assert int(gst.total_skips) == 1


def test_guarded_update_rejects_structure_change():
    def bad_update(grads, params):
        return {"w": params["w"], "extra": params["w"]}

    with pytest.raises(ValueError, match="tree structure"):
        guarded_update({"w": jnp.ones(2)}, bad_update,
                       {"w": jnp.ones(2)}, init_guard_state())


def test_guarded_update_found_inf_forces_skip():
    """The scaler's found_inf count composes into the skip decision even
    when the (already-unscaled) grads look finite."""
    params = {"w": jnp.ones((2,))}
    new, gst = guarded_update({"w": jnp.ones((2,))}, _sgd(), params,
                              init_guard_state(),
                              found_inf=jnp.asarray(3.0))
    np.testing.assert_array_equal(new["w"], params["w"])
    assert int(gst.last_skipped) == 1


def test_guarded_update_scaler_always_commits():
    """LossScaler.update WANTS the overflow (that is how dynamic scaling
    backs off) — its state commits even on skipped steps."""
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler("dynamic", init_scale=8.0, scale_factor=2.0)
    sstate = scaler.init_state()
    params = {"w": jnp.ones((2,))}

    bad = {"w": jnp.full((2,), jnp.nan)}
    new, gst, sstate = guarded_update(
        bad, _sgd(), params, init_guard_state(),
        scaler=scaler, scaler_state=sstate)
    np.testing.assert_array_equal(new["w"], params["w"])  # step skipped
    assert float(sstate.loss_scale) == 4.0                # scale backed off
    assert int(gst.last_skipped) == 1

    good = {"w": jnp.ones((2,))}
    new, gst, sstate = guarded_update(
        good, _sgd(), new, gst, scaler=scaler, scaler_state=sstate)
    assert float(sstate.loss_scale) == 4.0  # clean step: window counts up
    assert int(sstate.unskipped) == 1
    assert int(gst.consecutive_skips) == 0


def test_guarded_update_scaler_requires_state():
    from apex_tpu.amp.scaler import LossScaler

    with pytest.raises(ValueError, match="scaler_state"):
        guarded_update({"w": jnp.ones(2)}, _sgd(), {"w": jnp.ones(2)},
                       init_guard_state(),
                       scaler=LossScaler("dynamic"))


# ---------------------------------------------------------------------------
# guard: host-side escalation + telemetry
# ---------------------------------------------------------------------------

def test_check_guard_escalates_after_k_consecutive():
    gst = GuardState(consecutive_skips=jnp.asarray(2, jnp.int32),
                     total_skips=jnp.asarray(5, jnp.int32),
                     last_skipped=jnp.asarray(1, jnp.int32))
    assert check_guard(gst, max_consecutive_skips=3) == 2
    with pytest.raises(NonFiniteError, match="3 consecutive"):
        check_guard(gst._replace(
            consecutive_skips=jnp.asarray(3, jnp.int32)),
            max_consecutive_skips=3)


def test_check_guard_env_threshold(monkeypatch):
    monkeypatch.setenv(resilience.guard.ENV_MAX_SKIPS, "1")
    gst = GuardState(consecutive_skips=jnp.asarray(1, jnp.int32),
                     total_skips=jnp.asarray(1, jnp.int32),
                     last_skipped=jnp.asarray(1, jnp.int32))
    with pytest.raises(NonFiniteError):
        check_guard(gst)


def test_check_guard_reconciles_counter_when_polled_sparsely():
    """check_guard may run every N steps; the steps_skipped counter must
    match the device-side lifetime total, not the poll count."""
    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        gst = GuardState(consecutive_skips=jnp.asarray(1, jnp.int32),
                         total_skips=jnp.asarray(4, jnp.int32),
                         last_skipped=jnp.asarray(1, jnp.int32))
        check_guard(gst, max_consecutive_skips=100)
        check_guard(gst, max_consecutive_skips=100)  # no double count
    snap = reg.snapshot()
    assert snap["counters"]["guard/steps_skipped"] == 4
    assert snap["gauges"]["guard/consecutive_skips"] == 1


# ---------------------------------------------------------------------------
# chaos: NaN injection through the real DDP + EF-residual step
# ---------------------------------------------------------------------------

def _make_guarded_ddp_step(mesh, hidden, nan_step):
    """The docs/parallelism.md composition: int8-compressed sync, EF
    residual inside the guarded state, flag from LOCAL pre-compression
    grads, deterministic NaN injection at ``nan_step``."""
    from apex_tpu.parallel import DistributedDataParallel

    ddp = DistributedDataParallel(axis_name="dp", compress="int8")

    def loss_fn(p, xb, yb):
        h = jnp.tanh(xb @ p["w0"] + p["b0"]) @ p["w1"]
        return jnp.mean((h - yb) ** 2)

    def step_fn(p, res, gst, step, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb)
        grads = faults.inject_nan(grads, step, nan_step)
        flag = nonfinite_flag(grads)
        synced, new_res = ddp.sync(grads, res)

        def commit(g, st):
            prev_p, _ = st
            new_p = jax.tree_util.tree_map(
                lambda w, gg: w - 0.05 * gg, prev_p, g)
            return (new_p, new_res)

        (p, res), gst = guarded_update(synced, commit, (p, res), gst,
                                       axis_name="dp", flag=flag)
        return p, res, gst, loss

    sharded = jax.shard_map(
        step_fn, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P(), P()), check_vma=False)
    return ddp, jax.jit(sharded)


def _init_problem(hidden, batch):
    rng = np.random.RandomState(0)
    params = {
        "w0": jnp.asarray(rng.randn(hidden, hidden).astype(np.float32)
                          / np.sqrt(hidden)),
        "b0": jnp.zeros((hidden,), jnp.float32),
        "w1": jnp.asarray(rng.randn(hidden, hidden).astype(np.float32)
                          / np.sqrt(hidden)),
    }
    x = jnp.asarray(rng.randn(batch, hidden).astype(np.float32))
    y = jnp.asarray(rng.randn(batch, hidden).astype(np.float32))
    return params, x, y


@pytest.mark.multi_device
def test_nan_injection_skips_exactly_one_step_and_converges(dp_mesh):
    """Chaos (a): NaN grads at step 3 of a guarded int8-EF DDP run ->
    exactly that step skipped, final params finite, final loss within
    tolerance of the uninjected run."""
    mesh = dp_mesh(8)
    hidden, batch, steps = 32, 16, 10
    finals = {}
    for nan_step in (None, 3):
        ddp, train = _make_guarded_ddp_step(mesh, hidden, nan_step)
        params, x, y = _init_problem(hidden, batch)
        res = ddp.init_residual(params)
        gst = init_guard_state()
        loss0 = None
        for i in range(steps):
            params, res, gst, loss = train(
                params, res, gst, jnp.asarray(i, jnp.int32), x, y)
            if loss0 is None:
                loss0 = float(loss)
            check_guard(gst, max_consecutive_skips=steps + 1)
        finals[nan_step] = (params, float(loss), int(gst.total_skips))

    _, loss_clean, skipped_clean = finals[None]
    params_inj, loss_inj, skipped_inj = finals[3]
    assert skipped_clean == 0
    assert skipped_inj == 1  # exactly the poisoned step
    assert all(bool(jnp.all(jnp.isfinite(l)))
               for l in jax.tree_util.tree_leaves(params_inj))
    assert np.isfinite(loss_inj)
    assert loss_inj < loss0          # training progressed past the fault
    # one skipped SGD step on a smooth quadratic: small final-loss gap
    assert abs(loss_inj - loss_clean) <= 0.25 * abs(loss_clean) + 1e-4


@pytest.mark.multi_device
def test_skipped_step_does_not_commit_ef_residual(dp_mesh):
    """EF composition: the residual computed from poisoned gradients
    must not feed back into the next step — on a skipped step it stays
    bit-identical to the previous one."""
    mesh = dp_mesh(8)
    hidden, batch = 32, 16
    ddp, train = _make_guarded_ddp_step(mesh, hidden, nan_step=1)
    params, x, y = _init_problem(hidden, batch)
    res = ddp.init_residual(params)
    gst = init_guard_state()

    params, res0, gst, _ = train(params, res, gst,
                                 jnp.asarray(0, jnp.int32), x, y)
    # step 0 was clean: the residual carries quantization error
    assert any(float(jnp.max(jnp.abs(l))) > 0
               for l in jax.tree_util.tree_leaves(res0))
    params1, res1, gst, _ = train(params, res0, gst,
                                  jnp.asarray(1, jnp.int32), x, y)
    assert int(gst.last_skipped) == 1
    np.testing.assert_array_equal(np.asarray(params1["w0"]),
                                  np.asarray(params["w0"]))
    for a, b in zip(jax.tree_util.tree_leaves(res1),
                    jax.tree_util.tree_leaves(res0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_guard_adds_no_host_callbacks_to_compiled_step():
    """Chaos (iii): the guarded step — telemetry enabled, injection
    armed — lints clean under no-host-callback (the guard is pure
    in-graph selects + one scalar psum); assert_clean_hlo matches
    actual custom_call targets, replacing the substring grep."""
    from apex_tpu.analysis import assert_clean_hlo

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        _, train = _make_guarded_ddp_step(mesh, 16, nan_step=2)
        params, x, y = _init_problem(16, 8)
        res = jax.tree_util.tree_map(jnp.zeros_like, params)
        assert_clean_hlo(train, params, res, init_guard_state(),
                         jnp.zeros((), jnp.int32), x, y,
                         rules="no-host-callback")


# ---------------------------------------------------------------------------
# fault injectors
# ---------------------------------------------------------------------------

def test_inject_nan_is_identity_when_unarmed(monkeypatch):
    monkeypatch.delenv(faults.ENV_NAN_STEP, raising=False)
    monkeypatch.delenv(faults.ENV_FAULT_PLAN, raising=False)
    tree = {"w": jnp.ones((3,)), "n": jnp.arange(2)}
    out = faults.inject_nan(tree, jnp.asarray(0))
    np.testing.assert_array_equal(out["w"], tree["w"])
    # armed via env: fires only at the named step
    monkeypatch.setenv(faults.ENV_NAN_STEP, "2")
    assert not np.any(np.isnan(
        faults.inject_nan(tree, jnp.asarray(1))["w"]))
    poisoned = faults.inject_nan(tree, jnp.asarray(2))
    assert np.all(np.isnan(poisoned["w"]))
    np.testing.assert_array_equal(poisoned["n"], tree["n"])  # ints kept


# ---------------------------------------------------------------------------
# the consolidated fault plan (APEX_TPU_FAULT_PLAN)
# ---------------------------------------------------------------------------

def test_parse_fault_plan_grammar():
    plan = faults.parse_fault_plan(
        "nan@3:layer1;alloc@5;preempt@9;device_loss@7:4;"
        "decode@2:persistent;slot_nan@4:1;ckpt_torn@6;ckpt_fail@2")
    assert plan.step("nan") == 3
    assert plan.get("nan")["arg"] == "layer1"
    assert plan.step("alloc") == 5
    assert plan.step("preempt") == 9
    assert plan.get("device_loss") == {"kind": "device_loss",
                                       "step": 7, "arg": "4"}
    assert plan.get("decode")["arg"] == "persistent"
    assert plan.step("ckpt_fail") == 2
    assert bool(plan)
    assert not faults.parse_fault_plan("")
    assert faults.parse_fault_plan("  ;  ").get("nan") is None


@pytest.mark.parametrize("bad,match", [
    ("bogus@3", "bad entry"),
    ("nan=3", "bad entry"),
    ("nan@three", "non-integer step"),
    ("nan@1;nan@2", "duplicate entry"),
])
def test_parse_fault_plan_rejects(bad, match):
    with pytest.raises(ValueError, match=match):
        faults.parse_fault_plan(bad)


def test_fault_plan_feeds_the_env_helpers(monkeypatch):
    for var in (faults.ENV_NAN_STEP, faults.ENV_ALLOC_STEP):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv(faults.ENV_FAULT_PLAN,
                       "nan@3:layer1;alloc@5;preempt@9;device_loss@7:4")
    assert faults.nan_step_from_env() == 3
    assert faults.nan_path_from_env() == "layer1"
    assert faults.alloc_step_from_env() == 5
    assert faults.preempt_step_from_env() == 9
    assert faults.device_loss_spec_from_env() == (7, 4)
    # inject_nan picks the plan's path filter up for free
    tree = {"layer1": {"w": jnp.ones((2,))}, "layer2": {"w": jnp.ones((2,))}}
    poisoned = faults.inject_nan(tree, jnp.asarray(3))
    assert np.all(np.isnan(poisoned["layer1"]["w"]))
    assert not np.any(np.isnan(poisoned["layer2"]["w"]))
    with pytest.raises(faults.SyntheticResourceExhausted):
        faults.inject_alloc_failure(5)
    faults.inject_alloc_failure(4)  # other steps untouched


def test_legacy_fault_vars_win_with_deprecation(monkeypatch):
    monkeypatch.setenv(faults.ENV_FAULT_PLAN, "nan@3")
    monkeypatch.setenv(faults.ENV_NAN_STEP, "7")
    faults._legacy_warned.discard(faults.ENV_NAN_STEP)
    with pytest.warns(DeprecationWarning, match="APEX_TPU_FAULT_PLAN"):
        assert faults.nan_step_from_env() == 7  # legacy wins
    # warned once per process, honored silently afterwards
    assert faults.nan_step_from_env() == 7


def test_replica_loss_plan_grammar_and_injector(monkeypatch):
    """ISSUE 11 satellite: the ``replica_loss@N:R`` plan entry and the
    one-shot fleet injector (the replica-level sibling of
    device_loss, keyed on the fleet's lifetime step counter)."""
    monkeypatch.delenv(faults.ENV_FAULT_PLAN, raising=False)
    faults.disarm_replica_loss()
    # unarmed: no step fires
    assert faults.replica_loss_for(0) is None
    # grammar: kind@step:replica parses next to the other kinds
    plan = faults.parse_fault_plan("replica_loss@5:1;nan@3")
    assert plan.get("replica_loss") == {"kind": "replica_loss",
                                        "step": 5, "arg": "1"}
    with pytest.raises(ValueError, match="duplicate entry"):
        faults.parse_fault_plan("replica_loss@1;replica_loss@2")
    # API arming: fires exactly once at the named fleet step
    with faults.inject_replica_loss(2, 7) as st:
        assert faults.replica_loss_for(6) is None
        assert faults.replica_loss_for(7) == 2
        assert st["fired"] == 1
        assert faults.replica_loss_for(7) is None   # one-shot
    assert faults.replica_loss_for(7) is None       # disarmed on exit
    # env arming via the plan; arg defaults to replica 0
    monkeypatch.setenv(faults.ENV_FAULT_PLAN, "replica_loss@3")
    faults.disarm_replica_loss()
    assert faults.replica_loss_for(2) is None
    assert faults.replica_loss_for(3) == 0
    assert faults.replica_loss_for(3) is None
    faults.disarm_replica_loss()


def test_kv_corrupt_plan_grammar_and_injector(monkeypatch):
    """ISSUE 18 satellite: the ``kv_corrupt@N:R`` plan entry and the
    one-shot migration-payload corruption injector (the KV-handoff
    sibling of replica_loss — names the donor replica whose extracted
    payload the fleet flips a byte in, exercising the checksum-verify
    -> loud re-prefill fallback path end to end)."""
    monkeypatch.delenv(faults.ENV_FAULT_PLAN, raising=False)
    faults.disarm_kv_corrupt()
    # unarmed: no step fires
    assert faults.kv_corrupt_for(0) is None
    # grammar: kind@step:replica parses next to the other kinds
    plan = faults.parse_fault_plan("kv_corrupt@4:1;replica_loss@4:1")
    assert plan.get("kv_corrupt") == {"kind": "kv_corrupt",
                                      "step": 4, "arg": "1"}
    with pytest.raises(ValueError, match="duplicate entry"):
        faults.parse_fault_plan("kv_corrupt@1;kv_corrupt@2")
    # API arming: fires exactly once at the named fleet step
    with faults.inject_kv_corrupt(1, 6) as st:
        assert faults.kv_corrupt_for(5) is None
        assert faults.kv_corrupt_for(6) == 1
        assert st["fired"] == 1
        assert faults.kv_corrupt_for(6) is None     # one-shot
    assert faults.kv_corrupt_for(6) is None         # disarmed on exit
    # env arming via the plan; arg defaults to replica 0
    monkeypatch.setenv(faults.ENV_FAULT_PLAN, "kv_corrupt@2")
    faults.disarm_kv_corrupt()
    assert faults.kv_corrupt_for(1) is None
    assert faults.kv_corrupt_for(2) == 0
    assert faults.kv_corrupt_for(2) is None
    faults.disarm_kv_corrupt()


def test_inject_device_loss(monkeypatch):
    monkeypatch.delenv(faults.ENV_FAULT_PLAN, raising=False)
    faults.inject_device_loss(3)  # unarmed: no-op
    with pytest.raises(faults.DeviceLostError, match="DEVICE_LOST") \
            as exc:
        faults.inject_device_loss(3, 3, shrink_to=4, world=8)
    assert exc.value.shrink_to == 4
    monkeypatch.setenv(faults.ENV_FAULT_PLAN, "device_loss@2:1")
    faults.inject_device_loss(1)
    with pytest.raises(faults.DeviceLostError) as exc:
        faults.inject_device_loss(2)
    assert exc.value.shrink_to == 1


# ---------------------------------------------------------------------------
# checkpoint durability: manifest + verification + fallback chain
# ---------------------------------------------------------------------------

def _state(v=1.0):
    return {"w": jnp.full((8,), v), "step": jnp.asarray(int(v))}


def test_save_writes_manifest_and_verifies(tmp_path):
    path = save(str(tmp_path), 1, _state(), use_orbax=False)
    manifest = verify_checkpoint(path)
    assert manifest["format"] == checkpoint.MANIFEST_FORMAT
    assert manifest["num_leaves"] == 2
    assert "state.pkl" in manifest["files"]
    paths = {e["path"] for e in manifest["leaves"]}
    assert paths == {"w", "step"}
    restored = restore(str(tmp_path))
    np.testing.assert_array_equal(restored["w"], _state()["w"])


def test_restore_falls_back_past_corrupted_step(tmp_path):
    """Chaos (c): a bit-flipped newest checkpoint is rejected by its
    manifest and restore transparently falls back to the last verified
    step, warning loudly about what it rejected."""
    save(str(tmp_path), 1, _state(1.0), use_orbax=False)
    save(str(tmp_path), 2, _state(2.0), use_orbax=False)
    faults.corrupt_checkpoint(str(tmp_path), 2)
    with pytest.warns(UserWarning, match="REJECTED step 2"):
        restored = restore(str(tmp_path))
    assert int(restored["step"]) == 1  # the older, verified step


def test_restore_explicit_step_does_not_fall_back(tmp_path):
    save(str(tmp_path), 1, _state(1.0), use_orbax=False)
    save(str(tmp_path), 2, _state(2.0), use_orbax=False)
    faults.corrupt_checkpoint(str(tmp_path), 2)
    with pytest.raises(CheckpointCorruptError, match="sha256 mismatch"):
        restore(str(tmp_path), step=2)
    # the older step is still explicitly loadable
    assert int(restore(str(tmp_path), step=1)["step"]) == 1


def test_restore_rejects_torn_write_and_falls_back(tmp_path):
    """Chaos (b): a write killed mid-stream that still landed its step
    dir (truncated payload behind a full-size manifest) is caught by
    size verification before the unpickler ever sees the bytes."""
    save(str(tmp_path), 1, _state(1.0), use_orbax=False)
    with faults.torn_checkpoint_write(keep_bytes=32) as stats:
        save(str(tmp_path), 2, _state(2.0), use_orbax=False, retries=0)
    assert stats["fired"] == 1
    assert latest_step(str(tmp_path)) == 2  # the torn step IS visible
    with pytest.warns(UserWarning, match="torn write"):
        restored = restore(str(tmp_path))
    assert int(restored["step"]) == 1


def test_restore_metadata_audits_the_fallback(tmp_path):
    """ISSUE-8 satellite: the fallback chain's settling is auditable —
    restore metadata names the settled step and every rejected one,
    and the checkpoint/restore_fallback_step gauge lands."""
    save(str(tmp_path), 1, _state(1.0), use_orbax=False)
    save(str(tmp_path), 2, _state(2.0), use_orbax=False)
    save(str(tmp_path), 3, _state(3.0), use_orbax=False)
    faults.corrupt_checkpoint(str(tmp_path), 2)
    faults.corrupt_checkpoint(str(tmp_path), 3)
    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        with pytest.warns(UserWarning, match="REJECTED"):
            restored, meta = restore(str(tmp_path), with_metadata=True)
    assert int(restored["step"]) == 1
    assert meta["settled_step"] == 1
    assert meta["fallback_depth"] == 2
    assert [r["step"] for r in meta["rejected"]] == [3, 2]
    assert all("sha256 mismatch" in r["error"] for r in meta["rejected"])
    assert checkpoint.last_restore_metadata() == meta
    snap = reg.snapshot()
    assert snap["gauges"]["checkpoint/restore_fallback_step"] == 1
    assert snap["counters"]["checkpoint/restore_rejected"] == 2


def test_restore_metadata_clean_path_has_no_fallback(tmp_path):
    save(str(tmp_path), 4, _state(4.0), use_orbax=False)
    restored, meta = restore(str(tmp_path), with_metadata=True)
    assert meta == {"directory": str(tmp_path), "requested_step": None,
                    "settled_step": 4, "rejected": [],
                    "fallback_depth": 0}
    # default return shape unchanged: a bare dict, no tuple
    assert int(restore(str(tmp_path))["step"]) == 4


def test_training_state_topology_roundtrip(tmp_path):
    """The writing topology rides in the checkpoint (and manifest) so
    an elastic restore knows the shard layout it must re-partition."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    opt = DistributedFusedAdam(compress=True)
    checkpoint.save_training_state(
        str(tmp_path), 5, {"w": jnp.ones(3)}, {"m": jnp.zeros(3)},
        topology=opt.topology(8), use_orbax=False)
    state, meta = checkpoint.restore_training_state(
        str(tmp_path), with_metadata=True)
    assert state["topology"]["world"] == 8
    assert state["topology"]["optimizer"] == "DistributedFusedAdam"
    assert state["topology"]["grad_compress"] == "int8"
    assert meta["settled_step"] == 5
    manifest = verify_checkpoint(checkpoint._step_dir(str(tmp_path), 5))
    assert any(e["path"].startswith("topology/")
               for e in manifest["leaves"])


def test_restore_all_steps_corrupt_raises_with_inventory(tmp_path):
    save(str(tmp_path), 1, _state(1.0), use_orbax=False)
    faults.corrupt_checkpoint(str(tmp_path), 1)
    with pytest.warns(UserWarning, match="no older step"):
        with pytest.raises(CheckpointCorruptError,
                           match="every checkpoint"):
            restore(str(tmp_path))


def test_truncated_pickle_without_manifest_is_corrupt_not_opaque(
        tmp_path):
    """Even with verification unavailable (no manifest), a decode
    failure surfaces as CheckpointCorruptError, not a raw unpickle
    traceback."""
    path = checkpoint._step_dir(str(tmp_path), 3)
    os.makedirs(path)
    with open(os.path.join(path, "state.pkl"), "wb") as f:
        f.write(pickle.dumps(_state())[:20])
    with pytest.warns(UserWarning, match="no manifest.json"):
        with pytest.raises(CheckpointCorruptError,
                           match="failed to unpickle"):
            restore(str(tmp_path), step=3)


def test_orbax_selected_step_failure_is_corrupt_error(tmp_path):
    """Satellite: a step dir with no state.pkl hard-selects the orbax
    path; any orbax failure (or orbax being absent) must surface as
    CheckpointCorruptError feeding the fallback chain, never an opaque
    backend traceback."""
    path = checkpoint._step_dir(str(tmp_path), 5)
    os.makedirs(path)
    with open(os.path.join(path, "not_orbax_data"), "wb") as f:
        f.write(b"\x00" * 64)
    with pytest.warns(UserWarning, match="no manifest.json"):
        with pytest.raises(CheckpointCorruptError,
                           match="orbax"):
            restore(str(tmp_path), step=5)


@pytest.mark.skipif(not checkpoint._HAVE_ORBAX,
                    reason="orbax not installed")
def test_orbax_corrupted_payload_falls_back(tmp_path):
    """Corruption injector against a real orbax checkpoint: the resume
    path rejects it (manifest hash mismatch wraps whatever orbax would
    have said) and falls back to the older pickle step."""
    save(str(tmp_path), 1, _state(1.0), use_orbax=False)
    save(str(tmp_path), 2, _state(2.0), use_orbax=True)
    faults.corrupt_checkpoint(str(tmp_path), 2)
    with pytest.warns(UserWarning, match="REJECTED step 2"):
        restored = restore(str(tmp_path))
    assert int(restored["step"]) == 1


def test_pre_manifest_checkpoint_still_restores(tmp_path):
    """Backwards compatibility: a checkpoint written before the
    manifest era loads with a warning, not a rejection."""
    path = checkpoint._step_dir(str(tmp_path), 1)
    os.makedirs(path)
    with open(os.path.join(path, "state.pkl"), "wb") as f:
        pickle.dump({"w": np.ones(4)}, f)
    with pytest.warns(UserWarning, match="pre-manifest"):
        restored = restore(str(tmp_path))
    np.testing.assert_array_equal(restored["w"], 1.0)


# ---------------------------------------------------------------------------
# checkpoint durability: retries + retention
# ---------------------------------------------------------------------------

def test_transient_write_failure_retries_and_lands(tmp_path):
    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        with faults.failing_checkpoint_writes(failures=1) as stats:
            with pytest.warns(UserWarning, match="retrying"):
                save(str(tmp_path), 1, _state(), use_orbax=False,
                     retries=2, retry_base_delay=0.001)
    assert stats["fired"] == 1
    assert latest_step(str(tmp_path)) == 1
    verify_checkpoint(checkpoint._step_dir(str(tmp_path), 1))
    assert reg.snapshot()["counters"]["checkpoint/write_retries"] == 1


def test_write_failure_exhausting_retries_raises_and_lands_nothing(
        tmp_path):
    with faults.failing_checkpoint_writes(failures=3):
        with pytest.warns(UserWarning, match="retrying"):
            with pytest.raises(faults.FaultInjected):
                save(str(tmp_path), 1, _state(), use_orbax=False,
                     retries=1, retry_base_delay=0.001)
    assert latest_step(str(tmp_path)) is None  # nothing selectable


def test_keep_last_n_prunes_only_verified(tmp_path):
    for s in range(4):
        save(str(tmp_path), s, _state(float(s)), use_orbax=False,
             keep_last_n=2)
    assert checkpoint._all_steps(str(tmp_path)) == [2, 3]
    # both survivors verify
    for s in (2, 3):
        verify_checkpoint(checkpoint._step_dir(str(tmp_path), s))


def test_keep_last_n_not_applied_when_save_fails(tmp_path):
    """Retention can never eat the only good checkpoint: a failed save
    must not prune the older steps it was supposed to supersede."""
    save(str(tmp_path), 1, _state(1.0), use_orbax=False)
    with faults.failing_checkpoint_writes(failures=2):
        with pytest.raises(faults.FaultInjected):
            save(str(tmp_path), 2, _state(2.0), use_orbax=False,
                 retries=0, keep_last_n=1)
    assert checkpoint._all_steps(str(tmp_path)) == [1]
    assert int(restore(str(tmp_path))["step"]) == 1


# ---------------------------------------------------------------------------
# AsyncCheckpointer failure semantics (satellite)
# ---------------------------------------------------------------------------

def _wait_done(ck):
    """Let the background write finish WITHOUT consuming its result
    (wait_until_finished would re-raise and clear it)."""
    concurrent.futures.wait([ck._future])


def test_async_partial_write_surfaces_on_next_save(tmp_path):
    ck = AsyncCheckpointer(use_orbax=False, retries=0)
    with faults.failing_checkpoint_writes(failures=1):
        ck.save(str(tmp_path), 0, _state(0.0))
        _wait_done(ck)
    with pytest.raises(faults.FaultInjected):
        ck.save(str(tmp_path), 1, _state(1.0))
    # the failed step never became selectable
    assert latest_step(str(tmp_path)) is None
    # the failed future is consumed; a clean save works end to end
    ck.save(str(tmp_path), 2, _state(2.0))
    ck.wait_until_finished()
    assert latest_step(str(tmp_path)) == 2
    ck.close()


def test_async_partial_write_surfaces_on_close(tmp_path):
    ck = AsyncCheckpointer(use_orbax=False, retries=0)
    with faults.failing_checkpoint_writes(failures=1):
        ck.save(str(tmp_path), 0, _state(0.0))
        _wait_done(ck)
    with pytest.raises(faults.FaultInjected):
        ck.close()
    assert latest_step(str(tmp_path)) is None


def test_async_background_retry_lands(tmp_path):
    """The background write runs the same retry path as blocking save."""
    ck = AsyncCheckpointer(use_orbax=False, retries=2,
                           retry_base_delay=0.001)
    with faults.failing_checkpoint_writes(failures=1):
        with pytest.warns(UserWarning, match="retrying"):
            ck.save(str(tmp_path), 4, _state(4.0))
            ck.wait_until_finished()
    ck.close()
    assert latest_step(str(tmp_path)) == 4
    assert int(restore(str(tmp_path))["step"]) == 4


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------

def test_preemption_guard_fields_sigterm_and_restores_handlers(
        tmp_path):
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert signal.getsignal(signal.SIGTERM) != prev
        assert not guard.should_checkpoint()
        faults.simulate_preemption(signal.SIGTERM)
        assert guard.preempted
        assert guard.signum == signal.SIGTERM
        assert guard.should_checkpoint()
        # the loop saves and acknowledges
        save(str(tmp_path), 7, _state(7.0), use_orbax=False)
        guard.mark_saved()
        assert not guard.should_checkpoint()
    assert signal.getsignal(signal.SIGTERM) == prev
    assert latest_step(str(tmp_path)) == 7


def test_preemption_guard_final_save_runs_once_on_exit():
    calls = []
    with PreemptionGuard(final_save=lambda: calls.append(1)) as guard:
        guard.trigger()
    assert calls == [1]
    # not preempted -> no save; mark_saved suppresses the exit save
    calls.clear()
    with PreemptionGuard(final_save=lambda: calls.append(1)):
        pass
    assert calls == []
    with PreemptionGuard(final_save=lambda: calls.append(1)) as guard:
        guard.trigger()
        guard.mark_saved()
    assert calls == []


def test_preemption_counts_once_in_telemetry():
    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        with PreemptionGuard() as guard:
            guard.trigger()
            guard.should_checkpoint()
            guard.should_checkpoint()  # polled twice, counted once
    assert reg.snapshot()["counters"]["preemption/signals"] == 1


def test_preemption_guard_handlers_restored_on_exception():
    prev = signal.getsignal(signal.SIGTERM)
    with pytest.raises(RuntimeError):
        with PreemptionGuard():
            raise RuntimeError("boom")
    assert signal.getsignal(signal.SIGTERM) == prev


# ---------------------------------------------------------------------------
# satellite regressions: clip_grad_norm_ non-finite handling
# ---------------------------------------------------------------------------

def test_clip_grad_norm_error_if_nonfinite_raises():
    from apex_tpu.contrib.clip_grad import clip_grad_norm_

    grads = {"w": jnp.array([1.0, jnp.nan])}
    with pytest.raises(NonFiniteError, match="non-finite"):
        clip_grad_norm_(grads, 1.0, error_if_nonfinite=True)


def test_clip_grad_norm_nonfinite_falls_back_to_unclipped():
    """error_if_nonfinite=False: a NaN total norm must leave the grads
    untouched (previously every leaf was scaled by NaN)."""
    from apex_tpu.contrib.clip_grad import clip_grad_norm_

    grads = {"good": jnp.array([3.0, 4.0]),
             "bad": jnp.array([jnp.nan, 0.0])}
    out, norm = clip_grad_norm_(grads, 1.0, error_if_nonfinite=False)
    assert not np.isfinite(float(norm))
    np.testing.assert_array_equal(out["good"], grads["good"])  # unclipped
    assert np.isnan(np.asarray(out["bad"])[0])  # poison stays visible


def test_clip_grad_norm_finite_path_unchanged():
    from apex_tpu.contrib.clip_grad import clip_grad_norm_

    grads = {"w": jnp.array([3.0, 4.0])}  # norm 5
    out, norm = clip_grad_norm_(grads, 1.0)
    assert float(norm) == pytest.approx(5.0)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.array([0.6, 0.8]), rtol=1e-5)
    # under the clip threshold: untouched, error_if_nonfinite happy
    out2, norm2 = clip_grad_norm_(grads, 10.0, error_if_nonfinite=True)
    np.testing.assert_allclose(out2["w"], grads["w"], rtol=1e-6)


def test_clip_grad_norm_error_mode_rejects_jit():
    from apex_tpu.contrib.clip_grad import clip_grad_norm_

    with pytest.raises(ValueError, match="eagerly"):
        jax.jit(lambda g: clip_grad_norm_(
            g, 1.0, error_if_nonfinite=True))({"w": jnp.ones(2)})


# ---------------------------------------------------------------------------
# satellite regressions: LossScaler min_loss_scale floor
# ---------------------------------------------------------------------------

def _overflow_n(scaler, state, n):
    for _ in range(n):
        state = scaler.update(state, jnp.asarray(1.0))
    return state


def test_loss_scaler_min_scale_zero_is_honored():
    """min_loss_scale=0 means 'no floor' — the old truthiness check
    silently coerced it to 1.0."""
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler("dynamic", init_scale=4.0, scale_factor=2.0,
                        min_loss_scale=0)
    state = _overflow_n(scaler, scaler.init_state(), 4)
    assert float(state.loss_scale) == 0.25  # fell below 1.0


def test_loss_scaler_min_scale_default_floor():
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler("dynamic", init_scale=4.0, scale_factor=2.0)
    state = _overflow_n(scaler, scaler.init_state(), 6)
    assert float(state.loss_scale) == 1.0  # None -> legacy floor of 1.0


def test_loss_scaler_min_scale_positive_floor():
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler("dynamic", init_scale=16.0, scale_factor=2.0,
                        min_loss_scale=4.0)
    state = _overflow_n(scaler, scaler.init_state(), 5)
    assert float(state.loss_scale) == 4.0


# ---------------------------------------------------------------------------
# end-to-end: preemption -> final save -> verified resume
# ---------------------------------------------------------------------------

def test_preemption_to_resume_roundtrip(tmp_path):
    """The full drill: train, get preempted mid-run, land one final
    synchronous checkpoint, 'restart', resume from the verified step."""
    state = _state(0.0)
    step_holder = {"step": 0, "state": state}

    def final_save():
        save(str(tmp_path), step_holder["step"], step_holder["state"],
             use_orbax=False)

    with PreemptionGuard(final_save=final_save) as guard:
        for i in range(10):
            step_holder["step"] = i
            step_holder["state"] = _state(float(i))
            if i == 6:
                faults.simulate_preemption()
            if guard.should_checkpoint():
                break
    # the guard ran final_save on exit for the step the loop stopped at
    assert latest_step(str(tmp_path)) == 6
    restored = restore(str(tmp_path))
    assert int(restored["step"]) == 6
    verify_checkpoint(checkpoint._step_dir(str(tmp_path), 6))
