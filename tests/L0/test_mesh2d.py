"""2-D (data, model) mesh composition (ISSUE 15, ROADMAP item 4).

Evidence layers:

- **TP math**: the column/row-parallel GPT-2 block on the 2x4 mesh
  reproduces the single-device forward (the mappings region ops carry
  the psums; replicated grads stay model-invariant).
- **Axis scoping**: int8 DP compression + EF residual reduce over the
  ``data`` axis only — the overlapped step's per-axis comm bytes match
  the static collective graph EXACTLY, axis by axis, and the lint
  rules (overlap-serialization at a meaningful threshold included) run
  clean with zero skips.
- **Guard**: a poisoned 2-D step skips and reverts params AND the
  DP-scoped bucket-domain residual bit-exactly, the flag OR'd over
  BOTH axes.
- **Elastic 2-D ZeRO**: the shard table gains the model dimension —
  2x4 -> 2x2 -> 2x4 round-trips bit-identically through the canonical
  full-parameter form (monolithic AND overlap bucket layouts), the
  model-invariance of replicated leaves is verified not assumed, and a
  2x4-written state STEPS on a 2x2 mesh bit-identically to a native
  2x2 init (slow).
- **Supervisor**: tuple worlds route through mesh-shrink — a device
  loss on (2, 4) rebuilds at (2, 2) by default.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh2d

_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
for _p in (_ROOT, os.path.join(_ROOT, "tools")):
    if _p not in sys.path:
        sys.path.insert(0, _p)

HID, HEADS, VOCAB, SEQ = 32, 4, 64, 8


def _model(hidden=HID, layers=2, **kw):
    return mesh2d.gpt2_init(hidden=hidden, layers=layers, heads=HEADS,
                            vocab=VOCAB, max_seq=SEQ, **kw)


# ---------------------------------------------------------------------------
# host-side: specs, partition dims, local templates
# ---------------------------------------------------------------------------

class TestShardTable:
    def test_specs_and_dims_cover_the_layout(self):
        sp = _model()
        specs = mesh2d.gpt2_pspecs(sp)
        dims = mesh2d.gpt2_partition_dims(sp)
        attn = sp[0]["layer"]["attn"]
        s_attn = specs[0]["layer"]["attn"]
        d_attn = dims[0]["layer"]["attn"]
        assert s_attn["wq"] == P(None, "model") and d_attn["wq"] == 1
        assert s_attn["bq"] == P("model") and d_attn["bq"] == 0
        assert s_attn["wo"] == P("model") and d_attn["wo"] == 0
        assert s_attn["bo"] == P() and d_attn["bo"] is None
        assert specs[0]["embed"]["wte"] == P()
        assert dims[-1]["head"]["w"] is None
        assert attn["wq"].shape == (HID, HID)

    def test_local_template_divides_split_dims(self):
        sp = _model()
        local = mesh2d.local_template(sp, 4)
        assert local[0]["layer"]["attn"]["wq"].shape == (HID, HID // 4)
        assert local[0]["layer"]["attn"]["wo"].shape == (HID // 4, HID)
        assert local[0]["layer"]["ln1"]["g"].shape == (HID,)
        with pytest.raises(ValueError, match="does not split"):
            mesh2d.local_template(sp, 5)

    def test_mesh_validates_device_budget(self):
        with pytest.raises(ValueError, match="need"):
            mesh2d.mesh_2d(4, 4, devices=jax.devices()[:1])


# ---------------------------------------------------------------------------
# host-side: the 2-D ZeRO shard table (consolidate/reshard)
# ---------------------------------------------------------------------------

class TestZero2D:
    def _full_dict(self, rng, n, dp, tp):
        return {"format": 2, "optimizer": "DistributedFusedAdam",
                "dp_world": dp, "tp_world": tp, "n_elements": n,
                "block_size": 256, "grad_compress": "int8",
                "param_compress": "bf16", "step": np.int32(7),
                "master": rng.randn(n).astype(np.float32),
                "exp_avg": rng.randn(n).astype(np.float32),
                "exp_avg_sq": np.abs(rng.randn(n)).astype(np.float32),
                "grad_residual": (rng.randn(n) * 1e-3)
                .astype(np.float32)}

    def test_roundtrip_2x4_2x2_2x4_bit_identical(self):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _flat_size,
        )

        sp = _model()
        pdims = mesh2d.gpt2_partition_dims(sp)
        rng = np.random.RandomState(0)
        full0 = self._full_dict(rng, _flat_size(sp), 2, 4)
        for overlap in (False, True):
            opt = DistributedFusedAdam(compress=True, overlap=overlap)
            full = full0
            for world in ((2, 2), (2, 4)):
                st = opt.load_state_dict_resharded(
                    full, sp, world=world, partition_dims=pdims)
                assert len(st) == world[1]
                if overlap:
                    assert "buckets" in st[0]
                full = opt.state_dict_full(st, sp, world=world,
                                           partition_dims=pdims)
            for k in ("master", "exp_avg", "exp_avg_sq",
                      "grad_residual"):
                np.testing.assert_array_equal(full[k], full0[k]), \
                    (overlap, k)
            assert int(full["step"]) == 7

    def test_residual_consolidates_by_dp_sum_per_model_rank(self):
        """Each model column's residual is the sum over ITS dp ranks;
        on reshard, each new model column's dp-rank-0 carries the
        merged total."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _flat_size,
        )

        sp = _model()
        pdims = mesh2d.gpt2_partition_dims(sp)
        rng = np.random.RandomState(1)
        opt = DistributedFusedAdam(compress=True)
        full0 = self._full_dict(rng, _flat_size(sp), 2, 4)
        sts = opt.load_state_dict_resharded(full0, sp, world=(2, 4),
                                            partition_dims=pdims)
        for st in sts:
            res = np.asarray(st["grad_residual"])
            assert res.shape[0] == 2          # per-dp-rank stack
            assert np.abs(res[1]).max() == 0  # rank 0 carries the sum
        back = opt.state_dict_full(sts, sp, world=(2, 4),
                                   partition_dims=pdims)
        np.testing.assert_array_equal(back["grad_residual"],
                                      full0["grad_residual"])

    def test_replicated_leaf_divergence_refuses(self):
        """Model-invariance of replicated state is VERIFIED: a model
        rank whose replicated leaf diverged must fail consolidation,
        not silently average."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _flat_size,
        )

        sp = _model()
        pdims = mesh2d.gpt2_partition_dims(sp)
        rng = np.random.RandomState(2)
        opt = DistributedFusedAdam(compress=True)
        full0 = self._full_dict(rng, _flat_size(sp), 2, 4)
        sts = opt.load_state_dict_resharded(full0, sp, world=(2, 4),
                                            partition_dims=pdims)
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            split_params_for_model_axis,
        )

        # poison the LAST logical element (the replicated head's tail;
        # the zero pad tail beyond n_t is not consolidated)
        n_t = sum(l.size for l in jax.tree_util.tree_leaves(
            split_params_for_model_axis(sp, pdims, 4)[2]))
        bad = dict(sts[2])
        m = np.asarray(bad["master_shard"]).copy()
        m[n_t - 1] += 1.0
        bad["master_shard"] = m
        with pytest.raises(ValueError, match="replicated leaf"):
            opt.state_dict_full([sts[0], sts[1], bad, sts[3]], sp,
                                world=(2, 4), partition_dims=pdims)

    def test_2d_world_requires_partition_dims(self):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        opt = DistributedFusedAdam()
        with pytest.raises(ValueError, match="partition_dims"):
            opt.state_dict_full([], _model(), world=(2, 4))
        assert opt.topology((2, 4))["world"] == [2, 4]


# ---------------------------------------------------------------------------
# host-side: supervisor 2-D worlds
# ---------------------------------------------------------------------------

class TestSupervisor2D:
    def test_half_world_prefers_the_model_axis(self):
        from apex_tpu.resilience.supervisor import _half_world

        assert _half_world((2, 4)) == (2, 2)
        assert _half_world((2, 1)) == (1, 1)
        assert _half_world((1, 1)) == (1, 1)
        assert _half_world(8) == 4

    def test_mesh_shrink_on_tuple_world(self):
        from apex_tpu.resilience.faults import DeviceLostError
        from apex_tpu.resilience.supervisor import Supervisor

        def make_step(world):
            def step(state, i):
                if world == (2, 4) and i == 3:
                    raise DeviceLostError("chip 5 fell over")
                return {"x": state["x"] + 1}
            return step

        rebuilds = []

        def rebuild(world, host_state, step):
            rebuilds.append((world, step))
            return make_step(world), host_state

        sup = Supervisor(make_step((2, 4)), {"x": np.zeros(())},
                         rebuild=rebuild, world=(2, 4),
                         topology={"world": [2, 4]}, snapshot_every=2,
                         sleep=lambda s: None)
        rep = sup.run(6)
        assert rep["exit"] == "completed"
        assert sup.world == (2, 2)
        assert rebuilds == [((2, 2), 2)]
        assert sup.topology["world"] == [2, 2]


# ---------------------------------------------------------------------------
# host-side: per-axis comm telemetry + tools contracts
# ---------------------------------------------------------------------------

class TestPerAxisAccounting:
    def test_axis_label(self):
        from apex_tpu.telemetry.comm import axis_label

        assert axis_label("data") == "data"
        assert axis_label(("data", "model")) == "data,model"
        assert axis_label(None) is None
        assert axis_label(()) is None

    def test_record_collective_rolls_up_per_axis(self):
        from apex_tpu.telemetry import comm
        from apex_tpu.telemetry.registry import (MetricsRegistry,
                                                 use_registry)

        reg = MetricsRegistry(enabled=True)
        reg.enable()
        with use_registry(reg):
            comm.record_collective("psum", elements=1000,
                                   dtype=jnp.float32,
                                   axis_name="model", world=4)
            comm.record_collective("psum", elements=1000,
                                   dtype=jnp.int8, axis_name="data",
                                   world=2, mode="int8")
        model = reg.counter_value("comm/axis/model_bytes")
        data = reg.counter_value("comm/axis/data_bytes")
        assert model == 2.0 * 3 / 4 * 4000
        assert data == 2.0 * 1 / 2 * 1000
        assert reg.counter_value("comm/bytes") == model + data

    def test_report_renders_per_axis_table(self, capsys):
        import telemetry_report as tr

        events = [("f", {"kind": "collective", "name": "psum",
                         "dtype": "float32", "axis": "model",
                         "wire_bytes": 4096, "elements": 1024}),
                  ("f", {"kind": "collective", "name": "psum",
                         "dtype": "int8", "axis": "data",
                         "wire_bytes": 512, "elements": 512})]
        report = tr.aggregate(iter(events))
        assert report["collectives_by_axis"]["model"]["wire_bytes"] \
            == 4096
        assert report["collectives_by_axis"]["data"]["calls"] == 1
        tr.print_report(report)
        out = capsys.readouterr().out
        assert "per mesh axis" in out
        assert "axis data" in out and "axis model" in out

    def test_schema_gates_tp_dp_fields_at_round_20(self):
        import bench_schema_check as schema

        line = {"metric": "tp_dp_steps_per_sec", "value": 1.0,
                "unit": "steps/sec", "vs_baseline": 1.0,
                "tflops_per_sec": 0.1, "mfu": 0.01,
                "comm_bytes_per_step": 100,
                "measured_comm_bytes_per_step": 100,
                "model_flops_per_step_xla": 1.0,
                "peak_hbm_bytes": 1, "hbm_headroom_pct": 50.0,
                "compile_count": 1, "lint_violations": 0,
                "backend": "cpu-mesh",
                "static_comm_bytes_per_step": 100,
                "baseline_step_ms": 2.0, "overlapped_step_ms": 1.5,
                "measured_comm_bytes_per_axis": {"data": 60,
                                                 "model": 40},
                "static_comm_bytes_per_axis": {"data": 60,
                                               "model": 40},
                "reshard_bitexact": True}
        assert schema.check_metric_line(dict(line), round_n=20,
                                        errors=[]) == []
        # pre-round-20 records must not carry the per-axis dicts
        errs = schema.check_metric_line(dict(line), round_n=19,
                                        errors=[])
        assert any("only defined from round 20" in e for e in errs)
        # a round-20 tp_dp line missing the contract is flagged
        short = {k: v for k, v in line.items()
                 if k != "reshard_bitexact"}
        errs = schema.check_metric_line(short, round_n=20, errors=[])
        assert any("reshard_bitexact" in e for e in errs)
        bad = dict(line, measured_comm_bytes_per_axis=[1, 2])
        errs = schema.check_metric_line(bad, round_n=20, errors=[])
        assert any("axis-name" in e for e in errs)

    def test_trend_band_names_tp_dp(self):
        import bench_trend

        assert bench_trend.band_for("tp_dp_steps_per_sec") == 0.25


# ---------------------------------------------------------------------------
# on-mesh: forward parity, axis scoping, guard, overlap, ZeRO
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestMesh2D:
    def _setup(self, mode, layers=2, **kw):
        mesh = mesh2d.mesh_2d(2)
        sp = _model(layers=layers)
        step, state = mesh2d.build_train_step(
            mesh, sp, hidden=HID, heads=HEADS, mode=mode, **kw)
        tokens, labels = mesh2d.make_batch(mesh, batch_per_replica=2,
                                           seq=SEQ, vocab=VOCAB)
        return mesh, sp, step, state, tokens, labels

    def test_forward_matches_single_device_oracle(self):
        """Device (0, 0)'s loss == the un-meshed model on its local
        rows: the column/row split + TP psum reproduce the dense
        math."""
        mesh, sp, step, state, tokens, labels = self._setup("baseline")
        out = step(*state, tokens, labels)
        oracle = mesh2d.gpt2_loss(list(sp), tokens[:2], labels[:2],
                                  HID // HEADS)
        np.testing.assert_allclose(float(out[2]), float(oracle),
                                   rtol=2e-5)

    def test_overlapped_tracks_baseline(self):
        """Same mesh, same int8-over-data payload: the overlapped
        step's FIRST loss is bit-identical (identical forward) and the
        params stay within the per-block quantization bound of the
        baseline over 3 steps (ragged buckets shift the block grid —
        the 1-D suite pins the aligned case bit-exactly)."""
        mesh, sp, base, bstate, tokens, labels = self._setup("baseline")
        _, _, ovl, ostate, _, _ = self._setup("overlapped",
                                              fold_average=False)
        b, o = bstate, ostate
        for i in range(3):
            b = base(*b[:2], tokens, labels)
            o = ovl(*o[:2], tokens, labels)
            if i == 0:
                assert float(b[2]) == float(o[2])
        for pb, po in zip(jax.tree_util.tree_leaves(b[0]),
                          jax.tree_util.tree_leaves(o[0])):
            np.testing.assert_allclose(np.asarray(pb), np.asarray(po),
                                       atol=5e-4, rtol=1e-4)

    def test_guard_skip_reverts_bit_exact_on_2d_mesh(self):
        """Acceptance: guard skip-revert bit-exact under the 2-D mesh —
        params AND the DP-scoped bucket-domain residual — with the
        non-finite flag OR'd over BOTH axes."""
        mesh, sp, step, state, tokens, labels = self._setup(
            "guarded", guard_nan_step=1)
        out = step(*state, jnp.zeros((), jnp.int32), tokens, labels)
        assert int(out[2].total_skips) == 0
        before = jax.tree_util.tree_map(np.asarray, (out[0], out[1]))
        out = step(out[0], out[1], out[2], jnp.ones((), jnp.int32),
                   tokens, labels)
        assert int(out[2].total_skips) == 1
        for b_leaf, a_leaf in zip(
                jax.tree_util.tree_leaves(before),
                jax.tree_util.tree_leaves((out[0], out[1]))):
            assert np.array_equal(b_leaf, np.asarray(a_leaf))
        # a clean step after the skip moves again
        out = step(out[0], out[1], out[2],
                   2 * jnp.ones((), jnp.int32), tokens, labels)
        assert int(out[2].consecutive_skips) == 0
        assert not np.array_equal(
            np.asarray(jax.tree_util.tree_leaves(out[0])[0]),
            jax.tree_util.tree_leaves(before)[0])

    def test_zero_overlap_composes_on_2d_mesh(self):
        """overlapped_zero_step (per-bucket DP reduce-scatter -> shard
        update -> gather, scoped to 'data') drives the 2-D GPT block:
        the loss decreases and the step counter advances."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.parallel.overlap import overlapped_zero_step

        mesh = mesh2d.mesh_2d(2)
        sp = _model()
        tokens, labels = mesh2d.make_batch(mesh, batch_per_replica=2,
                                           seq=SEQ, vocab=VOCAB)
        opt = DistributedFusedAdam(lr=1e-2, axis_name="data",
                                   compress=True, overlap=True)
        head_dim = HID // HEADS
        pspecs = mesh2d.gpt2_pspecs(sp)

        def drv(sp_, state, tokens_, labels_):
            segs = mesh2d.gpt2_segments(labels_, len(sp_), head_dim)
            loss, sp_, state = overlapped_zero_step(
                segs, list(sp_), opt, state, tokens_)
            return tuple(sp_), state, loss

        step = jax.jit(jax.shard_map(
            drv, mesh=mesh,
            in_specs=(pspecs, P(), P("data"), P("data")),
            out_specs=(pspecs, P(), P()), check_vma=False))
        with mesh:
            state = jax.jit(lambda p: jax.shard_map(
                lambda q: opt.init(list(q)), mesh=mesh,
                in_specs=(pspecs,), out_specs=P(),
                check_vma=False)(p))(sp)
        losses = []
        cur = sp
        for _ in range(3):
            cur, state, loss = step(cur, state, tokens, labels)
            losses.append(float(loss))
        assert int(np.asarray(state["step"])) == 3
        assert losses[-1] < losses[0]

    def test_per_axis_static_matches_measured_exactly(self):
        """The tp_dp_overlapped target: trace-measured per-axis comm
        counters == the collective graph's static ring bytes, axis by
        axis (data carries the compressed grads, model the fp32
        activation psums)."""
        from apex_tpu.analysis import sharding
        from apex_tpu.analysis.targets import TARGETS
        from apex_tpu.telemetry.registry import (MetricsRegistry,
                                                 use_registry)

        fn, args, _ = TARGETS["tp_dp_overlapped"]()
        reg = MetricsRegistry(enabled=True)
        reg.enable()
        with use_registry(reg):
            lowered = fn.lower(*args)
        measured = {a: reg.counter_value(f"comm/axis/{a}_bytes")
                    for a in ("data", "model")}
        traced = fn.trace(*args)
        static = sharding.static_comm_bytes_by_axis(
            lowered.as_text(), traced.jaxpr)
        assert measured["data"] > 0 and measured["model"] > 0
        assert static["data"] == int(round(measured["data"]))
        assert static["model"] == int(round(measured["model"]))
        assert "?" not in static  # every op got an axis label

    def test_overlap_serialization_meaningfully_clean_on_2d(self):
        """The proof obligation: with the threshold BELOW the per-
        bucket DP payload (but above the TP activation psums), no DP
        bucket chains behind another large reduction — the rule is
        checked in the regime where it can actually fire."""
        from apex_tpu.analysis import LintConfig, assert_clean_hlo
        from apex_tpu.analysis.targets import (TARGETS,
                                               tp_dp_overlap_min_bytes)

        fn, args, _ = TARGETS["tp_dp_overlapped"]()
        report = assert_clean_hlo(
            fn, *args, rules="overlap-serialization",
            config=LintConfig(
                overlap_min_bytes=tp_dp_overlap_min_bytes()))
        assert report.rules_run == ("overlap-serialization",)

    def test_e2e_no_recompiles(self):
        from apex_tpu.analysis.targets import tp_dp_overlapped_step
        from apex_tpu.telemetry.compile_watch import assert_no_recompiles

        fn, args, _ = tp_dp_overlapped_step()
        out = fn(*args)
        out = fn(out[0], out[1], *args[2:])
        with assert_no_recompiles():
            for _ in range(2):
                out = fn(out[0], out[1], *args[2:])
        float(out[2])


# ---------------------------------------------------------------------------
# slow: the on-mesh elastic step equivalence + the live bench contract
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
@pytest.mark.slow
class TestElastic2DE2E:
    def test_resharded_2x4_state_steps_on_2x2_bit_identical(self):
        """The supervisor's elastic story on REAL meshes: a 2x4-written
        ZeRO master table resharded to 2x2 steps bit-identically to a
        native 2x2 init (fp32 sync — exact psum), proving the 2-D
        reshard changed nothing but the partition."""
        import functools

        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _padded_size,
        )

        sp = _model()
        pdims = mesh2d.gpt2_partition_dims(sp)
        opt = DistributedFusedAdam(lr=1e-2, axis_name="data")
        head_dim = HID // HEADS
        pspecs = mesh2d.gpt2_pspecs(sp)

        def one_step(tp, masters_host):
            """masters_host: [tp, padded_t] per-model-rank masters."""
            mesh = mesh2d.mesh_2d(2, tp)
            tokens, labels = mesh2d.make_batch(
                mesh, batch_per_replica=2, seq=SEQ, vocab=VOCAB)

            @functools.partial(
                jax.shard_map, mesh=mesh,
                in_specs=(pspecs, P("model", "data"), P("data"),
                          P("data")),
                out_specs=P("model", "data"), check_vma=False)
            def go(params, master_local, tokens_, labels_):
                loss, grads = jax.value_and_grad(
                    lambda q: mesh2d.gpt2_loss(q, tokens_, labels_,
                                               head_dim))(tuple(params))
                state = dict(opt.init(list(params)),
                             master_shard=master_local.reshape(-1))
                _, new_state = opt.step(list(grads), state,
                                        list(params))
                return new_state["master_shard"][None, :]

            return np.asarray(jax.jit(go)(
                sp, jnp.asarray(masters_host), tokens, labels))

        def masters_for(tp):
            from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: E501
                split_params_for_model_axis,
            )

            per_rank = split_params_for_model_axis(sp, pdims, tp)
            rows = []
            for lp in per_rank:
                flat = np.concatenate(
                    [np.asarray(l).reshape(-1)
                     for l in jax.tree_util.tree_leaves(lp)])
                padded = _padded_size(flat.size, 2, None, None, 256)
                rows.append(np.pad(flat, (0, padded - flat.size)))
            return np.stack(rows)

        out4 = one_step(4, masters_for(4))

        # write at 2x4 (zero moments: the fresh-run shape), reshard to
        # 2x2 through the canonical form, step on the 2x2 mesh
        st4 = []
        m4 = masters_for(4)
        for t in range(4):
            st4.append({"step": jnp.zeros((), jnp.int32),
                        "master_shard": m4[t],
                        "exp_avg_shard": np.zeros_like(m4[t]),
                        "exp_avg_sq_shard": np.zeros_like(m4[t])})
        full = opt.state_dict_full(st4, sp, world=(2, 4),
                                   partition_dims=pdims)
        st2 = opt.load_state_dict_resharded(full, sp, world=(2, 2),
                                            partition_dims=pdims)
        resharded = np.stack([np.asarray(s["master_shard"])
                              for s in st2])
        out2 = one_step(2, resharded)
        native2 = one_step(2, masters_for(2))
        # the production claim: the re-shard changed NOTHING but the
        # partition — the resharded masters step bit-identically to a
        # native 2x2 init. (No cross-topology float comparison: Adam's
        # first step is sign-like, so the tp=4 psum association makes
        # near-zero grads flip update signs — out4 only proves the
        # 2x4 step runs.)
        np.testing.assert_array_equal(out2, native2)
        assert np.isfinite(out4).all()

    def test_tp_dp_bench_contract(self, capsys):
        """The live round-20 contract: bench_tp_dp at tiny size emits a
        schema-valid line with one compile, clean lint, per-axis
        agreement, and reshard_bitexact."""
        import json as _json
        import os as _os
        import sys as _sys

        root = _os.path.dirname(_os.path.dirname(
            _os.path.dirname(_os.path.abspath(__file__))))
        for p in (root, _os.path.join(root, "tools")):
            if p not in _sys.path:
                _sys.path.insert(0, p)
        import bench
        import bench_schema_check as schema

        ret = bench.bench_tp_dp(2, 1, hidden=64, layers=2, heads=4,
                                vocab=64, seq=16)
        line = _json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert schema.check_metric_line(line, round_n=20,
                                        errors=[]) == []
        assert line["compile_count"] == 1
        assert line["lint_violations"] == 0
        assert line["reshard_bitexact"] is True
        assert line["backend"] == "cpu-mesh"
        assert line["measured_comm_bytes_per_axis"]["data"] > 0
        assert line["measured_comm_bytes_per_axis"]["model"] > 0
        assert line["static_comm_bytes_per_axis"] == \
            line["measured_comm_bytes_per_axis"]
        assert ret["baseline_step_ms"] > 0
