"""ZeRO-sharded optimizers vs their unsharded counterparts.

Mirrors reference apex/contrib/test/optimizers/test_dist_adam.py (470 LoC:
DistributedFusedAdam vs plain Adam step-by-step).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.testing import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import DistributedFusedAdam, DistributedFusedLAMB
from apex_tpu.optimizers import FusedAdam, FusedLAMB


def make_params(rng):
    return {"w": jnp.asarray(rng.randn(4, 6).astype(np.float32)),
            "b": jnp.asarray(rng.randn(5).astype(np.float32))}


class TestDistributedFusedAdam:
    @pytest.mark.multi_device
    def test_matches_fused_adam(self, rng, dp_mesh):
        """Sharded Adam over 4 dp ranks == plain Adam on averaged grads
        (the reference test's oracle)."""
        mesh = dp_mesh(4)
        params = make_params(rng)
        per_rank_grads = [
            jax.tree_util.tree_map(
                lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
                params)
            for _ in range(4)
        ]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_rank_grads)

        dopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                           out_specs=P())
        def run(params, grads_stacked):
            grads = jax.tree_util.tree_map(lambda a: a[0], grads_stacked)
            state = dopt.init(params)
            p, state = dopt.step(grads, state, params)
            p, state = dopt.step(grads, state, p)
            return p

        out = run(params, stacked)

        ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        avg_grads = jax.tree_util.tree_map(lambda a: a.mean(0), stacked)
        rp = params
        rs = ref_opt.init(params)
        rp, rs = ref_opt.step(avg_grads, rs, rp)
        rp, rs = ref_opt.step(avg_grads, rs, rp)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(rp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_single_device_path(self, rng):
        params = make_params(rng)
        opt = DistributedFusedAdam(lr=1e-2)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        p, s = opt.step(grads, state, params)
        ref = FusedAdam(lr=1e-2)
        rp, _ = ref.step(grads, ref.init(params), params)
        for a, b in zip(jax.tree_util.tree_leaves(p),
                        jax.tree_util.tree_leaves(rp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_overflow_skip(self, rng):
        params = make_params(rng)
        opt = DistributedFusedAdam(lr=1e-2)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        p, s = opt.step(grads, state, params,
                        found_inf=jnp.ones((), jnp.float32))
        for a, b in zip(jax.tree_util.tree_leaves(p),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7)
        assert int(s["step"]) == 0


@pytest.mark.multi_device
class TestCompressedZeRO:
    """Block-quantized grad reduce-scatter / param all-gather inside the
    ZeRO optimizers (ISSUE 1: parallel/compression.py wiring)."""

    def _stacked_grads(self, rng, params, world):
        per_rank = [
            jax.tree_util.tree_map(
                lambda p: jnp.asarray(
                    rng.randn(*p.shape).astype(np.float32)), params)
            for _ in range(world)
        ]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_rank)

    @pytest.mark.slow
    def test_int8_grads_track_uncompressed(self, rng, dp_mesh):
        """int8 grad sync + error feedback stays close to the exact
        reduce-scatter over a few steps (per-step quantization error is
        bounded by the shared block scale; EF stops it accumulating)."""
        mesh = dp_mesh(4)
        params = make_params(rng)
        stacked = self._stacked_grads(rng, params, 4)

        def run(opt):
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=(P(), P("dp")), out_specs=P())
            def go(params, grads_stacked):
                grads = jax.tree_util.tree_map(lambda a: a[0],
                                               grads_stacked)
                state = opt.init(params)
                p = params
                for _ in range(3):
                    p, state = opt.step(grads, state, p)
                return p
            return go(params, stacked)

        exact = run(DistributedFusedAdam(lr=1e-2, weight_decay=0.01))
        quant = run(DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                         grad_compress="int8"))
        for a, b in zip(jax.tree_util.tree_leaves(exact),
                        jax.tree_util.tree_leaves(quant)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-2)

    def test_residual_in_state_and_updates(self, rng, dp_mesh):
        mesh = dp_mesh(4)
        params = make_params(rng)
        stacked = self._stacked_grads(rng, params, 4)
        opt = DistributedFusedAdam(lr=1e-2, compress=True)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                           out_specs=P())
        def go(params, grads_stacked):
            grads = jax.tree_util.tree_map(lambda a: a[0], grads_stacked)
            state = opt.init(params)
            _, state = opt.step(grads, state, params)
            return state["grad_residual"][None]

        res = np.asarray(go(params, stacked))
        assert res.dtype == np.float32
        assert np.abs(res).max() > 0  # quantization error was captured

    def test_bf16_param_gather(self, rng, dp_mesh):
        """bf16 param all-gather: params come back bf16-rounded but the
        fp32 master shard keeps full precision (gathered params stay
        within one bf16 ulp of the exact ones)."""
        mesh = dp_mesh(4)
        params = make_params(rng)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
            params)

        def run(opt):
            @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                               out_specs=P())
            def go(params, grads):
                state = opt.init(params)
                p, _ = opt.step(grads, state, params)
                return p
            return go(params, grads)

        exact = run(DistributedFusedAdam(lr=1e-2))
        cast = run(DistributedFusedAdam(lr=1e-2, param_compress="bf16"))
        for a, b in zip(jax.tree_util.tree_leaves(exact),
                        jax.tree_util.tree_leaves(cast)):
            a = np.asarray(a)
            np.testing.assert_allclose(a, np.asarray(b),
                                       atol=np.abs(a).max() * 2 ** -8)

    @pytest.mark.slow  # tier-1 budget: the Adam variant stays tier-1
    def test_lamb_compressed_close(self, rng, dp_mesh):
        mesh = dp_mesh(4)
        params = make_params(rng)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
            params)

        def run(opt):
            @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                               out_specs=P())
            def go(params, grads):
                state = opt.init(params)
                g4 = jax.tree_util.tree_map(lambda g: g / 4.0, grads)
                p, _ = opt.step(g4, state, params)
                return p
            return go(params, grads)

        exact = run(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01))
        quant = run(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                         compress=True))
        for a, b in zip(jax.tree_util.tree_leaves(exact),
                        jax.tree_util.tree_leaves(quant)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-2)


class TestElasticReshard:
    """state_dict_full / load_state_dict_resharded (ISSUE 8): ZeRO
    shards written at one world size re-partition onto another —
    host-side math, bit-exact, int8 block alignment included."""

    def _ragged_params(self, rng):
        # n = 37*13 + 7 = 488: not a multiple of the 256-lane block nor
        # of any world size — every padding path exercises its tail
        return {"w": jnp.asarray(rng.randn(37, 13).astype(np.float32)),
                "b": jnp.asarray(rng.randn(7).astype(np.float32))}

    def _synthetic_state(self, rng, opt, params, world):
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _flat_size,
            _padded_size,
        )

        n = _flat_size(params)
        padded = _padded_size(n, world, opt.grad_compress,
                              opt.param_compress,
                              opt.compress_block_size)

        def vec():
            return np.pad(rng.randn(n).astype(np.float32),
                          (0, padded - n))

        state = {"step": jnp.asarray(7, jnp.int32),
                 "master_shard": jnp.asarray(vec()),
                 "exp_avg_shard": jnp.asarray(vec()),
                 "exp_avg_sq_shard": jnp.asarray(np.abs(vec()))}
        if opt.grad_compress == "int8":
            state["grad_residual"] = jnp.asarray(
                rng.randn(world, padded).astype(np.float32) * 1e-3
                * (np.arange(padded) < n))  # residual pad tail is zero
        return state, n, padded

    def test_roundtrip_8_4_1_8_bit_identical(self, rng):
        """The acceptance round-trip: consolidate at 8, reshard to 4,
        to 1, back to 8 — fp32 masters/moments and the (summed) EF
        residual bit-identical, ragged tail included."""
        params = self._ragged_params(rng)
        opt = DistributedFusedAdam(compress=True)
        st8, n, _ = self._synthetic_state(rng, opt, params, 8)
        full8 = opt.state_dict_full(st8, params, world=8)
        assert full8["master"].shape == (n,)
        st = st8
        full = full8
        for world in (4, 1, 8):
            st = opt.load_state_dict_resharded(full, params, world=world)
            full = opt.state_dict_full(st, params, world=world)
        for k in ("master", "exp_avg", "exp_avg_sq", "grad_residual"):
            np.testing.assert_array_equal(np.asarray(full8[k]),
                                          np.asarray(full[k]))
        assert int(full["step"]) == 7
        # the resharded padding is recomputed per world: block-aligned
        assert st["master_shard"].shape[0] % (8 * 256) == 0

    def test_residual_sum_is_the_invariant(self, rng):
        """Per-rank residuals consolidate to their SUM (the pending
        global correction) and reshard to total/world per rank —
        power-of-two division keeps the sum exact."""
        params = self._ragged_params(rng)
        opt = DistributedFusedAdam(compress=True)
        st8, n, padded = self._synthetic_state(rng, opt, params, 8)
        full = opt.state_dict_full(st8, params, world=8)
        np.testing.assert_array_equal(
            np.asarray(full["grad_residual"]),
            np.asarray(st8["grad_residual"]).sum(axis=0)[:n])
        st4 = opt.load_state_dict_resharded(full, params, world=4)
        assert st4["grad_residual"].shape[0] == 4
        np.testing.assert_array_equal(
            np.asarray(st4["grad_residual"]).sum(axis=0)[:n],
            np.asarray(full["grad_residual"]))

    def test_accepts_stacked_shards_and_rejects_bad_layout(self, rng):
        params = self._ragged_params(rng)
        opt = DistributedFusedAdam(compress=True)
        st8, _, padded = self._synthetic_state(rng, opt, params, 8)
        stacked = dict(st8, master_shard=np.asarray(
            st8["master_shard"]).reshape(8, -1))
        a = opt.state_dict_full(st8, params, world=8)
        b = opt.state_dict_full(stacked, params, world=8)
        np.testing.assert_array_equal(a["master"], b["master"])
        with pytest.raises(ValueError, match="wrong world"):
            opt.state_dict_full(st8, params, world=4)
        with pytest.raises(ValueError, match="stacked"):
            opt.state_dict_full(
                dict(st8, grad_residual=np.zeros((4, padded),
                                                 np.float32)),
                params, world=8)

    def test_rejects_wrong_model(self, rng):
        params = self._ragged_params(rng)
        opt = DistributedFusedAdam(compress=True)
        st8, _, _ = self._synthetic_state(rng, opt, params, 8)
        full = opt.state_dict_full(st8, params, world=8)
        other = {"w": jnp.zeros((5, 5), jnp.float32)}
        with pytest.raises(ValueError, match="wrong model"):
            opt.load_state_dict_resharded(full, other, world=4)

    def test_residual_dropped_with_warning_without_int8(self, rng):
        params = self._ragged_params(rng)
        writer = DistributedFusedAdam(compress=True)
        st8, _, _ = self._synthetic_state(rng, writer, params, 8)
        full = writer.state_dict_full(st8, params, world=8)
        plain = DistributedFusedAdam()  # no compression
        with pytest.warns(UserWarning, match="dropping the residual"):
            st = plain.load_state_dict_resharded(full, params, world=4)
        assert "grad_residual" not in st

    def test_lamb_shares_the_layout(self, rng):
        params = self._ragged_params(rng)
        opt = DistributedFusedLAMB(compress=True)
        st8, n, _ = self._synthetic_state(rng, opt, params, 8)
        full = opt.state_dict_full(st8, params, world=8)
        assert full["optimizer"] == "DistributedFusedLAMB"
        st1 = opt.load_state_dict_resharded(full, params, world=1)
        full1 = opt.state_dict_full(st1, params, world=1)
        np.testing.assert_array_equal(full["master"], full1["master"])
        topo = opt.topology(8)
        assert topo["world"] == 8 and topo["grad_compress"] == "int8"

    @pytest.mark.multi_device
    @pytest.mark.slow  # tier-1 budget (round 23): roundtrip_8_4_1_8 + residual invariant cover resharding
    def test_resharded_state_steps_on_smaller_mesh(self, rng, dp_mesh):
        """Integration: a world=4 state resharded to world=2 actually
        STEPS on a 2-way mesh — bit-identically to a native world=2
        init (the re-shard changed nothing but the partition), and
        ulp-close to the 4-way step (bitwise parity across different
        world sizes is impossible: the psum association differs)."""
        mesh4, mesh2 = dp_mesh(4), dp_mesh(2)
        params = make_params(rng)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
            params)
        opt = DistributedFusedAdam(lr=1e-2)  # fp32 sync: exact psum

        def one_step(mesh, world, init_state_host):
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=(P(), P(), P("dp")),
                               out_specs=P("dp"))
            def go(params, grads, master_local):
                # P("dp") already hands each rank its slice of the
                # host-global flat — exactly init's layout
                state = dict(opt.init(params), master_shard=master_local)
                g = jax.tree_util.tree_map(lambda x: x / world, grads)
                _, new_state = opt.step(g, state, params)
                return new_state["master_shard"]
            return np.asarray(go(params, grads,
                                 jnp.asarray(init_state_host)))

        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _flat_size,
            _flatten_f32,
            _padded_size,
        )

        n = _flat_size(params)
        pad4 = _padded_size(n, 4, None, None, opt.compress_block_size)
        pad2 = _padded_size(n, 2, None, None, opt.compress_block_size)
        flat = np.asarray(_flatten_f32(params))
        master4 = np.pad(flat, (0, pad4 - n))
        out4 = one_step(mesh4, 4, master4).reshape(-1)[:n]

        full = opt.state_dict_full(
            {"step": jnp.zeros((), jnp.int32),
             "master_shard": master4,
             "exp_avg_shard": np.zeros_like(master4),
             "exp_avg_sq_shard": np.zeros_like(master4)},
            params, world=4)
        st2 = opt.load_state_dict_resharded(full, params, world=2)
        out2 = one_step(mesh2, 2,
                        np.asarray(st2["master_shard"])).reshape(-1)[:n]
        native2 = one_step(mesh2, 2,
                           np.pad(flat, (0, pad2 - n))).reshape(-1)[:n]
        np.testing.assert_array_equal(out2, native2)  # bit-identical
        np.testing.assert_allclose(out2, out4, rtol=1e-5, atol=1e-6)


class TestDistributedFusedLAMB:
    @pytest.mark.multi_device
    def test_matches_fused_lamb(self, rng, dp_mesh):
        mesh = dp_mesh(4)
        params = make_params(rng)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
            params)

        dopt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                    grad_averaging=False)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                           out_specs=P())
        def run(params, grads):
            state = dopt.init(params)
            # identical grads on every rank; reduce-scatter sums -> x4
            grads4 = jax.tree_util.tree_map(lambda g: g / 4.0, grads)
            p, _ = dopt.step(grads4, state, params)
            return p

        out = run(params, grads)

        ref_opt = FusedLAMB(lr=1e-2, weight_decay=0.01, grad_averaging=False)
        rp, _ = ref_opt.step(grads, ref_opt.init(params), params)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(rp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
