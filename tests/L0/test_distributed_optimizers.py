"""ZeRO-sharded optimizers vs their unsharded counterparts.

Mirrors reference apex/contrib/test/optimizers/test_dist_adam.py (470 LoC:
DistributedFusedAdam vs plain Adam step-by-step).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.testing import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import DistributedFusedAdam, DistributedFusedLAMB
from apex_tpu.optimizers import FusedAdam, FusedLAMB


def make_params(rng):
    return {"w": jnp.asarray(rng.randn(4, 6).astype(np.float32)),
            "b": jnp.asarray(rng.randn(5).astype(np.float32))}


class TestDistributedFusedAdam:
    @pytest.mark.multi_device
    def test_matches_fused_adam(self, rng, dp_mesh):
        """Sharded Adam over 4 dp ranks == plain Adam on averaged grads
        (the reference test's oracle)."""
        mesh = dp_mesh(4)
        params = make_params(rng)
        per_rank_grads = [
            jax.tree_util.tree_map(
                lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
                params)
            for _ in range(4)
        ]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_rank_grads)

        dopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                           out_specs=P())
        def run(params, grads_stacked):
            grads = jax.tree_util.tree_map(lambda a: a[0], grads_stacked)
            state = dopt.init(params)
            p, state = dopt.step(grads, state, params)
            p, state = dopt.step(grads, state, p)
            return p

        out = run(params, stacked)

        ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        avg_grads = jax.tree_util.tree_map(lambda a: a.mean(0), stacked)
        rp = params
        rs = ref_opt.init(params)
        rp, rs = ref_opt.step(avg_grads, rs, rp)
        rp, rs = ref_opt.step(avg_grads, rs, rp)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(rp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_single_device_path(self, rng):
        params = make_params(rng)
        opt = DistributedFusedAdam(lr=1e-2)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        p, s = opt.step(grads, state, params)
        ref = FusedAdam(lr=1e-2)
        rp, _ = ref.step(grads, ref.init(params), params)
        for a, b in zip(jax.tree_util.tree_leaves(p),
                        jax.tree_util.tree_leaves(rp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_overflow_skip(self, rng):
        params = make_params(rng)
        opt = DistributedFusedAdam(lr=1e-2)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        p, s = opt.step(grads, state, params,
                        found_inf=jnp.ones((), jnp.float32))
        for a, b in zip(jax.tree_util.tree_leaves(p),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7)
        assert int(s["step"]) == 0


@pytest.mark.multi_device
class TestCompressedZeRO:
    """Block-quantized grad reduce-scatter / param all-gather inside the
    ZeRO optimizers (ISSUE 1: parallel/compression.py wiring)."""

    def _stacked_grads(self, rng, params, world):
        per_rank = [
            jax.tree_util.tree_map(
                lambda p: jnp.asarray(
                    rng.randn(*p.shape).astype(np.float32)), params)
            for _ in range(world)
        ]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_rank)

    @pytest.mark.slow
    def test_int8_grads_track_uncompressed(self, rng, dp_mesh):
        """int8 grad sync + error feedback stays close to the exact
        reduce-scatter over a few steps (per-step quantization error is
        bounded by the shared block scale; EF stops it accumulating)."""
        mesh = dp_mesh(4)
        params = make_params(rng)
        stacked = self._stacked_grads(rng, params, 4)

        def run(opt):
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=(P(), P("dp")), out_specs=P())
            def go(params, grads_stacked):
                grads = jax.tree_util.tree_map(lambda a: a[0],
                                               grads_stacked)
                state = opt.init(params)
                p = params
                for _ in range(3):
                    p, state = opt.step(grads, state, p)
                return p
            return go(params, stacked)

        exact = run(DistributedFusedAdam(lr=1e-2, weight_decay=0.01))
        quant = run(DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                         grad_compress="int8"))
        for a, b in zip(jax.tree_util.tree_leaves(exact),
                        jax.tree_util.tree_leaves(quant)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-2)

    def test_residual_in_state_and_updates(self, rng, dp_mesh):
        mesh = dp_mesh(4)
        params = make_params(rng)
        stacked = self._stacked_grads(rng, params, 4)
        opt = DistributedFusedAdam(lr=1e-2, compress=True)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                           out_specs=P())
        def go(params, grads_stacked):
            grads = jax.tree_util.tree_map(lambda a: a[0], grads_stacked)
            state = opt.init(params)
            _, state = opt.step(grads, state, params)
            return state["grad_residual"][None]

        res = np.asarray(go(params, stacked))
        assert res.dtype == np.float32
        assert np.abs(res).max() > 0  # quantization error was captured

    def test_bf16_param_gather(self, rng, dp_mesh):
        """bf16 param all-gather: params come back bf16-rounded but the
        fp32 master shard keeps full precision (gathered params stay
        within one bf16 ulp of the exact ones)."""
        mesh = dp_mesh(4)
        params = make_params(rng)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
            params)

        def run(opt):
            @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                               out_specs=P())
            def go(params, grads):
                state = opt.init(params)
                p, _ = opt.step(grads, state, params)
                return p
            return go(params, grads)

        exact = run(DistributedFusedAdam(lr=1e-2))
        cast = run(DistributedFusedAdam(lr=1e-2, param_compress="bf16"))
        for a, b in zip(jax.tree_util.tree_leaves(exact),
                        jax.tree_util.tree_leaves(cast)):
            a = np.asarray(a)
            np.testing.assert_allclose(a, np.asarray(b),
                                       atol=np.abs(a).max() * 2 ** -8)

    def test_lamb_compressed_close(self, rng, dp_mesh):
        mesh = dp_mesh(4)
        params = make_params(rng)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
            params)

        def run(opt):
            @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                               out_specs=P())
            def go(params, grads):
                state = opt.init(params)
                g4 = jax.tree_util.tree_map(lambda g: g / 4.0, grads)
                p, _ = opt.step(g4, state, params)
                return p
            return go(params, grads)

        exact = run(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01))
        quant = run(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                         compress=True))
        for a, b in zip(jax.tree_util.tree_leaves(exact),
                        jax.tree_util.tree_leaves(quant)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-2)


class TestDistributedFusedLAMB:
    @pytest.mark.multi_device
    def test_matches_fused_lamb(self, rng, dp_mesh):
        mesh = dp_mesh(4)
        params = make_params(rng)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
            params)

        dopt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                    grad_averaging=False)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                           out_specs=P())
        def run(params, grads):
            state = dopt.init(params)
            # identical grads on every rank; reduce-scatter sums -> x4
            grads4 = jax.tree_util.tree_map(lambda g: g / 4.0, grads)
            p, _ = dopt.step(grads4, state, params)
            return p

        out = run(params, grads)

        ref_opt = FusedLAMB(lr=1e-2, weight_decay=0.01, grad_averaging=False)
        rp, _ = ref_opt.step(grads, ref_opt.init(params), params)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(rp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
