"""External numerics oracle: apex_tpu ViTModel vs HuggingFace ViT.

A randomly-initialized ``transformers`` ViTForImageClassification (no
download) is converted with tools/convert_hf_vit; identical weights must
produce matching logits — validating the patch-conv layout conversion
(OIHW -> HWIO), CLS/position handling, fused-QKV permutation, pre-LN
bidirectional blocks with exact gelu, and the CLS classifier end to end.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, ".")  # repo root for tools/


def _tiny_vit(seed=0, image_size=32, patch=8):
    cfg = transformers.ViTConfig(
        hidden_size=48, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=96, image_size=image_size, patch_size=patch,
        num_channels=3, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, num_labels=10)
    torch.manual_seed(seed)
    return transformers.ViTForImageClassification(cfg).eval(), cfg


def test_logits_match_hf_vit():
    from tools.convert_hf_vit import convert_vit

    from apex_tpu.models.vit import ViTModel
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_vit()
    cfg, kwargs, params = convert_vit(hf.state_dict(), hf_cfg)
    assert kwargs["num_classes"] == 10

    rng = np.random.RandomState(0)
    imgs = rng.randn(2, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        # HF takes NCHW
        ref = hf(torch.asarray(imgs.transpose(0, 3, 1, 2))).logits.numpy()
    ours = ViTModel(cfg, **kwargs).apply({"params": params},
                                         jnp.asarray(imgs))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def test_vit_trains_end_to_end():
    """Grad flow + loss decreases on a tiny classification fit."""
    from apex_tpu.models.vit import ViTModel, vit_config, vit_loss_fn
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    cfg = vit_config(hidden_size=32, num_layers=2, num_heads=4,
                     ffn_hidden_size=64, compute_dtype=jnp.float32)
    model = ViTModel(cfg, image_size=16, patch_size=8, num_classes=4)
    rng = np.random.RandomState(1)
    imgs = jnp.asarray(rng.randn(8, 16, 16, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 4, (8,)))
    params = model.init(jax.random.PRNGKey(0), imgs)["params"]
    opt = FusedAdam(lr=3e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, g = jax.value_and_grad(
            lambda p: vit_loss_fn(model.apply({"params": p}, imgs),
                                  labels))(params)
        params, state = opt.step(g, state, params)
        return params, state, loss

    losses = []
    for _ in range(20):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_vit_refuses_causal_config():
    from apex_tpu.models import TransformerConfig
    from apex_tpu.models.vit import ViTModel

    cfg = TransformerConfig(hidden_size=32, num_layers=1,
                            num_attention_heads=4, vocab_size=1,
                            max_position_embeddings=1,
                            compute_dtype=jnp.float32)
    with pytest.raises(AssertionError, match="bidirectional"):
        ViTModel(cfg, image_size=16, patch_size=8, num_classes=2).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))


@pytest.mark.slow  # tier-1 budget (round 18): tp2-vs-tp1 parity is
# covered by the generation TP tests; the ViT variant rides the
# full suite
def test_vit_tp2_logits_match_tp1():
    """The whole vision family under tensor parallelism: split with the
    standard GPT rules (embed/classifier replicate), logits identical."""
    import functools

    from jax.sharding import PartitionSpec as P

    from tools.convert_hf_vit import convert_vit

    from apex_tpu.models.tp_split import split_params_for_tp
    from apex_tpu.models.vit import ViTModel
    from apex_tpu.transformer import parallel_state

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    parallel_state.destroy_model_parallel()
    hf, hf_cfg = _tiny_vit(seed=3)
    cfg, kwargs, params = convert_vit(hf.state_dict(), hf_cfg)
    imgs = jnp.asarray(
        np.random.RandomState(3).randn(2, 32, 32, 3), jnp.float32)
    ref = ViTModel(cfg, **kwargs).apply({"params": params}, imgs)

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, devices=jax.devices()[:2])
    stacked = split_params_for_tp(cfg, params, 2)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P("tp"), P()), out_specs=P(),
                       check_vma=False)
    def run(sp, x):
        p = jax.tree_util.tree_map(lambda a: a[0], sp)
        # class logits are fully replicated after the row-parallel psums
        return ViTModel(cfg, **kwargs).apply({"params": p}, x)

    out = run(stacked, imgs)
    parallel_state.destroy_model_parallel()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
