"""In-graph numerics observability (ISSUE 4): per-layer stats, the
flight-recorder ring, guard-trip post-mortems, and the amp/report
satellites.

The acceptance story covered here end-to-end: an 8-device DDP run with
``inject_nan`` targeting ONE module at step N trips the guard, and the
dumped flight record identifies that module prefix as the first
non-finite source with the prior K-1 steps' stats finite — while the
lowered HLO of the numerics-enabled step contains no host callbacks
(the same ``assert_clean_hlo(..., rules="no-host-callback")`` lint as
test_telemetry / test_resilience — apex_tpu.analysis).
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import resilience
from apex_tpu.parallel import DistributedDataParallel, distributed
from apex_tpu.resilience import faults
from apex_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    numerics,
    use_registry,
)

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# tensor_stats
# ---------------------------------------------------------------------------

def test_tensor_stats_known_values():
    x = jnp.asarray([3.0, -4.0, 0.0, 0.0])
    s = numerics.tensor_stats(x)
    assert float(s.l2) == pytest.approx(5.0)
    assert float(s.rms) == pytest.approx(2.5)
    assert float(s.absmax) == 4.0
    assert float(s.zero_frac) == 0.5
    assert float(s.nonfinite) == 0.0
    for f in ("fp16_overflow_frac", "fp16_underflow_frac",
              "bf16_overflow_frac", "bf16_underflow_frac"):
        assert float(getattr(s, f)) == 0.0


def test_tensor_stats_range_fractions():
    """fp16/bf16 thresholds: 1e5 overflows fp16 only, 1e-6 underflows
    fp16 only; both are comfortably inside bf16's range (bf16 shares
    fp32's exponent range, so bf16 under/overflow of an fp32 tensor
    only fires on fp32-subnormal/huge values — and XLA CPU flushes
    subnormals, so they are not assertable portably)."""
    s = numerics.tensor_stats(jnp.asarray([1e5, 1e-6, 1.0, 1.0]))
    assert float(s.fp16_overflow_frac) == pytest.approx(0.25)
    assert float(s.fp16_underflow_frac) == pytest.approx(0.25)
    assert float(s.bf16_overflow_frac) == 0.0
    assert float(s.bf16_underflow_frac) == 0.0


def test_tensor_stats_nonfinite_masked_but_counted():
    """NaN/Inf carry the signal through ``nonfinite``; the norm stats
    stay finite (masked) so the trend survives the blow-up. An inf is
    nonfinite, NOT an fp16/bf16 overflow."""
    s = numerics.tensor_stats(
        jnp.asarray([1.0, jnp.nan, jnp.inf, -jnp.inf]))
    assert float(s.nonfinite) == 3.0
    assert float(s.l2) == pytest.approx(1.0)
    assert float(s.absmax) == 1.0
    assert float(s.fp16_overflow_frac) == 0.0
    assert float(s.bf16_overflow_frac) == 0.0
    assert np.isfinite([float(getattr(s, f))
                        for f in numerics.STAT_FIELDS]).all()


def test_tensor_stats_rejects_int():
    with pytest.raises(TypeError, match="floating"):
        numerics.tensor_stats(jnp.arange(4))


def test_tensor_stats_under_jit_no_callback():
    from apex_tpu.analysis import assert_clean_hlo

    f = jax.jit(lambda x: numerics.tensor_stats(x))
    s = f(jnp.asarray([1.0, 2.0]))
    assert float(s.l2) == pytest.approx(np.sqrt(5.0))
    assert_clean_hlo(f, jnp.ones((8,)), rules="no-host-callback")


# ---------------------------------------------------------------------------
# tree_stats grouping
# ---------------------------------------------------------------------------

def _two_layer_tree(poison=None):
    tree = {
        "layer0": {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))},
        "layer1": {"w": jnp.full((4, 4), 2.0), "b": jnp.zeros((4,))},
        "step": jnp.asarray(7),  # int leaf: skipped
    }
    if poison:
        tree[poison]["w"] = jnp.full((4, 4), jnp.nan)
    return tree


def test_tree_stats_groups_by_prefix_depth():
    st1 = numerics.tree_stats(_two_layer_tree(), prefix_depth=1)
    assert sorted(st1) == ["layer0", "layer1"]
    # depth 2: w and b split out, int step leaf still skipped
    st2 = numerics.tree_stats(_two_layer_tree(), prefix_depth=2)
    assert sorted(st2) == ["layer0/b", "layer0/w",
                           "layer1/b", "layer1/w"]
    # group aggregation: layer0 = 16 ones + 4 zeros
    s = st1["layer0"]
    assert float(s.l2) == pytest.approx(4.0)
    assert float(s.zero_frac) == pytest.approx(4 / 20)
    assert float(s.absmax) == 1.0


def test_tree_stats_prefix_namespace_and_env_depth(monkeypatch):
    st = numerics.tree_stats(_two_layer_tree(), prefix_depth=1,
                             prefix="grads")
    assert sorted(st) == ["grads/layer0", "grads/layer1"]
    monkeypatch.setenv(numerics.ENV_DEPTH, "1")
    assert sorted(numerics.tree_stats(_two_layer_tree())) == \
        ["layer0", "layer1"]


def test_first_nonfinite_prefix_sorted_order():
    st = numerics.stats_to_floats(
        numerics.tree_stats(_two_layer_tree(poison="layer1"),
                            prefix_depth=1))
    assert numerics.first_nonfinite_prefix(st) == "layer1"
    st_clean = numerics.stats_to_floats(
        numerics.tree_stats(_two_layer_tree(), prefix_depth=1))
    assert numerics.first_nonfinite_prefix(st_clean) is None


# ---------------------------------------------------------------------------
# flight recorder ring semantics
# ---------------------------------------------------------------------------

def _stats_for(v, nan=False):
    leaf = jnp.full((4,), jnp.nan if nan else float(v))
    return numerics.tree_stats({"m": {"w": leaf}}, prefix_depth=1)


def test_ring_exact_length_and_eviction_order():
    rec = FlightRecorder(length=4, prefix_depth=1)
    state = rec.init_state({"m": {"w": jnp.zeros((4,))}})
    assert rec.fetch(state) == []  # empty ring: no rows
    for i in range(3):
        state = rec.record(state, i, _stats_for(i))
    rows = rec.fetch(state)
    assert [r["step"] for r in rows] == [0, 1, 2]  # partial fill
    for i in range(3, 7):
        state = rec.record(state, i, _stats_for(i))
    rows = rec.fetch(state)
    # exactly K rows, oldest evicted, oldest -> newest order
    assert [r["step"] for r in rows] == [3, 4, 5, 6]
    assert [r["stats"]["m"]["absmax"] for r in rows] == [3, 4, 5, 6]


def test_ring_first_nonfinite_and_prior_rows_finite():
    rec = FlightRecorder(length=8, prefix_depth=1)
    state = rec.init_state({"m": {"w": jnp.zeros((4,))}})
    for i in range(5):
        state = rec.record(state, i, _stats_for(i, nan=(i == 3)))
    rows = rec.fetch(state)
    assert rec.first_nonfinite(rows) == (3, "m")
    for r in rows[:3]:
        assert r["stats"]["m"]["nonfinite"] == 0.0
    clean = rec.fetch(rec.record(
        rec.init_state({"m": {"w": jnp.zeros((4,))}}), 0, _stats_for(1)))
    assert rec.first_nonfinite(clean) == (None, None)


def test_ring_record_under_jit_with_traced_cursor():
    rec = FlightRecorder(length=3, prefix_depth=1)

    @jax.jit
    def push(state, step, v):
        return rec.record(state, step, numerics.tree_stats(
            {"m": {"w": jnp.full((4,), v)}}, prefix_depth=1))

    state = rec.init_state({"m": {"w": jnp.zeros((4,))}})
    for i in range(5):
        state = push(state, jnp.asarray(i, jnp.int32),
                     jnp.asarray(float(i)))
    assert [r["step"] for r in rec.fetch(state)] == [2, 3, 4]
    from apex_tpu.analysis import assert_clean_hlo

    assert_clean_hlo(push, state, jnp.zeros((), jnp.int32),
                     jnp.zeros(()), rules="no-host-callback")


def test_ring_init_from_stats_dict_and_prefixes():
    rec = FlightRecorder(length=2, prefix_depth=1)
    tree = {"m": {"w": jnp.zeros((4,))}}
    by_prefixes = rec.init_state(tree, prefixes=("grads", "synced"))
    assert sorted(by_prefixes.buffer) == ["grads/m", "synced/m"]
    stats = numerics.tree_stats(tree, prefix_depth=1, prefix="grads")
    stats.update(numerics.tree_stats(tree, prefix_depth=1,
                                     prefix="synced"))
    from_stats = rec.init_state(stats)
    assert sorted(from_stats.buffer) == ["grads/m", "synced/m"]


def test_ring_rejects_zero_length():
    with pytest.raises(ValueError, match="length"):
        FlightRecorder(length=0)


def test_ring_env_length(monkeypatch):
    monkeypatch.setenv("APEX_TPU_NUMERICS_RING", "5")
    assert FlightRecorder().length == 5


# ---------------------------------------------------------------------------
# guard integration: recording survives the skip, post-mortems dump
# ---------------------------------------------------------------------------

def _sgd(lr=0.1):
    def update(grads, params):
        return jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                      params, grads)
    return update


def test_ring_contents_bit_identical_skipped_or_not():
    """The satellite contract: guarded_update records OUTSIDE the skip
    revert, so after the same grad sequence the ring is bit-identical
    whether steps were guarded (and one skipped) or recorded
    manually."""
    grads_seq = [
        {"m": {"w": jnp.full((4,), 1.0)}},
        {"m": {"w": jnp.full((4,), jnp.nan)}},   # skipped
        {"m": {"w": jnp.full((4,), 3.0)}},
    ]
    rec = FlightRecorder(length=4, prefix_depth=1)
    params = {"m": {"w": jnp.ones((4,))}}

    guarded = rec.init_state(params)
    gst = resilience.init_guard_state()
    p = params
    for i, g in enumerate(grads_seq):
        p, gst, guarded = resilience.guarded_update(
            g, _sgd(), p, gst, recorder=rec, recorder_state=guarded,
            step=i)
    assert int(gst.total_skips) == 1

    manual = rec.init_state(params)
    for i, g in enumerate(grads_seq):
        manual = rec.record(manual, i,
                            numerics.tree_stats(g, prefix_depth=1))

    for a, b in zip(jax.tree_util.tree_leaves(guarded),
                    jax.tree_util.tree_leaves(manual)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_guarded_update_recorder_arity_and_validation():
    from apex_tpu.amp.scaler import LossScaler

    params = {"m": {"w": jnp.ones((4,))}}
    rec = FlightRecorder(length=2, prefix_depth=1)
    rstate = rec.init_state(params)
    gst = resilience.init_guard_state()
    grads = {"m": {"w": jnp.full((4,), 2.0)}}

    out = resilience.guarded_update(grads, _sgd(), params, gst,
                                    recorder=rec, recorder_state=rstate)
    assert len(out) == 3  # state, guard, recorder_state
    assert int(out[2].cursor) == 1

    scaler = LossScaler("dynamic", init_scale=8.0)
    out = resilience.guarded_update(
        grads, _sgd(), params, gst, scaler=scaler,
        scaler_state=scaler.init_state(), recorder=rec,
        recorder_state=rstate)
    assert len(out) == 4  # + scaler_state third, recorder LAST
    assert isinstance(out[3], type(rstate))

    with pytest.raises(ValueError, match="recorder_state"):
        resilience.guarded_update(grads, _sgd(), params, gst,
                                  recorder=rec)


def test_check_guard_dumps_postmortem_and_names_prefix(tmp_path):
    rec = FlightRecorder(length=4, prefix_depth=1)
    params = {"good": {"w": jnp.ones((4,))},
              "bad": {"w": jnp.ones((4,))}}
    rstate = rec.init_state(params)
    gst = resilience.init_guard_state()
    for i, poison in enumerate([False, False, True]):
        grads = {"good": {"w": jnp.full((4,), 1.0)},
                 "bad": {"w": jnp.full((4,), jnp.nan if poison else 1.0)}}
        params, gst, rstate = resilience.guarded_update(
            grads, _sgd(), params, gst, recorder=rec,
            recorder_state=rstate, step=i)
    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        resilience.check_guard(gst, max_consecutive_skips=10,
                               recorder=rec, recorder_state=rstate,
                               postmortem_dir=str(tmp_path))
    pm_path = tmp_path / "numerics-postmortem-rank0.json"
    assert pm_path.exists()
    pm = json.loads(pm_path.read_text())
    assert pm["reason"] == "step_skipped"
    assert pm["first_nonfinite_prefix"] == "bad"
    assert pm["first_nonfinite_step"] == 2
    assert len(pm["rows"]) == 3
    # prior rows finite in every group
    for row in pm["rows"][:2]:
        for stats in row["stats"].values():
            assert stats["nonfinite"] == 0.0
    assert rec.last_postmortem["path"] == str(pm_path)


def test_check_guard_escalation_names_layer(tmp_path):
    from apex_tpu.resilience import NonFiniteError

    rec = FlightRecorder(length=4, prefix_depth=1)
    params = {"layerX": {"w": jnp.ones((4,))}}
    rstate = rec.init_state(params)
    gst = resilience.init_guard_state()
    for i in range(3):
        params, gst, rstate = resilience.guarded_update(
            {"layerX": {"w": jnp.full((4,), jnp.nan)}}, _sgd(), params,
            gst, recorder=rec, recorder_state=rstate, step=i)
    with pytest.raises(NonFiniteError, match="layerX"):
        resilience.check_guard(gst, max_consecutive_skips=3,
                               recorder=rec, recorder_state=rstate,
                               postmortem_dir=str(tmp_path))
    pm = json.loads(
        (tmp_path / "numerics-postmortem-rank0.json").read_text())
    assert pm["reason"] == "escalation"


def test_check_guard_without_recorder_unchanged():
    """Regression: the recorder is opt-in; the bare API and return
    stay as before."""
    gst = resilience.init_guard_state()
    assert resilience.check_guard(gst, max_consecutive_skips=3) == 0


# ---------------------------------------------------------------------------
# targeted fault injection
# ---------------------------------------------------------------------------

def test_inject_nan_path_filter_targets_one_module():
    tree = {"layer0": {"w": jnp.ones((3,))},
            "layer1": {"w": jnp.ones((3,))}}
    out = faults.inject_nan(tree, jnp.asarray(2), 2,
                            path_filter="layer1")
    np.testing.assert_array_equal(out["layer0"]["w"], 1.0)
    assert np.all(np.isnan(out["layer1"]["w"]))
    # other steps: identity everywhere
    out = faults.inject_nan(tree, jnp.asarray(1), 2,
                            path_filter="layer1")
    assert not np.any(np.isnan(out["layer1"]["w"]))
    # callable filter
    out = faults.inject_nan(tree, jnp.asarray(2), 2,
                            path_filter=lambda p: p.endswith("0/w"))
    assert np.all(np.isnan(out["layer0"]["w"]))
    np.testing.assert_array_equal(out["layer1"]["w"], 1.0)


# ---------------------------------------------------------------------------
# DDP / ZeRO wiring
# ---------------------------------------------------------------------------

def _grads_tree():
    return {"layer0": {"w": jnp.ones((512,))},
            "layer1": {"w": jnp.full((512,), 2.0)}}


@pytest.mark.multi_device
@pytest.mark.parametrize("message_size", [None, 64])
def test_ddp_sync_numerics_int8_returns_stats(dp_mesh, message_size):
    """Both sync paths (per-leaf and bucketed) append the stats dict:
    grads/* from the local pre-compression grads, synced/* from the
    dequantized result — the quantization error shows as an rms
    delta."""
    mesh = dp_mesh(8)
    ddp = DistributedDataParallel(axis_name="dp", compress="int8",
                                  numerics=1, message_size=message_size)
    grads = _grads_tree()
    res = ddp.init_residual(grads)

    def f(g, r):
        synced, new_r, stats = ddp.sync(g, r)
        return synced, new_r, stats

    synced, new_r, stats = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P(), P()),
        check_vma=False))(grads, res)
    assert sorted(stats) == ["grads/layer0", "grads/layer1",
                             "synced/layer0", "synced/layer1"]
    assert float(stats["grads/layer1"].rms) == pytest.approx(2.0)
    # dequant-vs-source rms delta: small but measurable quantization
    # error on the synced side
    delta = abs(float(stats["synced/layer1"].rms)
                - float(stats["grads/layer1"].rms))
    assert delta < 0.05


def test_all_reduce_gradients_numerics_no_compress():
    out, stats = distributed.all_reduce_gradients(
        _grads_tree(), (), numerics=1)
    assert sorted(stats) == ["grads/layer0", "grads/layer1",
                             "synced/layer0", "synced/layer1"]
    np.testing.assert_array_equal(out["layer0"]["w"],
                                  _grads_tree()["layer0"]["w"])
    assert float(stats["synced/layer0"].rms) == pytest.approx(1.0)


@pytest.mark.multi_device
@pytest.mark.parametrize("opt_name", ["adam", "lamb"])
def test_zero_optimizer_numerics_stats(dp_mesh, opt_name):
    """The ZeRO optimizers return pre-flatten grad stats third when
    numerics= is set (trace-only through the real optimizer)."""
    from apex_tpu.contrib.optimizers import (
        DistributedFusedAdam,
        DistributedFusedLAMB,
    )

    mesh = dp_mesh(8)
    cls = DistributedFusedAdam if opt_name == "adam" \
        else DistributedFusedLAMB
    opt = cls(lr=1e-3, axis_name="dp", numerics=1)

    def f(params, grads):
        state = opt.init(params)
        new_p, _, stats = opt.step(grads, state, params)
        return new_p, stats

    tree = {"enc": {"w": jnp.zeros((1024,), jnp.float32)}}
    jitted = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        check_vma=False))
    from apex_tpu.analysis import assert_clean_hlo

    assert_clean_hlo(jitted, tree, tree, rules="no-host-callback")
    _, stats = jitted(tree, tree)
    assert sorted(stats) == ["grads/enc"]
    assert float(stats["grads/enc"].zero_frac) == 1.0


# ---------------------------------------------------------------------------
# end-to-end acceptance: 8-device DDP, targeted NaN, post-mortem
# ---------------------------------------------------------------------------

def _make_numerics_ddp_step(mesh, hidden, nan_step, rec, target):
    ddp = DistributedDataParallel(axis_name="dp", compress="int8",
                                  numerics=1)

    def loss_fn(p, xb, yb):
        h = jnp.tanh(xb @ p["layer0"]["w"])
        h = h @ p["layer1"]["w"]
        return jnp.mean((h - yb) ** 2)

    def step_fn(p, res, gst, rstate, step, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb)
        grads = faults.inject_nan(grads, step, nan_step,
                                  path_filter=target)
        flag = resilience.nonfinite_flag(grads)
        synced, new_res, stats = ddp.sync(grads, res)

        def commit(g, st):
            prev_p, _ = st
            new_p = jax.tree_util.tree_map(
                lambda w, gg: w - 0.05 * gg, prev_p, g)
            return (new_p, new_res)

        (p, res), gst, rstate = resilience.guarded_update(
            synced, commit, (p, res), gst, axis_name="dp", flag=flag,
            recorder=rec, recorder_state=rstate, stats=stats, step=step)
        return p, res, gst, rstate, loss

    sharded = jax.shard_map(
        step_fn, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P(), P(), P()), check_vma=False)
    return ddp, jax.jit(sharded)


@pytest.mark.multi_device
def test_e2e_postmortem_identifies_poisoned_module(dp_mesh, tmp_path):
    """ISSUE 4 acceptance: NaN targeted at layer1 at step 5 of an
    8-device guarded DDP run -> guard trips (exactly one skip), the
    flight record names grads/layer1 as the first non-finite source,
    the prior K-1 ring rows are finite, and the lowered HLO has no
    host callbacks."""
    mesh = dp_mesh(8)
    hidden, batch, steps, nan_step = 16, 8, 6, 5
    rec = FlightRecorder(length=4, prefix_depth=1)
    ddp, train = _make_numerics_ddp_step(mesh, hidden, nan_step, rec,
                                         "layer1")
    rng = np.random.RandomState(0)
    params = {f"layer{i}": {"w": jnp.asarray(
        rng.randn(hidden, hidden).astype(np.float32) / np.sqrt(hidden))}
        for i in range(2)}
    x = jnp.asarray(rng.randn(batch, hidden).astype(np.float32))
    y = jnp.asarray(rng.randn(batch, hidden).astype(np.float32))
    res = ddp.init_residual(params)
    gst = resilience.init_guard_state()
    rstate = rec.init_state(params, prefixes=("grads", "synced"))

    from apex_tpu.analysis import assert_clean_hlo

    assert_clean_hlo(train, params, res, gst, rstate,
                     jnp.zeros((), jnp.int32), x, y,
                     rules="no-host-callback")

    reg = MetricsRegistry(enabled=True)
    with use_registry(reg):
        for i in range(steps):
            params, res, gst, rstate, loss = train(
                params, res, gst, rstate, jnp.asarray(i, jnp.int32),
                x, y)
            resilience.check_guard(gst, max_consecutive_skips=steps + 1,
                                   recorder=rec, recorder_state=rstate,
                                   postmortem_dir=str(tmp_path))
    assert int(gst.total_skips) == 1
    assert np.isfinite(float(loss))
    assert reg.snapshot()["counters"]["guard/steps_skipped"] == 1

    pm = json.loads(
        (tmp_path / "numerics-postmortem-rank0.json").read_text())
    assert pm["first_nonfinite_prefix"] == "grads/layer1"
    assert pm["first_nonfinite_step"] == nan_step
    # ring of length 4 after 6 steps: rows 2..5, the first K-1 finite
    assert [r["step"] for r in pm["rows"]] == [2, 3, 4, 5]
    for row in pm["rows"][:-1]:
        for stats in row["stats"].values():
            assert stats["nonfinite"] == 0.0
    # the untouched module never went non-finite, even on the bad step
    assert pm["rows"][-1]["stats"]["grads/layer0"]["nonfinite"] == 0.0
    assert pm["rows"][-1]["stats"]["grads/layer1"]["nonfinite"] > 0


# ---------------------------------------------------------------------------
# satellite: LossScaler telemetry
# ---------------------------------------------------------------------------

def test_loss_scaler_update_records_amp_metrics(tmp_path):
    from apex_tpu.amp.scaler import LossScaler

    reg = MetricsRegistry(jsonl_dir=str(tmp_path))
    scaler = LossScaler("dynamic", init_scale=8.0, scale_factor=2.0,
                        scale_window=2)
    with use_registry(reg):
        state = scaler.init_state()
        state = scaler.update(state, jnp.asarray(1.0))   # overflow: 8->4
        state = scaler.update(state, jnp.asarray(0.0))
        state = scaler.update(state, jnp.asarray(0.0))   # window: 4->8
    snap = reg.snapshot()
    assert snap["gauges"]["amp/loss_scale"] == 8.0
    assert snap["counters"]["amp/overflow"] == 1
    assert snap["counters"]["amp/scale_window_growth"] == 1
    events = []
    for f in tmp_path.glob("*.jsonl"):
        events.extend(json.loads(l) for l in f.read_text().splitlines())
    amp_ev = [e for e in events if e["kind"] == "amp"]
    assert len(amp_ev) == 3
    assert amp_ev[0]["overflow"] is True and amp_ev[0]["scale"] == 4.0
    assert amp_ev[2]["grew"] is True


def test_loss_scaler_disabled_registry_records_nothing():
    from apex_tpu.amp.scaler import LossScaler

    reg = MetricsRegistry()  # disabled
    scaler = LossScaler("dynamic", init_scale=8.0)
    with use_registry(reg):
        scaler.update(scaler.init_state(), jnp.asarray(1.0))
    snap = reg.snapshot()
    snap.pop("ts")  # snapshot's own timestamp, not an instrument
    assert snap == {"counters": {}, "gauges": {}, "histograms": {}}


def test_loss_scaler_update_lowering_identical_and_callback_free():
    """The regression the satellite asks for: telemetry adds no host
    callback to the lowered update — the HLO is identical whether the
    registry is enabled or disabled (recording under tracing is
    skipped entirely)."""
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler("dynamic", init_scale=8.0)
    state = scaler.init_state()

    def lowered_text(registry):
        with use_registry(registry):
            return jax.jit(scaler.update).lower(
                state, jnp.zeros(())).as_text()

    off = lowered_text(MetricsRegistry())
    on = lowered_text(MetricsRegistry(enabled=True))
    from apex_tpu.analysis import assert_clean_hlo

    with use_registry(MetricsRegistry(enabled=True)):
        assert_clean_hlo(jax.jit(scaler.update), state, jnp.zeros(()),
                         rules="no-host-callback")
    assert on == off


def test_loss_scaler_static_mode_update_untouched():
    from apex_tpu.amp.scaler import LossScaler

    reg = MetricsRegistry(enabled=True)
    scaler = LossScaler(128.0)  # static
    with use_registry(reg):
        state = scaler.init_state()
        assert scaler.update(state, jnp.asarray(1.0)) is state
    assert reg.snapshot()["gauges"] == {}


# ---------------------------------------------------------------------------
# satellite: telemetry_report forward compat + new kinds
# ---------------------------------------------------------------------------

def _report_module():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.remove(os.path.join(ROOT, "tools"))
    return telemetry_report


def test_telemetry_report_skips_unknown_kinds_with_footer(capsys):
    rep = _report_module()
    events = [
        ("r0", {"kind": "span", "name": "a", "duration_s": 0.5}),
        ("r0", {"kind": "from_the_future", "name": "x"}),
        ("r0", {"kind": "from_the_future", "name": "y"}),
        ("r0", {"kind": "hologram"}),
        ("r0", {"kind": "collective", "name": "psum",
                "wire_bytes": "not-a-number"}),  # malformed, not fatal
    ]
    report = rep.aggregate(events)
    assert report["events"] == 5
    assert report["unknown_kinds"] == {"from_the_future": 2,
                                       "hologram": 1}
    assert report["malformed_events"] == 1
    assert report["spans"]["a"]["count"] == 1
    rep.print_report(report, out=sys.stdout)
    out = capsys.readouterr().out
    assert "skipped 4 event(s)" in out
    assert "from_the_future: 2" in out


def test_telemetry_report_aggregates_numerics_and_amp(capsys):
    rep = _report_module()
    events = [
        ("r0", {"kind": "amp", "name": "loss_scale", "scale": 4.0,
                "overflow": True, "grew": False}),
        ("r0", {"kind": "amp", "name": "loss_scale", "scale": 8.0,
                "overflow": False, "grew": True}),
        ("r0", {"kind": "numerics", "name": "postmortem",
                "reason": "step_skipped", "path": "/tmp/pm.json",
                "first_nonfinite_prefix": "grads/layer1",
                "first_nonfinite_step": 5}),
        ("r0", {"kind": "guard", "name": "step_skipped"}),
    ]
    report = rep.aggregate(events)
    assert report["amp"] == {"updates": 2, "overflows": 1, "growths": 1,
                             "last_loss_scale": 8.0}
    assert report["numerics"]["postmortems"][0][
        "first_nonfinite_prefix"] == "grads/layer1"
    assert report["guard"]["skips"] == 1
    assert report["unknown_kinds"] == {}
    rep.print_report(report, out=sys.stdout)
    out = capsys.readouterr().out
    assert "grads/layer1" in out
    assert "last loss_scale = 8.0" in out


# ---------------------------------------------------------------------------
# tools/numerics_report renderer
# ---------------------------------------------------------------------------

def test_numerics_report_renders_postmortem(tmp_path, capsys):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import numerics_report
    finally:
        sys.path.remove(os.path.join(ROOT, "tools"))

    rec = FlightRecorder(length=3, prefix_depth=1)
    state = rec.init_state({"m": {"w": jnp.zeros((4,))}})
    for i in range(3):
        state = rec.record(state, i, _stats_for(i, nan=(i == 2)))
    rec.dump_postmortem(state, str(tmp_path), reason="unit")

    assert numerics_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "FIRST NON-FINITE: module prefix 'm' at step 2" in out
    assert "m:" in out

    assert numerics_report.main(["--json", str(tmp_path)]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["postmortems"][0]["first_nonfinite_prefix"] == "m"
    assert [r["step"] for r in data["postmortems"][0]["rows"]] == \
        [0, 1, 2]

    # nothing found -> exit 1, not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    assert numerics_report.main([str(empty)]) == 1


# ---------------------------------------------------------------------------
# bench config (tiny, CPU): emission + post-mortem + overhead field
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
def test_bench_ddp_numerics_emits_and_dumps(monkeypatch, tmp_path,
                                            capsys):
    sys.path.insert(0, ROOT)
    try:
        import bench
    finally:
        sys.path.remove(ROOT)

    from apex_tpu import telemetry
    from apex_tpu.telemetry.registry import ENV_DIR

    tel_dir = tmp_path / "tel"
    monkeypatch.setenv(ENV_DIR, str(tel_dir))
    prev = telemetry.set_registry(None)  # force re-resolution from env
    try:
        ret = bench.bench_ddp_numerics(2, 5, hidden=32, depth=2,
                                       nan_step=3, ring=4)
    finally:
        telemetry.set_registry(prev)

    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["metric"] == "ddp_numerics_steps_per_sec"
    assert isinstance(line["numerics_overhead_pct"], float)
    assert line["steps_skipped"] == 1
    assert line["first_nonfinite_prefix"] == "grads/layer1"
    assert ret["postmortem_path"] and os.path.exists(
        ret["postmortem_path"])
    pm = json.loads(open(ret["postmortem_path"]).read())
    assert pm["first_nonfinite_prefix"] == "grads/layer1"
    assert pm["first_nonfinite_step"] == 3
    # the post-mortem landed in the telemetry dir (no explicit dir set)
    assert os.path.dirname(ret["postmortem_path"]) == str(tel_dir)
