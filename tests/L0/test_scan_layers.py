"""scan_layers: the lax.scan-compiled stack equals the unrolled stack.

The scan path exists for compile time (O(1) in depth vs O(n) for the
unrolled loop — material for the 16-24 layer bench models); numerics must
be identical given the same weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.transformer_lm import (
    ParallelTransformer,
    TransformerConfig,
)
from apex_tpu.transformer import parallel_state


def _cfg(**kw):
    base = dict(hidden_size=32, num_layers=3, num_attention_heads=4,
                vocab_size=64, max_position_embeddings=16,
                compute_dtype=jnp.float32, use_flash_attention=False)
    base.update(kw)
    return TransformerConfig(**base)


def _unrolled_params_from_stacked(stacked, n):
    """layers/layer/<tree> with leading [n] axis -> {layer_i: <tree>}."""
    inner = stacked["layers"]["layer"]
    return {f"layer_{i}": jax.tree_util.tree_map(lambda a, i=i: a[i], inner)
            for i in range(n)}


def test_scan_matches_unrolled_dense():
    parallel_state.destroy_model_parallel()
    x = jnp.asarray(np.random.RandomState(0).randn(8, 2, 32), jnp.float32)
    scan_model = ParallelTransformer(_cfg(scan_layers=True))
    unroll_model = ParallelTransformer(_cfg())

    stacked = scan_model.init(jax.random.PRNGKey(0), x)["params"]
    out_scan = scan_model.apply({"params": stacked}, x)
    unrolled = _unrolled_params_from_stacked(stacked, 3)
    out_unroll = unroll_model.apply({"params": unrolled}, x)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_unroll),
                               rtol=2e-5, atol=2e-5)


def test_scan_grads_match_unrolled():
    parallel_state.destroy_model_parallel()
    x = jnp.asarray(np.random.RandomState(1).randn(8, 2, 32), jnp.float32)
    scan_model = ParallelTransformer(_cfg(scan_layers=True))
    unroll_model = ParallelTransformer(_cfg())
    stacked = scan_model.init(jax.random.PRNGKey(0), x)["params"]
    unrolled = _unrolled_params_from_stacked(stacked, 3)

    g_scan = jax.grad(
        lambda p: jnp.sum(scan_model.apply({"params": p}, x) ** 2))(stacked)
    g_unroll = jax.grad(
        lambda p: jnp.sum(unroll_model.apply({"params": p}, x) ** 2))(unrolled)
    g_scan_inner = g_scan["layers"]["layer"]
    for i in range(3):
        a = jax.tree_util.tree_map(lambda t, i=i: t[i], g_scan_inner)
        b = g_unroll[f"layer_{i}"]
        for (pa, la), (_, lb) in zip(
                jax.tree_util.tree_leaves_with_path(a),
                jax.tree_util.tree_leaves_with_path(b)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=5e-5, atol=5e-5,
                                       err_msg=f"layer {i} {pa}")


def test_scan_with_moe_collects_losses():
    from apex_tpu.transformer.moe import moe_loss_from_variables

    parallel_state.destroy_model_parallel()
    cfg = _cfg(scan_layers=True, num_moe_experts=2, moe_capacity_factor=4.0)
    x = jnp.asarray(np.random.RandomState(2).randn(8, 2, 32), jnp.float32)
    model = ParallelTransformer(cfg)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    out, mut = model.apply({"params": params}, x, mutable=["moe_losses"])
    total = moe_loss_from_variables(mut, aux_loss_coeff=1.0)
    assert out.shape == x.shape
    assert total.shape == ()
    # every one of the 3 scanned MoE layers contributes ~balanced aux >= 1
    assert float(total) > 2.0


def test_scan_moe_jitter_rng_threaded():
    """nn.scan must forward the 'jitter' rng stream (split per layer) —
    unlisted streams are dropped, which would silently disable jitter."""
    parallel_state.destroy_model_parallel()
    cfg = _cfg(scan_layers=True, num_moe_experts=2, moe_capacity_factor=4.0,
               moe_jitter_eps=0.3)
    x = jnp.asarray(np.random.RandomState(4).randn(8, 2, 32), jnp.float32)
    model = ParallelTransformer(cfg)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    base, _ = model.apply({"params": params}, x, mutable=["moe_losses"])
    jittered, _ = model.apply({"params": params}, x,
                              rngs={"jitter": jax.random.PRNGKey(7)},
                              mutable=["moe_losses"])
    assert not np.allclose(np.asarray(base), np.asarray(jittered))


def test_scan_moe_requires_uniform_stack():
    import pytest

    parallel_state.destroy_model_parallel()
    cfg = _cfg(scan_layers=True, num_moe_experts=2, moe_layer_freq=2)
    x = jnp.ones((4, 1, 32))
    with pytest.raises(ValueError, match="uniform"):
        ParallelTransformer(cfg).init(jax.random.PRNGKey(0), x)


def test_scan_gpt_model_trains():
    from apex_tpu.models import GPTModel
    from apex_tpu.models.gpt import gpt_loss_fn

    parallel_state.destroy_model_parallel()
    cfg = _cfg(scan_layers=True)
    model = GPTModel(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    def loss_fn(p):
        return gpt_loss_fn(model.apply({"params": p}, tokens),
                           jnp.roll(tokens, -1, axis=-1))

    loss, g = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    assert float(jnp.abs(
        jax.tree_util.tree_leaves(g["transformer"])[0]).sum()) > 0


def test_activation_checkpointing_off_matches_on(rng):
    """cfg.activation_checkpointing only changes the memory/compute
    schedule (VERDICT r1 item 6 MFU lever), never the math: loss and
    grads must match with remat on vs off."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.models.gpt import gpt_loss_fn

    cfg_on = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=16,
        compute_dtype=jnp.float32, use_flash_attention=False,
        activation_checkpointing=True)
    cfg_off = dataclasses.replace(cfg_on, activation_checkpointing=False)
    tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
    labels = jnp.asarray(rng.randint(0, 64, (2, 16)))
    params = GPTModel(cfg_on).init(jax.random.PRNGKey(0), tokens)["params"]

    def lg(cfg):
        model = GPTModel(cfg)
        return jax.value_and_grad(lambda p: gpt_loss_fn(
            model.apply({"params": p}, tokens), labels))(params)

    loss_on, g_on = lg(cfg_on)
    loss_off, g_off = lg(cfg_off)
    assert float(loss_on) == pytest.approx(float(loss_off), rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_on),
                    jax.tree_util.tree_leaves(g_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
