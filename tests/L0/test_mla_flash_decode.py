"""MLA latent-cache flash decode kernel vs the einsum formulation.

The kernel (contrib/mla_decode.py) streams the latent cache through VMEM
once with an online softmax; these tests run it in interpreter mode on
the CPU mesh (real kernel dataflow, no TPU needed) and pin it to the
einsum oracle:

- value parity across prefix lengths spanning tile boundaries, multiple
  batches, bf16 cache rows;
- end to end: DeepseekModel cached decode with the kernel forced ON is
  token-exact vs the einsum decode path AND the full-rerun forward;
- the fallback ladder (off-TPU -> einsum; indivisible cache -> einsum).

VERDICT r4 item 4; reference analog: apex/contrib/fmha exists purely to
make attention fast (fmha_api.cpp:363).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib import mla_decode as md


@pytest.fixture
def interpret_kernel():
    md.force_interpret(True)
    yield
    md.force_interpret(False)


class TestKernelParity:
    @pytest.mark.parametrize("length", [1, 7, 16, 33, 48])
    def test_matches_reference_across_tile_boundaries(self, length,
                                                      interpret_kernel):
        rng = np.random.RandomState(0)
        b, n, lat, rope, T = 2, 8, 32, 8, 48
        L = lat + rope
        q = jnp.asarray(rng.randn(b, n, L), jnp.float32).astype(jnp.bfloat16)
        cache = jnp.asarray(rng.randn(T, b, L),
                            jnp.float32).astype(jnp.bfloat16)
        ref = md.mla_decode_reference(q, cache, jnp.int32(length), lat, 0.3)
        out = md.mla_flash_decode(q, cache, jnp.int32(length), lat, 0.3,
                                  block_t=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6)

    def test_dead_tiles_do_not_change_result(self, interpret_kernel):
        """Rows beyond ``length`` must be invisible even when filled with
        huge values (the mask, not luck, protects the softmax)."""
        rng = np.random.RandomState(1)
        b, n, lat, T = 1, 4, 16, 32
        L = lat + 4
        q = jnp.asarray(rng.randn(b, n, L), jnp.float32)
        live = rng.randn(T, b, L).astype(np.float32)
        poisoned = live.copy()
        poisoned[10:] = 1e4  # length = 10 -> all poisoned rows are dead
        o1 = md.mla_flash_decode(q, jnp.asarray(live), jnp.int32(10), lat,
                                 0.5, block_t=8)
        o2 = md.mla_flash_decode(q, jnp.asarray(poisoned), jnp.int32(10),
                                 lat, 0.5, block_t=8)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   atol=1e-6)

    def test_fallbacks(self):
        """Off-TPU (no interpret) and indivisible cache lengths take the
        einsum path — same public entry, same result."""
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 2, 12), jnp.float32)
        cache = jnp.asarray(rng.randn(10, 1, 12), jnp.float32)  # T=10
        ref = md.mla_decode_reference(q, cache, jnp.int32(6), 8, 0.4)
        out = md.mla_flash_decode(q, cache, jnp.int32(6), 8, 0.4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))


class TestEndToEnd:
    def _model(self):
        from apex_tpu.models.mla import DeepseekModel, MLAConfig
        from apex_tpu.transformer import parallel_state

        parallel_state.destroy_model_parallel()
        cfg = MLAConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            q_lora_rank=None, kv_lora_rank=8, qk_nope_head_dim=8,
            qk_rope_head_dim=4, v_head_dim=8, ffn_hidden_size=64,
            max_decode_length=32, compute_dtype=jnp.float32)
        m = DeepseekModel(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(3).randint(0, 128, (2, 6)))
        params = m.init(jax.random.PRNGKey(0), tokens)["params"]
        return m, params, tokens

    def _greedy_cached(self, m, params, prompt, new_tokens):
        """prefill + single-token steps through the latent cache."""
        logits, var = m.apply({"params": params}, prompt, mode="prefill",
                              mutable=["cache"])
        seq = prompt
        for _ in range(new_tokens):
            nxt = jnp.argmax(logits[:, -1:], -1)
            seq = jnp.concatenate([seq, nxt], axis=1)
            logits, var = m.apply(
                {"params": params, "cache": var["cache"]}, nxt,
                mode="step", mutable=["cache"])
        return seq

    @pytest.mark.slow
    def test_cached_decode_token_exact_vs_einsum_path(self,
                                                      interpret_kernel):
        m, params, prompt = self._model()
        with_kernel = self._greedy_cached(m, params, prompt, 6)
        md.force_interpret(False)  # now the same steps ride the einsum path
        without = self._greedy_cached(m, params, prompt, 6)
        np.testing.assert_array_equal(np.asarray(with_kernel),
                                      np.asarray(without))

    @pytest.mark.slow  # duplicate coverage: the token-exact kernel-vs-
    # einsum test above walks the same cached-decode path (tier-1 budget)
    def test_cached_decode_matches_full_rerun(self, interpret_kernel):
        m, params, prompt = self._model()
        seq = self._greedy_cached(m, params, prompt, 5)
        # full-rerun oracle: greedy from scratch each step, no cache
        full = prompt
        for _ in range(5):
            logits = m.apply({"params": params}, full)
            full = jnp.concatenate(
                [full, jnp.argmax(logits[:, -1:], -1)], axis=1)
        np.testing.assert_array_equal(np.asarray(seq), np.asarray(full))
