"""Checkpoint -> 3D-parallel resharding oracles.

A full single-program GPTModel checkpoint, resharded into the pipelined
harness layout (pp x tp x dp, optional vpp chunks), must reproduce the
unsharded model's loss on the same batch — the same bar the TP-split and
HF-converter oracles set (reference analog: none; its checkpoints are
saved per rank and never change layout).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import GPTModel, gpt_loss_fn
from apex_tpu.models.reshard import (
    load_checkpoint_for_3d,
    split_gpt_params_for_pp,
)
from apex_tpu.models.transformer_lm import TransformerConfig
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp.grad_scaler import GradScaler
from apex_tpu.transformer.testing.gpt_3d import build_gpt_3d_harness

PP, DP, TP = 2, 2, 2
SEQ, MB, M = 16, 2, 2


def _cfg(**kw):
    kw.setdefault("compute_dtype", jnp.float32)
    return TransformerConfig(
        hidden_size=64, num_layers=4, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32,
        use_flash_attention=False, activation_checkpointing=False, **kw)


def _full_model_oracle(cfg, tokens, labels):
    """Init the unsharded model (tp=1) and return (params, mean loss)."""
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(7), tokens[:2])["params"]
    logits = model.apply({"params": params}, tokens)
    loss = float(gpt_loss_fn(logits, labels))
    parallel_state.destroy_model_parallel()
    return params, loss


def _pipelined_loss(cfg, params, tokens, labels, vpp=None):
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, pipeline_model_parallel_size_=PP,
        virtual_pipeline_model_parallel_size_=vpp,
        devices=jax.devices()[:8])
    stacked = load_checkpoint_for_3d(cfg, params, mesh, pp=PP,
                                     vpp=vpp or 1)
    init_state, step = build_gpt_3d_harness(
        cfg, mesh, FusedAdam(lr=1e-3), GradScaler(enabled=False),
        pp=PP, seq=SEQ, microbatch=MB, num_microbatches=M, vpp=vpp)
    state = init_state(jax.random.PRNGKey(0), tokens, labels,
                       stacked_params=stacked)
    *_, loss = step(*state, tokens, labels)
    # last-pp-stage rows carry per-replica microbatch loss sums
    return float(np.asarray(loss).sum()) / DP / M


@pytest.fixture(autouse=True)
def _clean_state():
    parallel_state.destroy_model_parallel()
    yield
    parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("vpp", [None, 2])
def test_resharded_checkpoint_matches_full_model_loss(vpp):
    cfg = _cfg()
    rng = np.random.RandomState(3)
    global_b = MB * M * DP
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    params, ref_loss = _full_model_oracle(cfg, tokens, labels)
    pipe_loss = _pipelined_loss(cfg, params, tokens, labels, vpp=vpp)
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-4)


def test_resharded_tied_checkpoint_unties_head():
    """A tie_word_embeddings checkpoint has no lm_head param; resharding
    materializes embedding.T so stages can run the untied head."""
    cfg = _cfg(tie_word_embeddings=True)
    rng = np.random.RandomState(4)
    global_b = MB * M * DP
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    params, ref_loss = _full_model_oracle(cfg, tokens, labels)
    assert "lm_head" not in params  # precondition: it IS a tied ckpt
    pipe_loss = _pipelined_loss(cfg, params, tokens, labels)
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-4)


def test_scan_layers_checkpoint_slices_stacked_stack():
    """scan_layers checkpoints keep one stacked [L, ...] leaf per param;
    the pp split must slice, not rename."""
    cfg = _cfg(scan_layers=True)
    stages = split_gpt_params_for_pp(cfg, _scan_params(cfg), pp=2)
    lead = jax.tree_util.tree_leaves(stages[0]["transformer"])[0]
    assert lead.shape[0] == cfg.num_layers // 2


def _scan_params(cfg):
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPTModel(cfg)
    tok = jnp.zeros((2, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tok)["params"]
    parallel_state.destroy_model_parallel()
    return params


def test_pp_split_validates_layer_count():
    cfg = _cfg()
    with pytest.raises(ValueError, match="multiple of pp"):
        split_gpt_params_for_pp(cfg, {}, pp=3)


def test_hf_gemma_checkpoint_through_3d_pipeline():
    """The full migration story on an external model family: HF Gemma
    (GeGLU, tied head, sqrt(hidden) embedding scale, GQA) converted,
    resharded to pp x tp x dp, pipelined loss == HF-converted unsharded
    loss."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import sys

    sys.path.insert(0, ".")
    from tools.convert_hf_gemma import convert_gemma

    import dataclasses

    # kv groups (2) must divide tp (2) for the TP shard split
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=48, intermediate_size=96,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=32,
        attention_dropout=0.0)
    torch.manual_seed(9)
    hf = transformers.GemmaForCausalLM(hf_cfg).eval()
    cfg, params = convert_gemma(hf.state_dict(), hf_cfg)
    cfg = dataclasses.replace(cfg, activation_checkpointing=False)

    rng = np.random.RandomState(9)
    global_b = MB * M * DP
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))

    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    logits = GPTModel(cfg).apply({"params": params}, tokens)
    ref_loss = float(gpt_loss_fn(logits, labels))
    parallel_state.destroy_model_parallel()

    pipe_loss = _pipelined_loss(cfg, params, tokens, labels)
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-4)
