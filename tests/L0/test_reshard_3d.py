"""Checkpoint -> 3D-parallel resharding oracles.

A full single-program GPTModel checkpoint, resharded into the pipelined
harness layout (pp x tp x dp, optional vpp chunks), must reproduce the
unsharded model's loss on the same batch — the same bar the TP-split and
HF-converter oracles set (reference analog: none; its checkpoints are
saved per rank and never change layout).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import GPTModel, gpt_loss_fn
from apex_tpu.models.reshard import (
    load_checkpoint_for_3d,
    split_gpt_params_for_pp,
)
from apex_tpu.models.transformer_lm import TransformerConfig
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp.grad_scaler import GradScaler
from apex_tpu.transformer.testing.gpt_3d import build_gpt_3d_harness

PP, DP, TP = 2, 2, 2
SEQ, MB, M = 16, 2, 2


def _cfg(**kw):
    kw.setdefault("compute_dtype", jnp.float32)
    return TransformerConfig(
        hidden_size=64, num_layers=4, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32,
        use_flash_attention=False, activation_checkpointing=False, **kw)


def _full_model_oracle(cfg, tokens, labels):
    """Init the unsharded model (tp=1) and return (params, mean loss)."""
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(7), tokens[:2])["params"]
    logits = model.apply({"params": params}, tokens)
    loss = float(gpt_loss_fn(logits, labels))
    parallel_state.destroy_model_parallel()
    return params, loss


def _pipelined_loss(cfg, params, tokens, labels, vpp=None):
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, pipeline_model_parallel_size_=PP,
        virtual_pipeline_model_parallel_size_=vpp,
        devices=jax.devices()[:8])
    stacked = load_checkpoint_for_3d(cfg, params, mesh, pp=PP,
                                     vpp=vpp or 1)
    init_state, step = build_gpt_3d_harness(
        cfg, mesh, FusedAdam(lr=1e-3), GradScaler(enabled=False),
        pp=PP, seq=SEQ, microbatch=MB, num_microbatches=M, vpp=vpp)
    state = init_state(jax.random.PRNGKey(0), tokens, labels,
                       stacked_params=stacked)
    *_, loss = step(*state, tokens, labels)
    # last-pp-stage rows carry per-replica microbatch loss sums
    return float(np.asarray(loss).sum()) / DP / M


@pytest.fixture(autouse=True)
def _clean_state():
    parallel_state.destroy_model_parallel()
    yield
    parallel_state.destroy_model_parallel()


@pytest.mark.slow
@pytest.mark.parametrize("vpp", [None, 2])
def test_resharded_checkpoint_matches_full_model_loss(vpp):
    cfg = _cfg()
    rng = np.random.RandomState(3)
    global_b = MB * M * DP
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    params, ref_loss = _full_model_oracle(cfg, tokens, labels)
    pipe_loss = _pipelined_loss(cfg, params, tokens, labels, vpp=vpp)
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-4)


@pytest.mark.slow
def test_resharded_tied_checkpoint_unties_head():
    """A tie_word_embeddings checkpoint has no lm_head param; resharding
    materializes embedding.T so stages can run the untied head."""
    cfg = _cfg(tie_word_embeddings=True)
    rng = np.random.RandomState(4)
    global_b = MB * M * DP
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    params, ref_loss = _full_model_oracle(cfg, tokens, labels)
    assert "lm_head" not in params  # precondition: it IS a tied ckpt
    pipe_loss = _pipelined_loss(cfg, params, tokens, labels)
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-4)


def test_scan_layers_checkpoint_slices_stacked_stack():
    """scan_layers checkpoints keep one stacked [L, ...] leaf per param;
    the pp split must slice, not rename."""
    cfg = _cfg(scan_layers=True)
    stages = split_gpt_params_for_pp(cfg, _scan_params(cfg), pp=2)
    lead = jax.tree_util.tree_leaves(stages[0]["transformer"])[0]
    assert lead.shape[0] == cfg.num_layers // 2


def _scan_params(cfg):
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPTModel(cfg)
    tok = jnp.zeros((2, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tok)["params"]
    parallel_state.destroy_model_parallel()
    return params


def test_pp_split_validates_layer_count():
    cfg = _cfg()
    with pytest.raises(ValueError, match="multiple of pp"):
        split_gpt_params_for_pp(cfg, {}, pp=3)


@pytest.mark.slow
def test_hf_gemma_checkpoint_through_3d_pipeline():
    """The full migration story on an external model family: HF Gemma
    (GeGLU, tied head, sqrt(hidden) embedding scale, GQA) converted,
    resharded to pp x tp x dp, pipelined loss == HF-converted unsharded
    loss."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import sys

    sys.path.insert(0, ".")
    from tools.convert_hf_gemma import convert_gemma

    import dataclasses

    # kv groups (2) must divide tp (2) for the TP shard split
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=48, intermediate_size=96,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=32,
        attention_dropout=0.0)
    torch.manual_seed(9)
    hf = transformers.GemmaForCausalLM(hf_cfg).eval()
    cfg, params = convert_gemma(hf.state_dict(), hf_cfg)
    cfg = dataclasses.replace(cfg, activation_checkpointing=False)

    rng = np.random.RandomState(9)
    global_b = MB * M * DP
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))

    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    logits = GPTModel(cfg).apply({"params": params}, tokens)
    ref_loss = float(gpt_loss_fn(logits, labels))
    parallel_state.destroy_model_parallel()

    pipe_loss = _pipelined_loss(cfg, params, tokens, labels)
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-4)


@pytest.mark.slow
def test_hf_mixtral_checkpoint_through_ep_sharding():
    """MoE migration story: HF Mixtral converted, expert-sharded over
    dp=2 x ep=2 x tp=2 (E sliced over ep, expert ffn tp-split two-region,
    router/dense replicated per the grad-sync rule), first-step loss ==
    the unsharded model evaluated per (dp, ep) batch cell."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import sys

    sys.path.insert(0, ".")
    from tools.convert_hf_mixtral import convert_mixtral

    from apex_tpu.models.reshard import load_moe_checkpoint_for_ep
    from apex_tpu.transformer.moe import moe_loss_from_variables
    from apex_tpu.transformer.testing.gpt_moe import build_gpt_moe_harness

    hf_cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=32, sliding_window=None,
        attention_dropout=0.0)
    torch.manual_seed(13)
    hf = transformers.MixtralForCausalLM(hf_cfg).eval()
    cfg, params = convert_mixtral(hf.state_dict(), hf_cfg)

    DPc, EPc, TPc = 2, 2, 2
    global_b = 8  # multiple of dp*ep
    rng = np.random.RandomState(13)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))

    # per-cell oracle: each (dp, ep) cell trains on its own batch block
    # (dp-major), so the harness loss is the mean of per-block losses
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPTModel(cfg)
    cell_losses = []
    for blk in range(DPc * EPc):
        rows = slice(blk * global_b // (DPc * EPc),
                     (blk + 1) * global_b // (DPc * EPc))
        logits, mut = model.apply({"params": params}, tokens[rows],
                                  mutable=["moe_losses"])
        cell_losses.append(
            float(gpt_loss_fn(logits, labels[rows])
                  + moe_loss_from_variables(mut, cfg.moe_aux_loss_coeff,
                                            cfg.moe_z_loss_coeff)))
    ref_loss = float(np.mean(cell_losses))
    parallel_state.destroy_model_parallel()

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TPc, expert_model_parallel_size_=EPc,
        devices=jax.devices()[:8])
    loaded = load_moe_checkpoint_for_ep(cfg, params, mesh)
    init_state, step = build_gpt_moe_harness(cfg, mesh, FusedAdam(lr=1e-3))
    state = init_state(jax.random.PRNGKey(0), tokens,
                       stacked_params=loaded)
    *_, loss = step(*state, tokens, labels)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-4)


def test_moe_scan_layers_split_slices_expert_axis():
    """scan_layers MoE trees stack layers under 'layers' ([L, E, ...]
    leaves); the ep split must slice the expert axis (1), not layers."""
    from apex_tpu.models.reshard import split_moe_params_for_ep

    cfg = _cfg(num_moe_experts=4, activation="swiglu", scan_layers=True,
               ffn_hidden_size=32, moe_capacity_factor=2.0)
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPTModel(cfg)
    tok = jnp.zeros((2, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tok)["params"]
    parallel_state.destroy_model_parallel()

    stacked = split_moe_params_for_ep(cfg, params, ep=2, tp=2)
    w1 = stacked["transformer"]["layers"]["layer"]["mlp"]["experts"]["w1"]
    # [ep, tp, L, E/ep, h, 2*ffn/tp]
    assert w1.shape == (2, 2, cfg.num_layers, 2, cfg.hidden_size,
                        2 * cfg.ffn_size // 2)


@pytest.mark.slow
def test_hf_phi_checkpoint_through_3d_pipeline():
    """Biased-head migration story: HF Phi (shared-LN parallel residual,
    partial rotary, lm_head bias) converted, resharded to pp x tp x dp —
    covers the vocab-column split of the 1-D head bias and the GPTStage
    bias add."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import sys

    sys.path.insert(0, ".")
    from tools.convert_hf_phi import convert_phi

    hf_cfg = transformers.PhiConfig(
        vocab_size=128, hidden_size=48, intermediate_size=96,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=32,
        partial_rotary_factor=0.5, attention_dropout=0.0,
        resid_pdrop=0.0, embd_pdrop=0.0)
    torch.manual_seed(21)
    hf = transformers.PhiForCausalLM(hf_cfg).eval()
    with torch.no_grad():  # nonzero bias so the vocab split is exercised
        hf.lm_head.bias.copy_(torch.randn_like(hf.lm_head.bias) * 0.3)
    cfg, params = convert_phi(hf.state_dict(), hf_cfg)
    assert float(jnp.abs(params["lm_head_bias"]).sum()) > 0

    rng = np.random.RandomState(21)
    global_b = MB * M * DP
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (global_b, SEQ)))

    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    logits = GPTModel(cfg).apply({"params": params}, tokens)
    ref_loss = float(gpt_loss_fn(logits, labels))
    parallel_state.destroy_model_parallel()

    pipe_loss = _pipelined_loss(cfg, params, tokens, labels)
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-4)
