"""apex_tpu.analysis — the static HLO/jaxpr lint pass (ISSUE 9).

Three layers of evidence:

- **Seeded violations**: each rule catches a deliberately bad program
  and names the offending op/argument path in the structured finding
  (the acceptance's per-rule requirement).
- **Clean hot paths**: the real DDP fp32/int8, ZeRO, guarded, and
  serving decode steps (``analysis.targets`` — built through the same
  machinery the benches use) lint clean with every rule running.
- **Integration**: the CompileWatcher lints on compile under
  ``APEX_TPU_HLO_LINT=1`` and emits ``lint`` JSONL events; bench
  staging carries ``lint_violations``; the donation-repro ladder is
  retired into the double-donation regression here.

Everything is trace-only except the watcher integration (one tiny
compile) and the serving target (AOT ladder of 2 executables).
"""

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import analysis
from apex_tpu.analysis import (
    Finding,
    HloLintError,
    LintConfig,
    LintReport,
    RULES,
    assert_clean_hlo,
    lint_fn,
    lint_lowered,
)
from apex_tpu.analysis.targets import TARGETS


def _rules_fired(report):
    return sorted({f.rule for f in report.findings})


# ---------------------------------------------------------------------------
# seeded violations — every rule must catch its bad program and name
# the offending op/argument path
# ---------------------------------------------------------------------------

class TestSeededViolations:
    def test_no_host_callback(self):
        def poisoned(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return y * 2

        report = lint_fn(poisoned, jnp.ones((4,)))
        assert _rules_fired(report) == ["no-host-callback"]
        f = report.findings[0]
        assert "custom_call @" in f.where
        assert "callback" in f.message

    def test_no_host_callback_substring_cannot_false_positive(self):
        """The precision the substring grep lacked: 'callback' inside
        a plain op constant/name must not fire the rule."""
        from apex_tpu.analysis.lint import LintContext, run_rules

        text = ('module @jit_f {\n'
                '  func.func public @main(%arg0: tensor<4xf32>) -> '
                '(tensor<4xf32>) {\n'
                '    // callback mentioned in a comment only\n'
                '    return %arg0 : tensor<4xf32>\n  }\n}\n')
        report = run_rules(LintContext(hlo_text=text),
                           rules="no-host-callback")
        assert report.ok

    @staticmethod
    def _custom_call_module(target):
        return ('module @jit_f {\n'
                '  func.func public @main(%arg0: tensor<8x128xf32>) -> '
                '(tensor<8x128xf32>) {\n'
                f'    %0 = stablehlo.custom_call @{target}(%arg0) : '
                '(tensor<8x128xf32>) -> tensor<8x128xf32>\n'
                '    return %0 : tensor<8x128xf32>\n  }\n}\n')

    def test_pallas_targets_allowlisted(self):
        """ISSUE 14 satellite: a compiled pallas_call lowers to a
        custom_call (tpu_custom_call / mosaic_cpu / ...) that runs
        on-device — kernel-backed hot paths must lint clean."""
        from apex_tpu.analysis.lint import LintContext, run_rules
        from apex_tpu.analysis.rules import PALLAS_CUSTOM_CALL_TARGETS

        for target in sorted(PALLAS_CUSTOM_CALL_TARGETS):
            report = run_rules(
                LintContext(hlo_text=self._custom_call_module(target)),
                rules="no-host-callback")
            assert report.ok, f"{target} false-positived: " \
                f"{[str(f) for f in report.findings]}"

    def test_pallas_allowlist_env_extendable(self, monkeypatch):
        """A marker-matching target (hypothetical new Pallas runtime
        name containing 'callback') trips by default and is waivable
        via APEX_TPU_HLO_LINT_PALLAS_TARGETS without a code change."""
        from apex_tpu.analysis.lint import LintContext, run_rules

        text = self._custom_call_module("my_pallas_kernel_callback")
        report = run_rules(LintContext(hlo_text=text),
                           rules="no-host-callback")
        assert not report.ok
        monkeypatch.setenv("APEX_TPU_HLO_LINT_PALLAS_TARGETS",
                           "other_target, my_pallas_kernel_callback")
        report = run_rules(LintContext(hlo_text=text),
                           rules="no-host-callback")
        assert report.ok

    def test_real_callback_trips_despite_allowlist(self, monkeypatch):
        """The seeded proof the allowlist cannot hide a REAL host
        callback: a jax.pure_callback program still trips the rule
        even with extra pallas targets allowlisted."""
        monkeypatch.setenv("APEX_TPU_HLO_LINT_PALLAS_TARGETS",
                           "tpu_custom_call,mosaic_cpu")

        def poisoned(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return y * 2

        report = lint_fn(poisoned, jnp.ones((4,)))
        assert _rules_fired(report) == ["no-host-callback"]
        assert "custom_call @" in report.findings[0].where

    def test_no_f64(self):
        from jax.experimental import enable_x64

        with enable_x64():
            report = lint_fn(lambda x: x.astype(jnp.float64) * 2.0,
                             jnp.ones((4,), jnp.float32))
        assert "no-f64" in _rules_fired(report)
        assert "line" in report.findings[0].where

    def test_unexpected_upcast(self):
        def upcast_matmul(a, b):
            return a.astype(jnp.float32) @ b.astype(jnp.float32).T

        report = lint_fn(upcast_matmul, jnp.ones((8, 8), jnp.bfloat16),
                         jnp.ones((8, 8), jnp.bfloat16))
        assert _rules_fired(report) == ["unexpected-upcast"]
        assert "dot_general" in report.findings[0].message

    def test_bf16_matmul_and_f32_accumulate_are_clean(self):
        report = lint_fn(lambda a, b: a @ b,
                         jnp.ones((8, 8), jnp.bfloat16),
                         jnp.ones((8, 8), jnp.bfloat16))
        assert report.ok
        # accumulating in f32 via preferred_element_type is the GOOD
        # spelling and must not fire
        report = lint_fn(
            lambda a, b: jax.lax.dot(a, b,
                                     preferred_element_type=jnp.float32),
            jnp.ones((8, 8), jnp.bfloat16),
            jnp.ones((8, 8), jnp.bfloat16))
        assert report.ok

    def test_donation_coverage(self):
        def step(w, x):
            return w - 0.01 * (x.T @ (x @ w)), jnp.sum(w)

        cfg = LintConfig(donate_min_bytes=1024)
        w = jnp.ones((64, 64))
        report = lint_fn(step, w, jnp.ones((4, 64)), config=cfg)
        assert _rules_fired(report) == ["donation-coverage"]
        assert report.findings[0].where == "args/0"
        # donated -> clean
        report = lint_fn(jax.jit(step, donate_argnums=(0,)), w,
                         jnp.ones((4, 64)), config=cfg)
        assert report.ok
        # below the size threshold -> clean (not carry-state worth 2x)
        report = lint_fn(step, jnp.ones((4, 4)), jnp.ones((2, 4)),
                         config=cfg)
        assert report.ok

    def test_double_donation(self):
        shared = jnp.ones((8,))
        params = {"scale": shared}
        masters = {"master": shared.astype(jnp.float32)}  # no-op alias

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, m):
            return (jax.tree_util.tree_map(lambda t: t * 2, p),
                    jax.tree_util.tree_map(lambda t: t * 3, m))

        report = lint_fn(step, params, masters)
        assert _rules_fired(report) == ["double-donation"]
        f = report.findings[0]
        assert "args/0/scale" in f.extra["paths"]
        assert "args/1/master" in f.extra["paths"]

    def test_trace_constant_capture(self):
        baked = jnp.arange(4096, dtype=jnp.float32)
        report = lint_fn(lambda x: x + baked, jnp.ones((4096,)),
                         config=LintConfig(const_min_bytes=1024))
        assert _rules_fired(report) == ["trace-constant-capture"]
        assert "const[" in report.findings[0].where
        # passing the array as an argument is the fix
        report = lint_fn(lambda x, c: x + c, jnp.ones((4096,)), baked,
                         config=LintConfig(const_min_bytes=1024))
        assert report.ok

    @pytest.mark.multi_device
    def test_collective_consistency_cond_divergence(self, dp_mesh):
        mesh = dp_mesh(8)
        allreduce = jax.shard_map(
            lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
            in_specs=P("dp"), out_specs=P(), check_vma=False)

        def diverging(x, pred):
            return jax.lax.cond(
                pred,
                lambda v: jnp.broadcast_to(allreduce(v), v.shape),
                lambda v: v, x)

        report = lint_fn(diverging, jnp.ones((8, 4)), jnp.asarray(True))
        assert "collective-consistency" in _rules_fired(report)
        assert "cond branches" in report.findings[0].message

    @pytest.mark.multi_device
    def test_collective_consistency_while_loop(self, dp_mesh):
        mesh = dp_mesh(8)

        def body(x):
            def cond(c):
                return c[1].sum() < 10.0

            def step(c):
                i, v = c
                return i + 1, jax.lax.psum(v, "dp") * 0.5

            return jax.lax.while_loop(
                cond, step, (jnp.zeros((), jnp.int32), x))[1]

        sm = jax.shard_map(body, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"), check_vma=False)
        report = lint_fn(sm, jnp.ones((8, 4)))
        assert "collective-consistency" in _rules_fired(report)
        assert "while" in report.findings[0].message

    @pytest.mark.multi_device
    def test_overlap_serialization_chained_collectives(self, dp_mesh):
        """Bucket 2's psum artificially data-dependent on bucket 1's
        result — the serialized chain the overlapped step must never
        emit (ISSUE 10 satellite)."""
        mesh = dp_mesh(8)

        def chained(a, b):
            s1 = jax.lax.psum(a, "dp")
            s2 = jax.lax.psum(b + 0.0 * s1[0], "dp")
            return s1, s2

        sm = jax.shard_map(chained, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P(), P()), check_vma=False)
        big = jnp.ones((1 << 18,), jnp.float32)  # 1 MiB payloads
        report = lint_fn(jax.jit(sm), big, big,
                         rules="overlap-serialization")
        assert _rules_fired(report) == ["overlap-serialization"]
        f = report.findings[0]
        assert "depends on the result" in f.message
        assert f.extra["upstream"] == 1

    @pytest.mark.multi_device
    def test_overlap_serialization_independent_buckets_clean(
            self, dp_mesh):
        mesh = dp_mesh(8)

        def indep(a, b):
            return jax.lax.psum(a, "dp"), jax.lax.psum(b, "dp")

        sm = jax.shard_map(indep, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P(), P()), check_vma=False)
        big = jnp.ones((1 << 18,), jnp.float32)
        report = lint_fn(jax.jit(sm), big, big,
                         rules="overlap-serialization")
        assert report.ok, report.render()

    @pytest.mark.multi_device
    def test_overlap_serialization_threshold_gates_small_chains(
            self, dp_mesh):
        """The scalar guard-flag psum / per-block scale pmax pattern:
        small collectives neither taint nor trip; dropping
        ``overlap_min_bytes`` below them flips the verdict."""
        mesh = dp_mesh(8)

        def chained(a, b):
            s1 = jax.lax.psum(a, "dp")
            return s1, jax.lax.psum(b + 0.0 * s1[0], "dp")

        sm = jax.shard_map(chained, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P(), P()), check_vma=False)
        small = jnp.ones((64,), jnp.float32)
        assert lint_fn(jax.jit(sm), small, small,
                       rules="overlap-serialization").ok
        report = lint_fn(jax.jit(sm), small, small,
                         rules="overlap-serialization",
                         config=LintConfig(overlap_min_bytes=16))
        assert _rules_fired(report) == ["overlap-serialization"]

    @pytest.mark.multi_device
    def test_replication_blowup_output(self, dp_mesh):
        mesh = dp_mesh(8)

        @functools.partial(jax.jit,
                           out_shardings=NamedSharding(mesh, P()))
        def f(x):
            return x @ x.T

        xin = jax.device_put(jnp.ones((64, 64)),
                             NamedSharding(mesh, P("dp", None)))
        report = lint_fn(
            f, xin, config=LintConfig(replicated_min_bytes=1024))
        assert "replication-blowup" in _rules_fired(report)
        assert report.findings[0].where == "result[0]"

    @pytest.mark.multi_device
    def test_replication_blowup_constraint(self, dp_mesh):
        mesh = dp_mesh(8)

        def f(x):
            h = x @ x.T
            h = jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, P()))
            return jnp.sum(h)

        xin = jax.device_put(jnp.ones((64, 64)),
                             NamedSharding(mesh, P("dp", None)))
        report = lint_fn(
            f, xin, config=LintConfig(replicated_min_bytes=1024))
        assert "replication-blowup" in _rules_fired(report)

    @pytest.mark.multi_device
    def test_sharded_outputs_do_not_fire_replication(self, dp_mesh):
        mesh = dp_mesh(8)

        @functools.partial(
            jax.jit, out_shardings=NamedSharding(mesh, P("dp", None)))
        def f(x):
            return x * 2

        xin = jax.device_put(jnp.ones((64, 64)),
                             NamedSharding(mesh, P("dp", None)))
        report = lint_fn(
            f, xin, config=LintConfig(replicated_min_bytes=1024))
        assert report.ok


# ---------------------------------------------------------------------------
# the SPMD communication rules (ISSUE 13): seeded violations + the
# collective dataflow graph machinery
# ---------------------------------------------------------------------------

def _seeded_module(body, num_partitions=8):
    return ('module @m attributes {mhlo.num_partitions = '
            f'{num_partitions} : i32}} {{\n'
            '  func.func public @main(%arg0: tensor<256xf32>) -> '
            '(tensor<256xf32>) {\n'
            f'{body}'
            '    return %0 : tensor<256xf32>\n  }\n}\n')


def _all_reduce_line(groups, shape="2x128"):
    rows = len(groups)
    cols = len(groups[0]) if groups else 0
    payload = ", ".join("[" + ", ".join(str(d) for d in g) + "]"
                        for g in groups)
    return (f'    %0 = "stablehlo.all_reduce"(%arg0) <{{channel_handle '
            f'= #stablehlo.channel_handle<handle = 1, type = 1>, '
            f'replica_groups = dense<[{payload}]> : '
            f'tensor<{rows}x{cols}xi64>, use_global_device_ids}}> ({{\n'
            f'    ^bb0(%a: tensor<f32>, %b: tensor<f32>):\n'
            f'      stablehlo.return %a : tensor<f32>\n'
            f'    }}) : (tensor<{shape}xf32>) -> tensor<{shape}xf32>\n')


class TestShardingRules:
    def test_implicit_reshard_seeded(self):
        """A collective_permute in the HLO the source jaxpr never
        authored — the GSPMD silent-reshard shape, named by operand
        and wire bytes."""
        from apex_tpu.analysis.lint import LintContext, run_rules

        traced = jax.jit(lambda x: x * 2).trace(jnp.ones((256,)))
        text = _seeded_module(
            '    %0 = "stablehlo.collective_permute"(%arg0) '
            '<{channel_handle = #stablehlo.channel_handle<handle = 1, '
            'type = 1>, source_target_pairs = dense<[[0, 1], [1, 0]]> '
            ': tensor<2x2xi64>}> : (tensor<256xf32>) -> '
            'tensor<256xf32>\n')
        report = run_rules(
            LintContext(hlo_text=text, closed_jaxpr=traced.jaxpr),
            rules="implicit-reshard")
        assert _rules_fired(report) == ["implicit-reshard"]
        f = report.findings[0]
        assert "collective_permute" in f.where
        assert "%arg0" in f.message
        assert f.extra["nbytes"] == 256 * 4  # each device ships it once

    @pytest.mark.slow  # one XLA SPMD-partitioner compile (~50s on the
    # 8-way virtual CPU mesh); the text-seeded test above keeps the
    # rule under tier-1 and the oneproc `sharding` smoke runs this
    # end-to-end at capture time
    @pytest.mark.multi_device
    def test_implicit_reshard_fires_on_real_gspmd_program(self, dp_mesh):
        """The real thing: mismatched in/out shardings force the SPMD
        partitioner to insert a resharding collective that is only
        visible post-compile — audit_spmd catches it, and the same
        post-optimization dialect (iota replica_groups, hyphenated op
        names) parses into the collective graph."""
        from apex_tpu.analysis import sharding

        mesh = dp_mesh(8)
        resharded = functools.partial(
            jax.jit, in_shardings=NamedSharding(mesh, P("dp", None)),
            out_shardings=NamedSharding(mesh, P(None, "dp")))(
                lambda v: v * 2)
        report = sharding.audit_spmd(resharded, jnp.ones((8, 8)),
                                     name="gspmd_reshard")
        fired = _rules_fired(report)
        assert fired == ["implicit-reshard"], report.render()
        assert report.findings[0].extra["nbytes"] > 0
        assert "no corresponding collective" in report.findings[0].message
        # the post-opt dialect parses into the same graph shape (reuse
        # the compile audit_spmd already paid for)
        compiled = resharded.trace(jnp.ones((8, 8))).lower().compile()
        graph = sharding.collective_graph(compiled.as_text())
        kinds = {op.kind for op in graph.ops}
        assert kinds & {"all_to_all", "collective_permute",
                        "all_gather"}
        for op in graph.ops:
            if op.replica_groups is not None:
                assert {d for g in op.replica_groups
                        for d in g} <= set(range(8))

    @pytest.mark.multi_device
    def test_implicit_reshard_clean_when_authored(self, dp_mesh):
        """An authored ppermute matches its lowered collective_permute
        1:1 — no finding."""
        mesh = dp_mesh(8)
        sm = jax.shard_map(
            lambda v: jax.lax.ppermute(
                v, "dp", [(i, (i + 1) % 8) for i in range(8)]),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False)
        report = lint_fn(jax.jit(sm), jnp.ones((8, 4)),
                         rules="implicit-reshard")
        assert report.ok, report.render()

    def test_replica_group_consistency_coverage(self):
        """Groups covering only half the device set: the other half
        executes the op with no group to join — the deadlock shape."""
        from apex_tpu.analysis.lint import LintContext, run_rules

        text = _seeded_module(_all_reduce_line([[0, 1], [2, 3]]))
        report = run_rules(LintContext(hlo_text=text),
                           rules="replica-group-consistency")
        assert _rules_fired(report) == ["replica-group-consistency"]
        f = report.findings[0]
        assert "all_reduce" in f.where
        assert f.extra["missing"] == [4, 5, 6, 7]

    def test_replica_group_consistency_overlap_and_sizes(self):
        from apex_tpu.analysis.lint import LintContext, run_rules

        # device 1 in two groups — not a partition
        text = _seeded_module(
            _all_reduce_line([[0, 1], [1, 2], [3, 4], [5, 6], [7, 0]]),
            num_partitions=8)
        report = run_rules(LintContext(hlo_text=text),
                           rules="replica-group-consistency")
        assert any("more than one group" in f.message
                   for f in report.findings)
        # a clean partition of the full set is quiet
        text = _seeded_module(
            _all_reduce_line([[0, 1, 2, 3], [4, 5, 6, 7]]))
        report = run_rules(LintContext(hlo_text=text),
                           rules="replica-group-consistency")
        assert report.ok, report.render()

    @pytest.mark.multi_device
    def test_comm_budget(self, dp_mesh):
        """Static program wire bytes vs a declared budget; budget 0 =
        no budget declared, the rule runs and is clean."""
        mesh = dp_mesh(8)
        sm = jax.shard_map(lambda a: jax.lax.psum(a, "dp"), mesh=mesh,
                           in_specs=P(), out_specs=P(), check_vma=False)
        big = jnp.ones((1 << 18,), jnp.float32)  # 1 MiB payload
        report = lint_fn(jax.jit(sm), big, rules="comm-budget",
                         config=LintConfig(comm_budget_bytes=1024))
        assert _rules_fired(report) == ["comm-budget"]
        f = report.findings[0]
        assert "all_reduce" in f.where
        assert f.extra["nbytes"] > 1024
        assert f.extra["budget_bytes"] == 1024
        # generous budget -> clean; no budget -> runs and is clean
        assert lint_fn(jax.jit(sm), big, rules="comm-budget",
                       config=LintConfig(
                           comm_budget_bytes=1 << 30)).ok
        report = lint_fn(jax.jit(sm), big, rules="comm-budget")
        assert report.ok and report.rules_run == ("comm-budget",)

    @pytest.mark.multi_device
    def test_sharding_propagation_loss(self, dp_mesh):
        """A large intermediate pinned replicated BETWEEN two sharded
        values — named with both sharded endpoints; the same tensor
        with no sharded consumer stays quiet under this rule."""
        mesh = dp_mesh(8)

        def lossy(x):
            h = x @ x.T
            h = jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, P()))
            return jax.lax.with_sharding_constraint(
                h * 2, NamedSharding(mesh, P("dp", None)))

        xin = jax.device_put(jnp.ones((64, 64)),
                             NamedSharding(mesh, P("dp", None)))
        cfg = LintConfig(replicated_min_bytes=1024)
        report = lint_fn(lossy, xin,
                         rules="sharding-propagation-loss", config=cfg)
        assert _rules_fired(report) == ["sharding-propagation-loss"]
        f = report.findings[0]
        assert "line" in f.where
        assert f.extra["nbytes"] == 64 * 64 * 4
        assert "upstream" in f.message and "downstream" in f.message

        def sink(x):
            h = jax.lax.with_sharding_constraint(
                x @ x.T, NamedSharding(mesh, P()))
            return jnp.sum(h)  # no sharded consumer downstream

        report = lint_fn(sink, xin,
                         rules="sharding-propagation-loss", config=cfg)
        assert report.ok, report.render()


@pytest.mark.multi_device
class TestCollectiveGraph:
    """analysis.sharding — the parser + ring model the four rules and
    the bench's static_comm_bytes_per_step stand on."""

    def _measured(self, jitted, args):
        from apex_tpu.telemetry.registry import (MetricsRegistry,
                                                 use_registry)

        reg = MetricsRegistry(enabled=True)
        reg.enable()
        with use_registry(reg):
            lowered = jitted.lower(*args)
        return lowered, reg.counter_value("comm/bytes")

    def test_static_matches_measured_fp32_exact(self, dp_mesh):
        """The ddp_fp32 step: the parsed graph's ring bytes equal the
        trace-measured record_collective total EXACTLY."""
        from apex_tpu.analysis import sharding
        from apex_tpu.analysis.targets import TARGETS

        fn, args, kwargs = TARGETS["ddp_fp32"]()
        lowered, measured = self._measured(fn, args)
        static = sharding.static_comm_bytes(lowered.as_text())
        assert measured > 0
        assert static == int(round(measured))

    def test_static_matches_measured_int8_band(self, dp_mesh):
        """The tiny ddp_compressed (int8 + EF) step: the emulated-int8
        payload is recognized through the convert(i8->i32) feeding the
        psum, so static lands within the documented 25% band of the
        semantic measured bytes (exact under today's emulation)."""
        from apex_tpu.analysis import sharding
        from apex_tpu.analysis.targets import TARGETS

        fn, args, kwargs = TARGETS["ddp_int8"]()
        lowered, measured = self._measured(fn, args)
        graph = sharding.collective_graph(lowered.as_text())
        static = graph.total_wire_bytes
        assert measured > 0
        assert abs(static - measured) / measured <= 0.25
        assert any(op.emulated and op.wire_dtype == "i8"
                   for op in graph.ops)

    def test_graph_structure_tp_dp(self, dp_mesh):
        """The 2-D mesh target carries two collective families with
        DIFFERENT partitions of the same 8 devices — the graph sees
        both, with axes attached from the jaxpr."""
        from apex_tpu.analysis import build_context, sharding
        from apex_tpu.analysis.targets import TARGETS

        fn, args, kwargs = TARGETS["tp_dp"]()
        ctx = build_context(fn, *args, name="tp_dp", **kwargs)
        rows = sharding.comm_table(ctx)
        partitions = {tuple(tuple(g) for g in r["replica_groups"])
                      for r in rows if r["replica_groups"]}
        assert len(partitions) == 2  # TP groups and DP groups coexist
        axes = {a for r in rows for a in (r["axes"] or ())}
        assert axes == {"data", "model"}
        assert any(r["emulated"] for r in rows)  # int8 scoped to data
        dp_rows = [r for r in rows if r["axes"] == ["data"]]
        assert all(len(g) == 2 for r in dp_rows
                   for g in r["replica_groups"])

    def test_graph_edges_and_device_set(self, dp_mesh):
        """The scale pmax feeds the quantized psum — a dataflow edge
        in the collective graph — and the device set is the mesh."""
        from apex_tpu.analysis import sharding
        from apex_tpu.parallel import compression

        mesh = dp_mesh(8)
        sm = jax.shard_map(
            lambda g: compression.psum_compressed(g, "dp"), mesh=mesh,
            in_specs=P(), out_specs=(P(), P()), check_vma=False)
        lowered = jax.jit(sm).lower(jnp.ones((1000,), jnp.float32))
        graph = sharding.collective_graph(lowered.as_text())
        assert len(graph.ops) == 2  # scale pmax + payload psum
        assert (0, 1) in graph.edges
        assert graph.device_set() == set(range(8))

    def test_postopt_hlo_dialect_parses_text(self):
        """The post-partitioning dialect parses without a compile:
        hyphenated op names, iota replica_groups (with and without a
        transpose), and brace groups all land in the graph."""
        from apex_tpu.analysis import sharding

        text = (
            "HloModule jit_f\n"
            "ENTRY %main {\n"
            "  %p0 = f32[4,2]{1,0} parameter(0)\n"
            "  %all-gather = f32[8,2]{1,0} all-gather(f32[4,2]{1,0} "
            "%p0), channel_id=1, replica_groups=[4,2]<=[2,4]T(1,0), "
            "dimensions={0}, use_global_device_ids=true\n"
            "  %all-to-all.1 = f32[8,2]{1,0} all-to-all(f32[8,2]{1,0} "
            "%all-gather), channel_id=2, "
            "replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}\n"
            "  %collective-permute.2 = f32[8,2]{1,0} collective-permute("
            "f32[8,2]{1,0} %all-to-all.1), channel_id=3, "
            "source_target_pairs={{0,1},{1,0}}\n"
            "}\n")
        graph = sharding.collective_graph(text)
        assert [op.kind for op in graph.ops] == [
            "all_gather", "all_to_all", "collective_permute"]
        ag, a2a, cp = graph.ops
        # iota [4,2]<=[2,4]T(1,0): arange(8).reshape(2,4).T -> 4 groups
        assert ag.replica_groups == ((0, 4), (1, 5), (2, 6), (3, 7))
        assert a2a.replica_groups == ((0, 1, 2, 3), (4, 5, 6, 7))
        assert cp.source_target_pairs == ((0, 1), (1, 0))
        assert ag.channel_id == 1 and cp.channel_id == 3
        # dataflow edges follow the def-use chain
        assert (0, 1) in graph.edges and (1, 2) in graph.edges
        # ring model at each op's own group size
        assert ag.wire_bytes == (2 - 1) * 4 * 2 * 4  # (g-1)*shard
        assert a2a.wire_bytes == int(3 / 4 * 8 * 2 * 4)
        assert cp.wire_bytes == 8 * 2 * 4


class TestBenchCommGate:
    """bench.py closes the loop: static stamped next to measured, and
    a disagreement beyond the band fails the bench."""

    def test_bench_stages_static_comm(self, monkeypatch):
        import bench

        step = jax.jit(lambda x: (x * 2, jnp.sum(x)))
        bench._measure_step_cost(step, (jnp.ones((8,)),))
        # no collectives in the step: static is an honest zero
        assert bench._PENDING_MEASURED.get(
            "static_comm_bytes_per_step") == 0
        bench._PENDING_MEASURED.clear()

    def test_bench_static_comm_null_when_disabled(self, monkeypatch):
        import bench

        monkeypatch.setenv("APEX_TPU_STATIC_COMM", "0")
        step = jax.jit(lambda x: (x * 2, jnp.sum(x)))
        bench._measure_step_cost(step, (jnp.ones((8,)),))
        assert bench._PENDING_MEASURED.get(
            "static_comm_bytes_per_step") is None
        bench._PENDING_MEASURED.clear()

    def test_emit_carries_static_comm(self, capsys):
        import bench

        bench._PENDING_MEASURED["static_comm_bytes_per_step"] = 1820
        bench._emit("static_comm_probe_metric", 1.0, "x/sec", 1e9, 1,
                    1.0)
        line = json.loads(capsys.readouterr().out.strip())
        assert line["static_comm_bytes_per_step"] == 1820
        bench._PENDING_MEASURED.clear()

    @pytest.mark.multi_device
    def test_gate_fails_bench_on_disagreement(self, dp_mesh,
                                              monkeypatch):
        """A lying static model (simulated by monkeypatching the
        parser) must crash the measurement, not emit an untrusted
        number; APEX_TPU_COMM_GATE=0 restores the old behavior."""
        import bench
        from apex_tpu.analysis import sharding
        from apex_tpu.analysis.targets import TARGETS

        step, args, _ = TARGETS["ddp_fp32"]()  # instrumented psum
        monkeypatch.setattr(sharding, "static_comm_bytes",
                            lambda text: 1)
        with pytest.raises(RuntimeError,
                           match="comm-bytes disagreement"):
            bench._measure_step_cost(step, args)
        bench._PENDING_MEASURED.clear()
        monkeypatch.setenv("APEX_TPU_COMM_GATE", "0")
        bench._measure_step_cost(step, args)
        assert bench._PENDING_MEASURED[
            "static_comm_bytes_per_step"] == 1
        bench._PENDING_MEASURED.clear()

    @pytest.mark.multi_device
    def test_gate_agrees_on_real_int8_step(self, dp_mesh):
        """The in-bench gate passes on the real compressed step (the
        acceptance's ddp_compressed contract at test size)."""
        import bench
        from apex_tpu.analysis.targets import TARGETS

        fn, args, kwargs = TARGETS["ddp_int8"]()
        bench._measure_step_cost(fn, args)
        staged = dict(bench._PENDING_MEASURED)
        bench._PENDING_MEASURED.clear()
        static = staged["static_comm_bytes_per_step"]
        measured = staged["measured_comm_bytes_per_step"]
        assert static is not None and measured > 0
        assert abs(static - measured) / measured <= 0.25


# ---------------------------------------------------------------------------
# clean pass over the real hot paths — the acceptance's other half
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestCleanHotPaths:
    @pytest.mark.parametrize("name", [n for n in TARGETS
                                      if n != "serve_decode"])
    def test_training_steps_lint_clean(self, name):
        fn, args, kwargs = TARGETS[name]()
        report = assert_clean_hlo(fn, *args, name=name, **kwargs)
        # every rule ran — nothing silently skipped on the full context
        assert not report.rules_skipped
        assert set(report.rules_run) == set(RULES)

    def test_serve_decode_lints_clean(self):
        fn, args, kwargs = TARGETS["serve_decode"]()
        report = assert_clean_hlo(fn, *args, name="serve_decode",
                                  **kwargs)
        assert not report.rules_skipped


# ---------------------------------------------------------------------------
# the donation-repro retirement: the double-donate contract in
# optimizers._base / fp16_optimizer / amp_optimizer, enforced
# ---------------------------------------------------------------------------

class TestDonationContractRegression:
    def _amp_style_step(self, params, masters):
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, m):
            new_m = jax.tree_util.tree_map(
                lambda t: t - 0.1 * t, m)
            new_p = jax.tree_util.tree_map(
                lambda t: t.astype(jnp.float32), new_m)
            return new_p, new_m

        return step

    def test_astype_masters_trip_double_donation(self):
        """The exact round-2/3 bug shape: fp32 masters built with a
        no-op astype alias the already-fp32 (norm) params; donating
        both would die in Execute() — the rule catches it at trace
        time instead."""
        params = {"conv": jnp.ones((8, 8), jnp.float32),
                  "norm_scale": jnp.ones((8,), jnp.float32)}
        aliased = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params)  # no-op = alias
        step = self._amp_style_step(params, aliased)
        report = lint_fn(step, params, aliased)
        assert "double-donation" in _rules_fired(report)

    def test_master_copy_tree_masters_are_clean(self):
        """master_copy_tree (the fix) forces distinct buffers — the
        same donated step lints clean."""
        from apex_tpu.optimizers._base import master_copy_tree

        params = {"conv": jnp.ones((8, 8), jnp.float32),
                  "norm_scale": jnp.ones((8,), jnp.float32)}
        masters = master_copy_tree(params)
        step = self._amp_style_step(params, masters)
        assert_clean_hlo(step, params, masters,
                         rules="double-donation")

    def test_amp_optimizer_masters_are_alias_free(self):
        """The real amp O2 init path: AMPOptimizer's fp32 masters must
        not alias params (the contract the comments in amp_optimizer
        used to merely describe)."""
        from apex_tpu.amp.amp_optimizer import AmpOptimizer
        from apex_tpu.amp.scaler import LossScaler
        from apex_tpu.optimizers import FusedAdam

        params = {"dense": jnp.ones((16, 16), jnp.float32),
                  "scale": jnp.ones((16,), jnp.float32)}
        opt = AmpOptimizer(FusedAdam(lr=1e-3), LossScaler(128.0),
                           master_weights=True)
        state = opt.init(params)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(p, s):
            new_p, new_s = opt.step(
                jax.tree_util.tree_map(jnp.ones_like, p), s, p)
            return new_p, new_s

        assert_clean_hlo(train_step, params, state,
                         rules="double-donation")


# ---------------------------------------------------------------------------
# report / selection machinery
# ---------------------------------------------------------------------------

class TestLintMachinery:
    def test_unknown_rule_raises(self):
        with pytest.raises(ValueError, match="unknown lint rule"):
            lint_fn(lambda x: x, jnp.ones(()), rules="no-such-rule")

    def test_waive_excludes_rule(self):
        baked = jnp.arange(2048, dtype=jnp.float32)
        report = lint_fn(lambda x: x + baked, jnp.ones((2048,)),
                         waive="trace-constant-capture",
                         config=LintConfig(const_min_bytes=64))
        assert report.ok
        assert "trace-constant-capture" not in report.rules_run

    def test_assert_clean_hlo_raises_with_rule_and_where(self):
        def poisoned(x):
            return jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        with pytest.raises(HloLintError) as exc:
            assert_clean_hlo(poisoned, jnp.ones((4,)))
        msg = str(exc.value)
        assert "no-host-callback" in msg
        assert "custom_call @" in msg

    def test_lint_lowered_skips_jaxpr_rules_visibly(self):
        lowered = jax.jit(lambda x: x * 2).lower(jnp.ones((4,)))
        report = lint_lowered(lowered)
        assert report.ok
        assert "unexpected-upcast" in report.rules_skipped
        assert "collective-consistency" in report.rules_skipped
        # text-capable rules still ran
        assert "no-host-callback" in report.rules_run
        assert "trace-constant-capture" in report.rules_run

    def test_lint_lowered_const_fallback_uses_text(self):
        baked = jnp.arange(4096, dtype=jnp.float32)
        lowered = jax.jit(lambda x: x + baked).lower(jnp.ones((4096,)))
        report = lint_lowered(
            lowered, config=LintConfig(const_min_bytes=1024))
        assert _rules_fired(report) == ["trace-constant-capture"]

    def test_report_shapes(self):
        report = lint_fn(lambda x: x, jnp.ones(()))
        d = report.to_dict()
        assert d["violations"] == 0
        assert set(d["rules_run"]) == set(RULES)
        assert "0 violation(s)" in report.render()
        counts = report.counts()
        assert all(v == 0 for v in counts.values())

    def test_finding_to_dict(self):
        f = Finding("r", "msg", where="w", extra={"nbytes": 3})
        assert f.to_dict() == {"rule": "r", "severity": "error",
                               "message": "msg", "where": "w",
                               "nbytes": 3}

    def test_report_to_registry_emits_events(self, tmp_path):
        from apex_tpu.telemetry.registry import (MetricsRegistry,
                                                 use_registry)

        reg = MetricsRegistry(enabled=True)
        reg.enable(jsonl_dir=str(tmp_path))
        report = LintReport("prog", [Finding("no-f64", "bad")],
                            ("no-f64",), ())
        with use_registry(reg):
            analysis.report_to_registry(report, registry=reg)
        assert reg.counter_value("lint/violations") == 1
        events = [json.loads(line) for p in tmp_path.glob("*.jsonl")
                  for line in open(p) if line.strip()]
        lint_events = [e for e in events if e["kind"] == "lint"]
        assert any(e.get("rule") == "no-f64" for e in lint_events)
        summary = [e for e in lint_events if e.get("summary")]
        assert summary and summary[-1]["violations"] == 1


# ---------------------------------------------------------------------------
# CompileWatcher + bench integration
# ---------------------------------------------------------------------------

class TestWatcherIntegration:
    def test_watcher_lints_on_compile(self, tmp_path, monkeypatch):
        from apex_tpu.telemetry import CompileWatcher
        from apex_tpu.telemetry.registry import (MetricsRegistry,
                                                 use_registry)

        reg = MetricsRegistry(enabled=True)
        reg.enable(jsonl_dir=str(tmp_path))
        watcher = CompileWatcher(enabled=True, lint=True,
                                 registry=reg)

        baked = jnp.arange(1024, dtype=jnp.float32)
        monkeypatch.setenv("APEX_TPU_HLO_LINT_CONST_BYTES", "512")

        @jax.jit
        def step(x):
            return x + baked

        with use_registry(reg):
            watched = watcher.watch(step, "bad_step")
            watched(jnp.ones((1024,)))  # compiles -> lints
        assert "bad_step" in watcher.lint_reports
        assert watcher.lint_violation_count() >= 1
        events = [json.loads(line) for p in tmp_path.glob("*.jsonl")
                  for line in open(p) if line.strip()]
        lint_events = [e for e in events if e["kind"] == "lint"]
        assert any(e.get("rule") == "trace-constant-capture"
                   for e in lint_events)

    def test_watcher_lint_off_by_default(self):
        from apex_tpu.telemetry import CompileWatcher

        watcher = CompileWatcher(enabled=True, lint=False)
        watched = watcher.watch(jax.jit(lambda x: x * 3), "clean")
        watched(jnp.ones((4,)))
        assert watcher.lint_reports == {}

    def test_record_aot_lints_lowered(self, monkeypatch):
        from apex_tpu.telemetry import CompileWatcher

        monkeypatch.setenv("APEX_TPU_HLO_LINT_CONST_BYTES", "512")
        watcher = CompileWatcher(enabled=True, lint=True)
        baked = jnp.arange(1024, dtype=jnp.float32)
        lowered = jax.jit(lambda x: x + baked).lower(jnp.ones((1024,)))
        watcher.record_aot("aot_prog", (jnp.ones((1024,)),),
                           seconds=0.1, lowered=lowered)
        assert watcher.lint_violation_count() >= 1

    def test_bench_stages_lint_violations(self, monkeypatch):
        import bench

        monkeypatch.setenv("APEX_TPU_HLO_LINT", "1")
        step = jax.jit(lambda x: (x * 2, jnp.sum(x)))
        bench._measure_step_cost(step, (jnp.ones((8,)),))
        assert bench._PENDING_MEASURED.get("lint_violations") == 0
        bench._PENDING_MEASURED.clear()

    def test_bench_lint_null_when_unset(self, monkeypatch):
        import bench

        monkeypatch.delenv("APEX_TPU_HLO_LINT", raising=False)
        step = jax.jit(lambda x: (x * 2, jnp.sum(x)))
        bench._measure_step_cost(step, (jnp.ones((8,)),))
        assert bench._PENDING_MEASURED.get("lint_violations") is None
        bench._PENDING_MEASURED.clear()

    def test_emit_carries_lint_violations(self, capsys):
        import bench

        bench._PENDING_MEASURED["lint_violations"] = 2
        bench._emit("lint_probe_metric", 1.0, "x/sec", 1e9, 1, 1.0)
        line = json.loads(capsys.readouterr().out.strip())
        assert line["lint_violations"] == 2
        bench._PENDING_MEASURED.clear()


# ---------------------------------------------------------------------------
# tools: CLI table + telemetry_report lint kind
# ---------------------------------------------------------------------------

class TestTools:
    def test_hlo_lint_run_and_table(self):
        """The CLI machinery on a subset (the full table incl. the
        serving engine is exercised by the CLI itself and the clean-
        pass tests above)."""
        import tools.hlo_lint as hlo_lint

        reports = hlo_lint.run_lint(configs=["ddp_fp32"])
        assert list(reports) == ["ddp_fp32"]
        assert reports["ddp_fp32"].ok
        table = hlo_lint.render_table(reports)
        assert "ddp_fp32" in table
        assert "no-host-callback" in table

    def test_hlo_lint_unknown_config(self):
        import tools.hlo_lint as hlo_lint

        with pytest.raises(SystemExit, match="unknown config"):
            hlo_lint.run_lint(configs=["nope"])

    @pytest.mark.multi_device
    def test_hlo_lint_comm_table(self):
        """--comm: one trace serves both the rule report and the
        collective table; the int8 emulation is called out."""
        import tools.hlo_lint as hlo_lint

        reports, tables = hlo_lint.run_lint(configs=["ddp_int8"],
                                            comm=True)
        assert reports["ddp_int8"].ok
        rows = tables["ddp_int8"]
        assert rows and all(r["op"] == "all_reduce" for r in rows)
        assert any(r["emulated"] for r in rows)
        assert all(r["wire_bytes"] > 0 for r in rows)
        text = hlo_lint.render_comm_table(tables)
        assert "ddp_int8" in text
        assert "emulated int8" in text
        assert "axes=dp" in text

    def test_telemetry_report_renders_sharding_rules(self):
        """The lint kind is rule-name generic: the four new rules'
        findings roll up exactly like the PR-9 rules'."""
        from tools.telemetry_report import aggregate

        events = [
            ("r0", {"kind": "lint", "name": "step",
                    "rule": "implicit-reshard", "severity": "error",
                    "message": "inserted", "where": "all_to_all@line 9",
                    "nbytes": 4096}),
            ("r0", {"kind": "lint", "name": "step",
                    "rule": "comm-budget", "severity": "error",
                    "message": "over", "where": "all_reduce@line 3"}),
            ("r0", {"kind": "lint", "name": "step", "summary": True,
                    "violations": 2, "clean": False,
                    "rules_run": ["implicit-reshard", "comm-budget"],
                    "rules_skipped": []}),
        ]
        rep = aggregate(events)
        assert rep["lint"]["violations"] == 2
        assert rep["lint"]["by_rule"] == {"implicit-reshard": 1,
                                          "comm-budget": 1}
        assert rep["unknown_kinds"] == {}

    def test_telemetry_report_lint_kind(self):
        from tools.telemetry_report import aggregate

        events = [
            ("r0", {"kind": "lint", "name": "step",
                    "rule": "no-f64", "severity": "error",
                    "message": "bad", "where": "line 3"}),
            ("r0", {"kind": "lint", "name": "step", "summary": True,
                    "violations": 1, "clean": False,
                    "rules_run": ["no-f64"], "rules_skipped": []}),
            ("r0", {"kind": "lint", "name": "other", "summary": True,
                    "violations": 0, "clean": True,
                    "rules_run": ["no-f64"], "rules_skipped": []}),
        ]
        rep = aggregate(events)
        assert rep["lint"]["violations"] == 1
        assert rep["lint"]["by_rule"] == {"no-f64": 1}
        assert rep["lint"]["programs"]["step"]["clean"] is False
        assert rep["lint"]["programs"]["other"]["clean"] is True
        # and the kind is known — not counted as unknown
        assert rep["unknown_kinds"] == {}
