"""apex_tpu.analysis — the static HLO/jaxpr lint pass (ISSUE 9).

Three layers of evidence:

- **Seeded violations**: each rule catches a deliberately bad program
  and names the offending op/argument path in the structured finding
  (the acceptance's per-rule requirement).
- **Clean hot paths**: the real DDP fp32/int8, ZeRO, guarded, and
  serving decode steps (``analysis.targets`` — built through the same
  machinery the benches use) lint clean with every rule running.
- **Integration**: the CompileWatcher lints on compile under
  ``APEX_TPU_HLO_LINT=1`` and emits ``lint`` JSONL events; bench
  staging carries ``lint_violations``; the donation-repro ladder is
  retired into the double-donation regression here.

Everything is trace-only except the watcher integration (one tiny
compile) and the serving target (AOT ladder of 2 executables).
"""

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import analysis
from apex_tpu.analysis import (
    Finding,
    HloLintError,
    LintConfig,
    LintReport,
    RULES,
    assert_clean_hlo,
    lint_fn,
    lint_lowered,
)
from apex_tpu.analysis.targets import TARGETS


def _rules_fired(report):
    return sorted({f.rule for f in report.findings})


# ---------------------------------------------------------------------------
# seeded violations — every rule must catch its bad program and name
# the offending op/argument path
# ---------------------------------------------------------------------------

class TestSeededViolations:
    def test_no_host_callback(self):
        def poisoned(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return y * 2

        report = lint_fn(poisoned, jnp.ones((4,)))
        assert _rules_fired(report) == ["no-host-callback"]
        f = report.findings[0]
        assert "custom_call @" in f.where
        assert "callback" in f.message

    def test_no_host_callback_substring_cannot_false_positive(self):
        """The precision the substring grep lacked: 'callback' inside
        a plain op constant/name must not fire the rule."""
        from apex_tpu.analysis.lint import LintContext, run_rules

        text = ('module @jit_f {\n'
                '  func.func public @main(%arg0: tensor<4xf32>) -> '
                '(tensor<4xf32>) {\n'
                '    // callback mentioned in a comment only\n'
                '    return %arg0 : tensor<4xf32>\n  }\n}\n')
        report = run_rules(LintContext(hlo_text=text),
                           rules="no-host-callback")
        assert report.ok

    def test_no_f64(self):
        from jax.experimental import enable_x64

        with enable_x64():
            report = lint_fn(lambda x: x.astype(jnp.float64) * 2.0,
                             jnp.ones((4,), jnp.float32))
        assert "no-f64" in _rules_fired(report)
        assert "line" in report.findings[0].where

    def test_unexpected_upcast(self):
        def upcast_matmul(a, b):
            return a.astype(jnp.float32) @ b.astype(jnp.float32).T

        report = lint_fn(upcast_matmul, jnp.ones((8, 8), jnp.bfloat16),
                         jnp.ones((8, 8), jnp.bfloat16))
        assert _rules_fired(report) == ["unexpected-upcast"]
        assert "dot_general" in report.findings[0].message

    def test_bf16_matmul_and_f32_accumulate_are_clean(self):
        report = lint_fn(lambda a, b: a @ b,
                         jnp.ones((8, 8), jnp.bfloat16),
                         jnp.ones((8, 8), jnp.bfloat16))
        assert report.ok
        # accumulating in f32 via preferred_element_type is the GOOD
        # spelling and must not fire
        report = lint_fn(
            lambda a, b: jax.lax.dot(a, b,
                                     preferred_element_type=jnp.float32),
            jnp.ones((8, 8), jnp.bfloat16),
            jnp.ones((8, 8), jnp.bfloat16))
        assert report.ok

    def test_donation_coverage(self):
        def step(w, x):
            return w - 0.01 * (x.T @ (x @ w)), jnp.sum(w)

        cfg = LintConfig(donate_min_bytes=1024)
        w = jnp.ones((64, 64))
        report = lint_fn(step, w, jnp.ones((4, 64)), config=cfg)
        assert _rules_fired(report) == ["donation-coverage"]
        assert report.findings[0].where == "args/0"
        # donated -> clean
        report = lint_fn(jax.jit(step, donate_argnums=(0,)), w,
                         jnp.ones((4, 64)), config=cfg)
        assert report.ok
        # below the size threshold -> clean (not carry-state worth 2x)
        report = lint_fn(step, jnp.ones((4, 4)), jnp.ones((2, 4)),
                         config=cfg)
        assert report.ok

    def test_double_donation(self):
        shared = jnp.ones((8,))
        params = {"scale": shared}
        masters = {"master": shared.astype(jnp.float32)}  # no-op alias

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, m):
            return (jax.tree_util.tree_map(lambda t: t * 2, p),
                    jax.tree_util.tree_map(lambda t: t * 3, m))

        report = lint_fn(step, params, masters)
        assert _rules_fired(report) == ["double-donation"]
        f = report.findings[0]
        assert "args/0/scale" in f.extra["paths"]
        assert "args/1/master" in f.extra["paths"]

    def test_trace_constant_capture(self):
        baked = jnp.arange(4096, dtype=jnp.float32)
        report = lint_fn(lambda x: x + baked, jnp.ones((4096,)),
                         config=LintConfig(const_min_bytes=1024))
        assert _rules_fired(report) == ["trace-constant-capture"]
        assert "const[" in report.findings[0].where
        # passing the array as an argument is the fix
        report = lint_fn(lambda x, c: x + c, jnp.ones((4096,)), baked,
                         config=LintConfig(const_min_bytes=1024))
        assert report.ok

    @pytest.mark.multi_device
    def test_collective_consistency_cond_divergence(self, dp_mesh):
        mesh = dp_mesh(8)
        allreduce = jax.shard_map(
            lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
            in_specs=P("dp"), out_specs=P(), check_vma=False)

        def diverging(x, pred):
            return jax.lax.cond(
                pred,
                lambda v: jnp.broadcast_to(allreduce(v), v.shape),
                lambda v: v, x)

        report = lint_fn(diverging, jnp.ones((8, 4)), jnp.asarray(True))
        assert "collective-consistency" in _rules_fired(report)
        assert "cond branches" in report.findings[0].message

    @pytest.mark.multi_device
    def test_collective_consistency_while_loop(self, dp_mesh):
        mesh = dp_mesh(8)

        def body(x):
            def cond(c):
                return c[1].sum() < 10.0

            def step(c):
                i, v = c
                return i + 1, jax.lax.psum(v, "dp") * 0.5

            return jax.lax.while_loop(
                cond, step, (jnp.zeros((), jnp.int32), x))[1]

        sm = jax.shard_map(body, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"), check_vma=False)
        report = lint_fn(sm, jnp.ones((8, 4)))
        assert "collective-consistency" in _rules_fired(report)
        assert "while" in report.findings[0].message

    @pytest.mark.multi_device
    def test_overlap_serialization_chained_collectives(self, dp_mesh):
        """Bucket 2's psum artificially data-dependent on bucket 1's
        result — the serialized chain the overlapped step must never
        emit (ISSUE 10 satellite)."""
        mesh = dp_mesh(8)

        def chained(a, b):
            s1 = jax.lax.psum(a, "dp")
            s2 = jax.lax.psum(b + 0.0 * s1[0], "dp")
            return s1, s2

        sm = jax.shard_map(chained, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P(), P()), check_vma=False)
        big = jnp.ones((1 << 18,), jnp.float32)  # 1 MiB payloads
        report = lint_fn(jax.jit(sm), big, big,
                         rules="overlap-serialization")
        assert _rules_fired(report) == ["overlap-serialization"]
        f = report.findings[0]
        assert "depends on the result" in f.message
        assert f.extra["upstream"] == 1

    @pytest.mark.multi_device
    def test_overlap_serialization_independent_buckets_clean(
            self, dp_mesh):
        mesh = dp_mesh(8)

        def indep(a, b):
            return jax.lax.psum(a, "dp"), jax.lax.psum(b, "dp")

        sm = jax.shard_map(indep, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P(), P()), check_vma=False)
        big = jnp.ones((1 << 18,), jnp.float32)
        report = lint_fn(jax.jit(sm), big, big,
                         rules="overlap-serialization")
        assert report.ok, report.render()

    @pytest.mark.multi_device
    def test_overlap_serialization_threshold_gates_small_chains(
            self, dp_mesh):
        """The scalar guard-flag psum / per-block scale pmax pattern:
        small collectives neither taint nor trip; dropping
        ``overlap_min_bytes`` below them flips the verdict."""
        mesh = dp_mesh(8)

        def chained(a, b):
            s1 = jax.lax.psum(a, "dp")
            return s1, jax.lax.psum(b + 0.0 * s1[0], "dp")

        sm = jax.shard_map(chained, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P(), P()), check_vma=False)
        small = jnp.ones((64,), jnp.float32)
        assert lint_fn(jax.jit(sm), small, small,
                       rules="overlap-serialization").ok
        report = lint_fn(jax.jit(sm), small, small,
                         rules="overlap-serialization",
                         config=LintConfig(overlap_min_bytes=16))
        assert _rules_fired(report) == ["overlap-serialization"]

    @pytest.mark.multi_device
    def test_replication_blowup_output(self, dp_mesh):
        mesh = dp_mesh(8)

        @functools.partial(jax.jit,
                           out_shardings=NamedSharding(mesh, P()))
        def f(x):
            return x @ x.T

        xin = jax.device_put(jnp.ones((64, 64)),
                             NamedSharding(mesh, P("dp", None)))
        report = lint_fn(
            f, xin, config=LintConfig(replicated_min_bytes=1024))
        assert "replication-blowup" in _rules_fired(report)
        assert report.findings[0].where == "result[0]"

    @pytest.mark.multi_device
    def test_replication_blowup_constraint(self, dp_mesh):
        mesh = dp_mesh(8)

        def f(x):
            h = x @ x.T
            h = jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, P()))
            return jnp.sum(h)

        xin = jax.device_put(jnp.ones((64, 64)),
                             NamedSharding(mesh, P("dp", None)))
        report = lint_fn(
            f, xin, config=LintConfig(replicated_min_bytes=1024))
        assert "replication-blowup" in _rules_fired(report)

    @pytest.mark.multi_device
    def test_sharded_outputs_do_not_fire_replication(self, dp_mesh):
        mesh = dp_mesh(8)

        @functools.partial(
            jax.jit, out_shardings=NamedSharding(mesh, P("dp", None)))
        def f(x):
            return x * 2

        xin = jax.device_put(jnp.ones((64, 64)),
                             NamedSharding(mesh, P("dp", None)))
        report = lint_fn(
            f, xin, config=LintConfig(replicated_min_bytes=1024))
        assert report.ok


# ---------------------------------------------------------------------------
# clean pass over the real hot paths — the acceptance's other half
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestCleanHotPaths:
    @pytest.mark.parametrize("name", [n for n in TARGETS
                                      if n != "serve_decode"])
    def test_training_steps_lint_clean(self, name):
        fn, args, kwargs = TARGETS[name]()
        report = assert_clean_hlo(fn, *args, name=name, **kwargs)
        # every rule ran — nothing silently skipped on the full context
        assert not report.rules_skipped
        assert set(report.rules_run) == set(RULES)

    def test_serve_decode_lints_clean(self):
        fn, args, kwargs = TARGETS["serve_decode"]()
        report = assert_clean_hlo(fn, *args, name="serve_decode",
                                  **kwargs)
        assert not report.rules_skipped


# ---------------------------------------------------------------------------
# the donation-repro retirement: the double-donate contract in
# optimizers._base / fp16_optimizer / amp_optimizer, enforced
# ---------------------------------------------------------------------------

class TestDonationContractRegression:
    def _amp_style_step(self, params, masters):
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, m):
            new_m = jax.tree_util.tree_map(
                lambda t: t - 0.1 * t, m)
            new_p = jax.tree_util.tree_map(
                lambda t: t.astype(jnp.float32), new_m)
            return new_p, new_m

        return step

    def test_astype_masters_trip_double_donation(self):
        """The exact round-2/3 bug shape: fp32 masters built with a
        no-op astype alias the already-fp32 (norm) params; donating
        both would die in Execute() — the rule catches it at trace
        time instead."""
        params = {"conv": jnp.ones((8, 8), jnp.float32),
                  "norm_scale": jnp.ones((8,), jnp.float32)}
        aliased = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params)  # no-op = alias
        step = self._amp_style_step(params, aliased)
        report = lint_fn(step, params, aliased)
        assert "double-donation" in _rules_fired(report)

    def test_master_copy_tree_masters_are_clean(self):
        """master_copy_tree (the fix) forces distinct buffers — the
        same donated step lints clean."""
        from apex_tpu.optimizers._base import master_copy_tree

        params = {"conv": jnp.ones((8, 8), jnp.float32),
                  "norm_scale": jnp.ones((8,), jnp.float32)}
        masters = master_copy_tree(params)
        step = self._amp_style_step(params, masters)
        assert_clean_hlo(step, params, masters,
                         rules="double-donation")

    def test_amp_optimizer_masters_are_alias_free(self):
        """The real amp O2 init path: AMPOptimizer's fp32 masters must
        not alias params (the contract the comments in amp_optimizer
        used to merely describe)."""
        from apex_tpu.amp.amp_optimizer import AmpOptimizer
        from apex_tpu.amp.scaler import LossScaler
        from apex_tpu.optimizers import FusedAdam

        params = {"dense": jnp.ones((16, 16), jnp.float32),
                  "scale": jnp.ones((16,), jnp.float32)}
        opt = AmpOptimizer(FusedAdam(lr=1e-3), LossScaler(128.0),
                           master_weights=True)
        state = opt.init(params)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(p, s):
            new_p, new_s = opt.step(
                jax.tree_util.tree_map(jnp.ones_like, p), s, p)
            return new_p, new_s

        assert_clean_hlo(train_step, params, state,
                         rules="double-donation")


# ---------------------------------------------------------------------------
# report / selection machinery
# ---------------------------------------------------------------------------

class TestLintMachinery:
    def test_unknown_rule_raises(self):
        with pytest.raises(ValueError, match="unknown lint rule"):
            lint_fn(lambda x: x, jnp.ones(()), rules="no-such-rule")

    def test_waive_excludes_rule(self):
        baked = jnp.arange(2048, dtype=jnp.float32)
        report = lint_fn(lambda x: x + baked, jnp.ones((2048,)),
                         waive="trace-constant-capture",
                         config=LintConfig(const_min_bytes=64))
        assert report.ok
        assert "trace-constant-capture" not in report.rules_run

    def test_assert_clean_hlo_raises_with_rule_and_where(self):
        def poisoned(x):
            return jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        with pytest.raises(HloLintError) as exc:
            assert_clean_hlo(poisoned, jnp.ones((4,)))
        msg = str(exc.value)
        assert "no-host-callback" in msg
        assert "custom_call @" in msg

    def test_lint_lowered_skips_jaxpr_rules_visibly(self):
        lowered = jax.jit(lambda x: x * 2).lower(jnp.ones((4,)))
        report = lint_lowered(lowered)
        assert report.ok
        assert "unexpected-upcast" in report.rules_skipped
        assert "collective-consistency" in report.rules_skipped
        # text-capable rules still ran
        assert "no-host-callback" in report.rules_run
        assert "trace-constant-capture" in report.rules_run

    def test_lint_lowered_const_fallback_uses_text(self):
        baked = jnp.arange(4096, dtype=jnp.float32)
        lowered = jax.jit(lambda x: x + baked).lower(jnp.ones((4096,)))
        report = lint_lowered(
            lowered, config=LintConfig(const_min_bytes=1024))
        assert _rules_fired(report) == ["trace-constant-capture"]

    def test_report_shapes(self):
        report = lint_fn(lambda x: x, jnp.ones(()))
        d = report.to_dict()
        assert d["violations"] == 0
        assert set(d["rules_run"]) == set(RULES)
        assert "0 violation(s)" in report.render()
        counts = report.counts()
        assert all(v == 0 for v in counts.values())

    def test_finding_to_dict(self):
        f = Finding("r", "msg", where="w", extra={"nbytes": 3})
        assert f.to_dict() == {"rule": "r", "severity": "error",
                               "message": "msg", "where": "w",
                               "nbytes": 3}

    def test_report_to_registry_emits_events(self, tmp_path):
        from apex_tpu.telemetry.registry import (MetricsRegistry,
                                                 use_registry)

        reg = MetricsRegistry(enabled=True)
        reg.enable(jsonl_dir=str(tmp_path))
        report = LintReport("prog", [Finding("no-f64", "bad")],
                            ("no-f64",), ())
        with use_registry(reg):
            analysis.report_to_registry(report, registry=reg)
        assert reg.counter_value("lint/violations") == 1
        events = [json.loads(line) for p in tmp_path.glob("*.jsonl")
                  for line in open(p) if line.strip()]
        lint_events = [e for e in events if e["kind"] == "lint"]
        assert any(e.get("rule") == "no-f64" for e in lint_events)
        summary = [e for e in lint_events if e.get("summary")]
        assert summary and summary[-1]["violations"] == 1


# ---------------------------------------------------------------------------
# CompileWatcher + bench integration
# ---------------------------------------------------------------------------

class TestWatcherIntegration:
    def test_watcher_lints_on_compile(self, tmp_path, monkeypatch):
        from apex_tpu.telemetry import CompileWatcher
        from apex_tpu.telemetry.registry import (MetricsRegistry,
                                                 use_registry)

        reg = MetricsRegistry(enabled=True)
        reg.enable(jsonl_dir=str(tmp_path))
        watcher = CompileWatcher(enabled=True, lint=True,
                                 registry=reg)

        baked = jnp.arange(1024, dtype=jnp.float32)
        monkeypatch.setenv("APEX_TPU_HLO_LINT_CONST_BYTES", "512")

        @jax.jit
        def step(x):
            return x + baked

        with use_registry(reg):
            watched = watcher.watch(step, "bad_step")
            watched(jnp.ones((1024,)))  # compiles -> lints
        assert "bad_step" in watcher.lint_reports
        assert watcher.lint_violation_count() >= 1
        events = [json.loads(line) for p in tmp_path.glob("*.jsonl")
                  for line in open(p) if line.strip()]
        lint_events = [e for e in events if e["kind"] == "lint"]
        assert any(e.get("rule") == "trace-constant-capture"
                   for e in lint_events)

    def test_watcher_lint_off_by_default(self):
        from apex_tpu.telemetry import CompileWatcher

        watcher = CompileWatcher(enabled=True, lint=False)
        watched = watcher.watch(jax.jit(lambda x: x * 3), "clean")
        watched(jnp.ones((4,)))
        assert watcher.lint_reports == {}

    def test_record_aot_lints_lowered(self, monkeypatch):
        from apex_tpu.telemetry import CompileWatcher

        monkeypatch.setenv("APEX_TPU_HLO_LINT_CONST_BYTES", "512")
        watcher = CompileWatcher(enabled=True, lint=True)
        baked = jnp.arange(1024, dtype=jnp.float32)
        lowered = jax.jit(lambda x: x + baked).lower(jnp.ones((1024,)))
        watcher.record_aot("aot_prog", (jnp.ones((1024,)),),
                           seconds=0.1, lowered=lowered)
        assert watcher.lint_violation_count() >= 1

    def test_bench_stages_lint_violations(self, monkeypatch):
        import bench

        monkeypatch.setenv("APEX_TPU_HLO_LINT", "1")
        step = jax.jit(lambda x: (x * 2, jnp.sum(x)))
        bench._measure_step_cost(step, (jnp.ones((8,)),))
        assert bench._PENDING_MEASURED.get("lint_violations") == 0
        bench._PENDING_MEASURED.clear()

    def test_bench_lint_null_when_unset(self, monkeypatch):
        import bench

        monkeypatch.delenv("APEX_TPU_HLO_LINT", raising=False)
        step = jax.jit(lambda x: (x * 2, jnp.sum(x)))
        bench._measure_step_cost(step, (jnp.ones((8,)),))
        assert bench._PENDING_MEASURED.get("lint_violations") is None
        bench._PENDING_MEASURED.clear()

    def test_emit_carries_lint_violations(self, capsys):
        import bench

        bench._PENDING_MEASURED["lint_violations"] = 2
        bench._emit("lint_probe_metric", 1.0, "x/sec", 1e9, 1, 1.0)
        line = json.loads(capsys.readouterr().out.strip())
        assert line["lint_violations"] == 2
        bench._PENDING_MEASURED.clear()


# ---------------------------------------------------------------------------
# tools: CLI table + telemetry_report lint kind
# ---------------------------------------------------------------------------

class TestTools:
    def test_hlo_lint_run_and_table(self):
        """The CLI machinery on a subset (the full table incl. the
        serving engine is exercised by the CLI itself and the clean-
        pass tests above)."""
        import tools.hlo_lint as hlo_lint

        reports = hlo_lint.run_lint(configs=["ddp_fp32"])
        assert list(reports) == ["ddp_fp32"]
        assert reports["ddp_fp32"].ok
        table = hlo_lint.render_table(reports)
        assert "ddp_fp32" in table
        assert "no-host-callback" in table

    def test_hlo_lint_unknown_config(self):
        import tools.hlo_lint as hlo_lint

        with pytest.raises(SystemExit, match="unknown config"):
            hlo_lint.run_lint(configs=["nope"])

    def test_telemetry_report_lint_kind(self):
        from tools.telemetry_report import aggregate

        events = [
            ("r0", {"kind": "lint", "name": "step",
                    "rule": "no-f64", "severity": "error",
                    "message": "bad", "where": "line 3"}),
            ("r0", {"kind": "lint", "name": "step", "summary": True,
                    "violations": 1, "clean": False,
                    "rules_run": ["no-f64"], "rules_skipped": []}),
            ("r0", {"kind": "lint", "name": "other", "summary": True,
                    "violations": 0, "clean": True,
                    "rules_run": ["no-f64"], "rules_skipped": []}),
        ]
        rep = aggregate(events)
        assert rep["lint"]["violations"] == 1
        assert rep["lint"]["by_rule"] == {"no-f64": 1}
        assert rep["lint"]["programs"]["step"]["clean"] is False
        assert rep["lint"]["programs"]["other"]["clean"] is True
        # and the kind is known — not counted as unknown
        assert rep["unknown_kinds"] == {}
