"""Live monitoring control plane (ISSUE 20): rolling-window alert
engine, OpenMetrics exposition, online straggler/bubble attribution,
and the zero-overhead-off contract.

Covers:

- ``AlertRule`` validation and the stock ``default_rules()`` table;
- every rule kind end to end through ``Monitor.poll()``: gauge
  above/below, counter increase/rate over the snapshot window,
  histogram p99, EWMA z-score span anomalies, fleet replica health,
  supervisor recovery — including ``for_polls``/``resolve_polls``
  hysteresis and the firing -> resolved ``alert`` event transitions
  (with evidence, the ``monitor/alerts_firing`` gauge, and the
  ``monitor/alerts_fired`` counter);
- ``JsonlTailer`` incremental cross-rank intake (byte offsets,
  complete-lines-only, own-rank skip);
- OpenMetrics: renderer output round-trips the strict conformance
  parser, counter ``_total`` / summary-quantile discipline, firing
  alert samples, and the parser's rejection cases; the stdlib scrape
  endpoint serves it over HTTP on an ephemeral port;
- zero-overhead-off: a Monitor on a disabled registry is fully inert
  (no tap, no thread, no socket, no events) and the lowered HLO of a
  guarded train step is byte-identical with the monitor on or off;
- the chaos acceptance (tier-1, stub fleet — no compiles): a replica
  kill fires ``replica_health`` and the respawn resolves it; a REAL
  jitted ``guarded_update`` fed NaN gradients fires ``guard_skips``
  through ``check_guard`` and a clean step resolves it, with
  ``alerts_firing()`` back to 0;
- ``PipelineAttributor``: exposure-difference straggler naming on
  synthetic tick spans, the pp == 1 / uniform-load abstain cases,
  measured bubble fraction, per-axis comm exposure — plus (slow) the
  real ``build_pipeline_step(..., straggler=)`` trace naming the
  delayed stage through the Monitor's live tap;
- ``tools/monitor_dash.py --once`` renders a captured dir with the
  firing count as exit code, and ``tools/telemetry_report.py`` folds
  ``alert``/``monitor`` events into the per-rule rollup.
"""

import io
import json
import os
import sys
import types
import urllib.request

import numpy as np
import pytest

from apex_tpu.telemetry import MetricsRegistry, use_registry
from apex_tpu.telemetry.attribution import PipelineAttributor
from apex_tpu.telemetry.monitor import (
    AlertRule,
    JsonlTailer,
    Monitor,
    default_rules,
    parse_openmetrics,
    render_openmetrics,
)

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import monitor_dash  # noqa: E402
import telemetry_report  # noqa: E402


def _reg(tmp_path=None):
    return MetricsRegistry(
        enabled=True,
        jsonl_dir=str(tmp_path) if tmp_path is not None else None)


def _rule(**kw):
    kw.setdefault("name", "r")
    return AlertRule(kw.pop("name"), kw.pop("kind"), **kw)


def _capture_alerts(reg):
    rows = []
    reg.add_event_tap(
        lambda rec: rows.append(rec) if rec.get("kind") == "alert"
        else None)
    return rows


# ---------------------------------------------------------------------------
# AlertRule + default table
# ---------------------------------------------------------------------------


class TestAlertRule:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown rule kind"):
            _rule(kind="nope", metric="x", threshold=1.0)

    def test_unknown_severity_rejected(self):
        with pytest.raises(ValueError, match="unknown severity"):
            _rule(kind="gauge_above", metric="x", threshold=1.0,
                  severity="catastrophic")

    def test_metric_and_threshold_required(self):
        with pytest.raises(ValueError, match="needs a metric"):
            _rule(kind="gauge_above", threshold=1.0)
        with pytest.raises(ValueError, match="needs a threshold"):
            _rule(kind="gauge_above", metric="x")

    def test_event_driven_kinds_take_no_metric(self):
        assert _rule(kind="replica_health").metric is None
        assert _rule(kind="recovery").threshold is None

    def test_default_rules_cover_the_contract(self):
        names = {r.name for r in default_rules()}
        assert {"ttft_slo_interactive", "guard_skips", "pending_depth",
                "recompiles", "hbm_headroom", "goodput_ratio",
                "step_time_anomaly", "replica_health",
                "recovery_escalation"} <= names

    def test_duplicate_rule_names_rejected(self):
        rules = [_rule(name="dup", kind="recovery"),
                 _rule(name="dup", kind="replica_health")]
        with pytest.raises(ValueError, match="duplicate"):
            Monitor(_reg(), rules=rules)

    def test_describe_round_trips_the_knobs(self):
        d = _rule(name="x", kind="gauge_above", metric="m",
                  threshold=2.0, for_polls=3, severity="page").describe()
        assert d["for_polls"] == 3 and d["severity"] == "page"


# ---------------------------------------------------------------------------
# rule kinds through poll()
# ---------------------------------------------------------------------------


class TestRuleKinds:
    def test_gauge_above_fires_and_resolves(self):
        reg = _reg()
        events = _capture_alerts(reg)
        mon = Monitor(reg, rules=[_rule(
            name="g", kind="gauge_above", metric="q/depth",
            threshold=5.0)])
        reg.gauge("q/depth").set(3.0)
        assert mon.poll()["firing"] == 0
        reg.gauge("q/depth").set(9.0)
        res = mon.poll()
        assert res["firing"] == 1
        row = res["alerts"][0]
        assert row["firing"] and row["value"] == 9.0
        assert row["evidence"] == {"q/depth": 9.0}
        reg.gauge("q/depth").set(1.0)
        assert mon.poll()["firing"] == 0
        states = [e["state"] for e in events]
        assert states == ["firing", "resolved"]
        assert events[1]["duration_s"] is not None
        assert reg.gauge("monitor/alerts_firing").value == 0.0
        assert reg.counter("monitor/alerts_fired").value == 1.0
        mon.close()

    def test_gauge_pattern_matches_many_names(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(
            name="g", kind="gauge_above", metric="*/pending_depth",
            threshold=2.0)])
        reg.gauge("fleet/pending_depth").set(1.0)
        reg.gauge("serve/pending_depth").set(7.0)
        row = mon.poll()["alerts"][0]
        assert row["firing"]
        assert row["evidence"] == {"serve/pending_depth": 7.0}
        mon.close()

    def test_gauge_below_floor(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(
            name="hbm", kind="gauge_below",
            metric="memory/hbm_headroom", threshold=0.05)])
        reg.gauge("memory/hbm_headroom").set(0.5)
        assert mon.poll()["firing"] == 0
        reg.gauge("memory/hbm_headroom").set(0.01)
        assert mon.poll()["firing"] == 1
        mon.close()

    def test_for_polls_and_resolve_polls_hysteresis(self):
        reg = _reg()
        events = _capture_alerts(reg)
        mon = Monitor(reg, rules=[_rule(
            name="g", kind="gauge_above", metric="d", threshold=0.0,
            for_polls=3, resolve_polls=2)])
        reg.gauge("d").set(1.0)
        assert mon.poll()["firing"] == 0    # breach 1
        assert mon.poll()["firing"] == 0    # breach 2
        assert mon.poll()["firing"] == 1    # breach 3 -> fires
        reg.gauge("d").set(-1.0)
        assert mon.poll()["firing"] == 1    # ok 1 — still firing
        assert mon.poll()["firing"] == 0    # ok 2 -> resolves
        reg.gauge("d").set(1.0)
        assert mon.poll()["firing"] == 0    # streak restarted
        assert [e["state"] for e in events] == ["firing", "resolved"]
        mon.close()

    def test_counter_increase_over_window(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(
            name="c", kind="counter_increase", metric="compile/count",
            threshold=0.0, window_s=60.0)])
        reg.counter("compile/count").inc()
        # first poll: no window base yet — never fires
        assert mon.poll()["firing"] == 0
        assert mon.poll()["firing"] == 0    # no growth since base
        reg.counter("compile/count").inc(2.0)
        res = mon.poll()
        assert res["firing"] == 1
        assert res["alerts"][0]["evidence"]["compile/count"][
            "delta"] == 2.0
        mon.close()

    def test_counter_rate_above(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(
            name="c", kind="counter_rate_above", metric="tok",
            threshold=1e9, window_s=60.0)])
        reg.counter("tok").inc()
        mon.poll()
        reg.counter("tok").inc()
        assert mon.poll()["firing"] == 0    # rate nowhere near 1e9/s
        mon.close()

    def test_hist_p99_above(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(
            name="slo", kind="hist_p99_above",
            metric="fleet/ttft_*", threshold=100.0)])
        for _ in range(20):
            reg.histogram("fleet/ttft_interactive").observe(10.0)
        assert mon.poll()["firing"] == 0
        for _ in range(20):
            reg.histogram("fleet/ttft_interactive").observe(500.0)
        row = mon.poll()["alerts"][0]
        assert row["firing"]
        assert row["evidence"]["fleet/ttft_interactive"]["p99"] > 100.0
        mon.close()

    def test_ewma_z_span_anomaly(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(
            name="z", kind="ewma_z", metric="train/step",
            threshold=4.0)], ewma_warmup=8)
        for i in range(12):                 # warmup with some variance
            reg.event("span", "train/step",
                      duration_s=1.0 + 0.01 * (i % 2))
        assert mon.poll()["firing"] == 0
        reg.event("span", "train/step", duration_s=30.0)
        res = mon.poll()
        assert res["firing"] == 1
        assert abs(res["alerts"][0]["value"]) > 4.0
        assert res["alerts"][0]["evidence"]["value_s"] == 30.0
        # anomaly is consume-once: the next poll resolves
        assert mon.poll()["firing"] == 0
        mon.close()

    def test_replica_health_from_events_and_gauges(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(name="rh",
                                        kind="replica_health")])
        reg.event("fleet", "replica_state", replica=0, old="serving",
                  new="quarantined", reason="kill")
        row = mon.poll()["alerts"][0]
        assert row["firing"]
        assert row["evidence"]["replicas"] == {"0": "quarantined"}
        reg.event("fleet", "replica_state", replica=0,
                  old="respawning", new="serving", reason="respawn")
        assert mon.poll()["firing"] == 0
        # the serving < expected gauge path fires without any event
        reg.gauge("fleet/replicas_serving").set(1.0)
        reg.gauge("fleet/replicas_expected").set(2.0)
        assert mon.poll()["firing"] == 1
        reg.gauge("fleet/replicas_serving").set(2.0)
        assert mon.poll()["firing"] == 0
        mon.close()

    def test_recovery_rule_tracks_supervisor_window(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(name="rec",
                                        kind="recovery")])
        reg.event("recovery", "failure", cls="numerics", step=7)
        row = mon.poll()["alerts"][0]
        assert row["firing"] and row["evidence"]["cls"] == "numerics"
        reg.event("recovery", "recovered", cls="numerics")
        assert mon.poll()["firing"] == 0
        # the gauge path: in_recovery == 1 fires without an event
        reg.gauge("recovery/in_recovery").set(1.0)
        assert mon.poll()["firing"] == 1
        reg.gauge("recovery/in_recovery").set(0.0)
        assert mon.poll()["firing"] == 0
        mon.close()

    def test_own_alert_events_never_feed_back(self):
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(
            name="g", kind="gauge_above", metric="d", threshold=0.0)])
        reg.gauge("d").set(1.0)
        for _ in range(5):
            mon.poll()                      # alert + monitor events
        assert mon.alerts()[0]["fired_count"] == 1
        mon.close()


# ---------------------------------------------------------------------------
# cross-rank JSONL tailing
# ---------------------------------------------------------------------------


class TestJsonlTailer:
    def test_incremental_complete_lines_only(self, tmp_path):
        p = tmp_path / "telemetry-rank7.jsonl"
        t = JsonlTailer(str(tmp_path))
        assert t.poll() == []
        with open(p, "w") as f:
            f.write(json.dumps({"kind": "fleet", "name": "a"}) + "\n")
            f.write('{"kind": "fleet", "na')   # torn write
        recs = t.poll()
        assert [r["name"] for r in recs] == ["a"]
        with open(p, "a") as f:
            f.write('me": "b"}\n')              # completed now
        assert [r["name"] for r in t.poll()] == ["b"]
        assert t.poll() == []                   # nothing new

    def test_skip_files_and_garbage_lines(self, tmp_path):
        (tmp_path / "telemetry-rank0.jsonl").write_text(
            '{"kind": "x", "name": "mine"}\n')
        (tmp_path / "telemetry-rank1.jsonl").write_text(
            'not json\n{"kind": "x", "name": "theirs"}\n[1,2]\n')
        t = JsonlTailer(str(tmp_path),
                        skip_files=("telemetry-rank0.jsonl",))
        assert [r["name"] for r in t.poll()] == ["theirs"]

    def test_monitor_tails_other_ranks(self, tmp_path):
        rank_dir = tmp_path / "tel"
        rank_dir.mkdir()
        reg = _reg()
        mon = Monitor(reg, rules=[_rule(name="rh",
                                        kind="replica_health")],
                      tail_dir=str(rank_dir))
        (rank_dir / "telemetry-rank3.jsonl").write_text(json.dumps(
            {"kind": "fleet", "name": "replica_state", "replica": 2,
             "new": "respawning"}) + "\n")
        assert mon.poll()["firing"] == 1    # remote rank's kill seen
        mon.close()


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------


class TestOpenMetrics:
    def _snapshot(self):
        reg = _reg()
        reg.counter("fleet/submitted").inc(3.0)
        reg.gauge("memory/hbm_headroom").set(0.42)
        for v in (1.0, 2.0, 3.0):
            reg.histogram("fleet/ttft_interactive").observe(v)
        return reg.snapshot()

    def test_render_parse_round_trip(self):
        text = render_openmetrics(self._snapshot())
        fams = parse_openmetrics(text)
        c = fams["apex_tpu_fleet_submitted"]
        assert c["type"] == "counter"
        assert c["samples"][0][0] == "apex_tpu_fleet_submitted_total"
        g = fams["apex_tpu_memory_hbm_headroom"]
        assert g["samples"][0][2] == "0.42"
        s = fams["apex_tpu_fleet_ttft_interactive"]
        assert s["type"] == "summary"
        quantiles = {lab.get("quantile") for (_, lab, _) in
                     s["samples"] if lab}
        assert quantiles == {"0.5", "0.99"}
        names = {n for (n, _, _) in s["samples"]}
        assert "apex_tpu_fleet_ttft_interactive_count" in names
        assert "apex_tpu_fleet_ttft_interactive_sum" in names

    def test_firing_alerts_render_as_labeled_samples(self):
        rows = [{"rule": "guard_skips", "severity": "page",
                 "firing": True},
                {"rule": "quiet", "severity": "info", "firing": False}]
        text = render_openmetrics(self._snapshot(), alerts=rows)
        fams = parse_openmetrics(text)
        samples = fams["apex_tpu_monitor_alert"]["samples"]
        assert len(samples) == 1
        assert samples[0][1] == {"rule": "guard_skips",
                                 "severity": "page"}

    def test_nan_and_inf_values_render_legally(self):
        reg = _reg()
        reg.gauge("weird").set(float("nan"))
        reg.gauge("hot").set(float("inf"))
        fams = parse_openmetrics(render_openmetrics(reg.snapshot()))
        vals = {fams[k]["samples"][0][2] for k in
                ("apex_tpu_weird", "apex_tpu_hot")}
        assert vals == {"NaN", "+Inf"}

    @pytest.mark.parametrize("text,msg", [
        ("apex_tpu_x 1\n# EOF\n", "no preceding TYPE"),
        ("# TYPE apex_tpu_x counter\napex_tpu_x 1\n# EOF\n",
         "_total"),
        ("# TYPE apex_tpu_x gauge\napex_tpu_x_total 1\n# EOF\n",
         "must not carry suffix"),
        ("# TYPE apex_tpu_x gauge\napex_tpu_x 1\n", "EOF"),
        ("# TYPE apex_tpu_x gauge\n# TYPE apex_tpu_x gauge\n# EOF\n",
         "duplicate TYPE"),
        ("# TYPE apex_tpu_x gauge\napex_tpu_x 1e\n# EOF\n",
         "malformed value"),
        ('# TYPE apex_tpu_x gauge\napex_tpu_x{a=b} 1\n# EOF\n',
         "malformed"),
        ("# TYPE apex_tpu_x summary\napex_tpu_x 1\n# EOF\n",
         "quantile"),
    ])
    def test_parser_rejects_nonconformant(self, text, msg):
        with pytest.raises(ValueError, match=msg):
            parse_openmetrics(text)

    def test_scrape_endpoint_serves_the_exposition(self):
        reg = _reg()
        reg.gauge("memory/hbm_headroom").set(0.3)
        mon = Monitor(reg, rules=default_rules())
        try:
            srv = mon.serve(port=0)
            assert srv is not None and mon.bound_port
            url = f"http://127.0.0.1:{mon.bound_port}/metrics"
            with urllib.request.urlopen(url, timeout=10) as resp:
                assert resp.status == 200
                assert "openmetrics-text" in resp.headers[
                    "Content-Type"]
                body = resp.read().decode("utf-8")
            fams = parse_openmetrics(body)
            assert "apex_tpu_memory_hbm_headroom" in fams
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{mon.bound_port}/nope",
                    timeout=10)
        finally:
            mon.close()
        assert mon.bound_port is None

    def test_no_port_configured_means_no_server(self, monkeypatch):
        monkeypatch.delenv("APEX_TPU_MONITOR_PORT", raising=False)
        mon = Monitor(_reg(), rules=[])
        assert mon.serve() is None
        mon.close()


# ---------------------------------------------------------------------------
# zero-overhead-off
# ---------------------------------------------------------------------------


class TestZeroOverheadOff:
    def test_disabled_monitor_is_fully_inert(self):
        reg = MetricsRegistry()             # disabled
        events = []
        orig = reg.event

        def counting(kind, name, **fields):
            events.append(kind)
            return orig(kind, name, **fields)

        reg.event = counting
        mon = Monitor(reg, rules=default_rules())
        assert not mon.enabled
        assert mon.poll() is None
        assert mon.render_openmetrics() == "# EOF\n"
        assert mon.serve(port=0) is None
        assert mon.start() is mon and mon._thread is None
        mon.close()
        assert events == []                 # not even start/stop

    def test_lowered_hlo_byte_identical_monitor_on_vs_off(self):
        import jax
        import jax.numpy as jnp

        from apex_tpu.resilience import guard

        def opt_update(g, p):
            return jax.tree_util.tree_map(
                lambda pv, gv: pv - 0.1 * gv, p, g)

        def train_step(g, p, gs):
            return guard.guarded_update(g, opt_update, p, gs)

        g = {"w": jnp.ones((8,), jnp.float32)}
        p = {"w": jnp.ones((8,), jnp.float32)}
        gs = guard.init_guard_state()

        def lowered_text():
            return jax.jit(train_step).lower(g, p, gs).as_text()

        off = lowered_text()
        reg = _reg()
        mon = Monitor(reg, rules=default_rules())
        with use_registry(reg):
            on = lowered_text()
            mon.poll()
        mon.close()
        assert on == off


# ---------------------------------------------------------------------------
# chaos acceptance: stub fleet kill + real guard NaN, fire -> resolve
# ---------------------------------------------------------------------------


class _StubEngine:
    def __init__(self, num_slots=4):
        self.config = types.SimpleNamespace(
            num_slots=num_slots, batch_buckets=(2, 4),
            prefill_buckets=(64,), eos_token_id=None, pad_token_id=0)
        self.max_len = 10_000
        self.decode_retries_total = 0
        self.compile_count = 6
        self.spec = types.SimpleNamespace(
            bytes_per_slot=lambda: 0, cache_dtype_name=lambda: "stub")

    def kv_cache_bytes(self):
        return 0

    def prefill(self, slot_ids, prompts, *, pad_slot_ids=None):
        return np.ones(len(prompts), np.int32)

    def decode(self, slot_ids, tokens, *, pad_slot_ids=None,
               retries=0, backoff_s=0.0, backoff_cap_s=0.0):
        return np.ones(len(slot_ids), np.int32), \
            np.ones(len(slot_ids), bool)


class TestChaosAcceptance:
    def test_replica_kill_fires_and_respawn_resolves(self, tmp_path):
        from apex_tpu.resilience import faults
        from apex_tpu.serving import FleetConfig, Request, ServeFleet

        reg = _reg(tmp_path)
        events = _capture_alerts(reg)
        mon = Monitor(reg, rules=default_rules())
        fleet = ServeFleet(
            engine_factory=lambda idx, mesh, name: _StubEngine(),
            config=FleetConfig(num_replicas=2, respawn_delay_ticks=1),
            registry=reg)
        try:
            saw_firing = False
            with faults.inject_replica_loss(0, 2):
                for i in range(6):
                    fleet.submit(Request(
                        rid=i,
                        prompt=np.arange(3, dtype=np.int32) % 7,
                        max_new_tokens=4, arrival=0.0,
                        tier="interactive" if i % 2 else "batch"))
                for _ in range(400):
                    if not fleet._work_remaining():
                        break
                    fleet.step()
                    res = mon.poll()
                    rh = next(r for r in res["alerts"]
                              if r["rule"] == "replica_health")
                    saw_firing = saw_firing or rh["firing"]
            for _ in range(3):
                mon.poll()
        finally:
            faults.disarm_replica_loss()
        assert saw_firing, "the kill never fired replica_health"
        rows = {r["rule"]: r for r in mon.alerts()}
        assert rows["replica_health"]["fired_count"] >= 1
        assert not rows["replica_health"]["firing"]
        transitions = [(e["name"], e["state"]) for e in events
                       if e["name"] == "replica_health"]
        assert ("replica_health", "firing") in transitions
        assert ("replica_health", "resolved") in transitions
        assert mon.alerts_firing() == 0
        mon.close()
        reg.disable()

    def test_real_guard_nan_fires_and_clean_step_resolves(self):
        import jax
        import jax.numpy as jnp

        from apex_tpu.resilience import guard

        reg = _reg()
        mon = Monitor(reg, rules=default_rules())

        def opt_update(g, p):
            return jax.tree_util.tree_map(
                lambda pv, gv: pv - 0.1 * gv, p, g)

        step = jax.jit(lambda g, p, gs: guard.guarded_update(
            g, opt_update, p, gs))
        params = {"w": jnp.ones((4,), jnp.float32)}
        gs = guard.init_guard_state()
        params, gs = step({"w": jnp.full((4,), jnp.nan)}, params, gs)
        guard.check_guard(gs, 8, registry=reg)
        res = mon.poll()
        gsk = next(r for r in res["alerts"]
                   if r["rule"] == "guard_skips")
        assert gsk["firing"] and gsk["value"] == 1.0
        params, gs = step({"w": jnp.ones((4,), jnp.float32)},
                          params, gs)
        guard.check_guard(gs, 8, registry=reg)
        res = mon.poll()
        assert not next(r for r in res["alerts"]
                        if r["rule"] == "guard_skips")["firing"]
        assert mon.alerts_firing() == 0
        mon.close()


# ---------------------------------------------------------------------------
# online straggler / bubble attribution
# ---------------------------------------------------------------------------


def _tick(t, dur, fwd=(), bwd=(), phase="steady"):
    return {"kind": "span", "name": f"pp_tick_{t}",
            "duration_s": dur, "phase": phase,
            "fwd": [list(u) for u in fwd],
            "bwd": [list(u) for u in bwd]}


def _feed_1f1b(attr, pp=4, m=8, base=0.010, slow_stage=None,
               slow_extra=0.030):
    """Synthetic 1F1B ramp: tick i runs stages active in a sliding
    window, so every stage gets exposed and unexposed ticks."""
    t = 0
    for start in range(m + pp - 1):
        active = [r for r in range(pp) if 0 <= start - r < m]
        dur = base + (slow_extra if slow_stage in active else 0.0)
        attr.add_span(_tick(t, dur,
                            fwd=[(r, start - r) for r in active]))
        t += 1


class TestPipelineAttributor:
    def test_straggler_named_with_delta(self):
        attr = PipelineAttributor()
        _feed_1f1b(attr, pp=4, m=8, slow_stage=2)
        rep = attr.report()
        assert rep["pp"] == 4 and rep["microbatches"] == 8
        assert rep["straggler"] == 2
        assert rep["straggler_delta_s"] == pytest.approx(0.030,
                                                         rel=0.3)

    def test_uniform_load_abstains(self):
        attr = PipelineAttributor()
        _feed_1f1b(attr, pp=4, m=8, slow_stage=None)
        assert attr.report()["straggler"] is None

    def test_pp1_abstains(self):
        attr = PipelineAttributor()
        for t in range(8):
            attr.add_span(_tick(t, 0.01, fwd=[(0, t)]))
        rep = attr.report()
        assert rep["pp"] == 1 and rep["straggler"] is None

    def test_bubble_fraction_measured_vs_analytic(self):
        attr = PipelineAttributor()
        _feed_1f1b(attr, pp=4, m=8)
        rep = attr.report()
        assert rep["bubble_fraction_analytic"] == pytest.approx(
            3 / 11)
        assert 0.0 < rep["bubble_fraction_measured"] < 1.0

    def test_comm_exposure_split(self):
        attr = PipelineAttributor()
        attr.add_span({"kind": "span", "name": "ddp_overlap_bucket_0",
                       "duration_s": 0.02, "bubble": True})
        attr.add_span({"kind": "span", "name": "ddp_overlap_bucket_1",
                       "duration_s": 0.06})
        data = attr.report()["comm_exposure"]["data"]
        assert data["buckets"] == 2
        assert data["exposed_fraction"] == pytest.approx(0.75)

    def test_non_matching_spans_ignored(self):
        attr = PipelineAttributor()
        assert not attr.add_span({"kind": "span", "name": "train/step",
                                  "duration_s": 1.0})
        assert not attr.add_span({"kind": "event", "name": "pp_tick_0"})
        assert attr.ticks_seen == 0

    def test_monitor_feeds_attributor_from_tap(self):
        reg = _reg()
        mon = Monitor(reg, rules=[])
        for t in range(6):
            active = [(0, t)] if t % 2 else [(0, t), (1, t)]
            reg.event("span", f"pp_tick_{t}",
                      duration_s=0.01 + 0.02 * (len(active) > 1),
                      fwd=[list(u) for u in active], bwd=[])
        rep = mon.straggler_report()
        assert rep["pp"] == 2 and rep["ticks"] == 6
        mon.close()

    @pytest.mark.slow  # compiles a 2-stage 3-D pipeline step
    def test_real_pipeline_straggler_named_via_trace(self):
        import jax

        from apex_tpu.parallel import mesh2d, pipeline

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices for a pipe axis")
        reg = _reg()
        mon = Monitor(reg, rules=[])
        mesh = pipeline.mesh_3d(1, 1, 2, devices=jax.devices()[:2])
        sp = mesh2d.gpt2_init(hidden=32, layers=2, heads=4, vocab=32,
                              max_seq=8)
        step, state = pipeline.build_pipeline_step(
            mesh, sp, hidden=32, heads=4, microbatches=4,
            straggler=(1, 0.05))
        tokens, labels = pipeline.make_batch_3d(
            mesh, microbatches=4, batch_per_replica=2, seq=8,
            vocab=32)
        with use_registry(reg):
            out = step(*state, tokens, labels)
            jax.block_until_ready(out[-1])
        rep = mon.straggler_report()
        assert rep["pp"] == 2 and rep["ticks"] > 0
        assert rep["straggler"] == 1
        assert rep["bubble_fraction_measured"] is not None
        mon.close()


# ---------------------------------------------------------------------------
# lifecycle + registry snapshot
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_start_close_emits_monitor_events(self, tmp_path):
        reg = _reg(tmp_path)
        mon = Monitor(reg, rules=default_rules())
        mon.start(interval_s=0.01)
        mon.poll()
        mon.close()
        mon.close()                         # idempotent
        reg.disable()
        kinds = {}
        for p in sorted(tmp_path.glob("*.jsonl")):
            for line in p.read_text().splitlines():
                rec = json.loads(line)
                if rec.get("kind") == "monitor":
                    kinds[rec["name"]] = rec
        assert "start" in kinds and "stop" in kinds
        assert kinds["stop"]["polls"] >= 1
        assert "guard_skips" in kinds["start"]["rules"]

    def test_context_manager_closes(self):
        reg = _reg()
        with Monitor(reg, rules=[]) as mon:
            mon.poll()
        assert mon._closed

    def test_snapshot_is_a_point_in_time_copy(self):
        reg = _reg()
        reg.counter("c").inc(2.0)
        reg.gauge("g").set(1.0)
        reg.histogram("h").observe(5.0)
        snap = reg.snapshot()
        reg.counter("c").inc(10.0)
        reg.gauge("g").set(99.0)
        assert snap["counters"]["c"] == 2.0
        assert snap["gauges"]["g"] == 1.0
        assert snap["histograms"]["h"]["count"] == 1
        assert "ts" in snap


# ---------------------------------------------------------------------------
# the human ends: dash + report
# ---------------------------------------------------------------------------


def _write_capture(tmp_path):
    recs = [
        {"kind": "monitor", "name": "start", "rules": ["guard_skips"]},
        {"kind": "alert", "name": "guard_skips", "state": "firing",
         "severity": "page", "value": 2.0, "ts": 1.0},
        {"kind": "alert", "name": "guard_skips", "state": "resolved",
         "severity": "page", "duration_s": 0.5, "ts": 2.0},
        {"kind": "alert", "name": "pending_depth", "state": "firing",
         "severity": "warn", "value": 70.0, "ts": 3.0},
        {"kind": "fleet", "name": "replica_state", "replica": 0,
         "old": "serving", "new": "respawning", "ts": 3.5},
        {"kind": "span", "name": "pp_tick_0", "duration_s": 0.01,
         "fwd": [[0, 0]], "bwd": [], "phase": "warmup"},
        {"kind": "summary",
         "gauges": {"monitor/alerts_firing": 1.0,
                    "guard/consecutive_skips": 0.0},
         "counters": {}, "histograms": {}},
    ]
    path = tmp_path / "telemetry-rank0.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return recs


class TestDashAndReport:
    def test_dash_once_exit_code_is_firing_count(self, tmp_path,
                                                 capsys):
        _write_capture(tmp_path)
        rc = monitor_dash.main([str(tmp_path), "--once"])
        out = capsys.readouterr().out
        assert rc == 1                      # pending_depth unresolved
        assert "pending_depth" in out and "guard_skips" in out
        assert "0:respawning" in out

    def test_dash_missing_dir_is_loud(self, tmp_path, capsys):
        assert monitor_dash.main([str(tmp_path / "nope"),
                                  "--once"]) == 2

    def test_report_folds_alert_and_monitor_kinds(self, tmp_path):
        _write_capture(tmp_path)
        report = telemetry_report.aggregate(
            telemetry_report.load_events(
                [str(tmp_path / "telemetry-rank0.jsonl")]))
        alerts = report["alerts"]
        assert alerts["by_rule"]["guard_skips"]["fired"] == 1
        assert alerts["by_rule"]["guard_skips"]["resolved"] == 1
        assert alerts["by_rule"]["pending_depth"][
            "last_state"] == "firing"
        assert alerts["monitor"]["starts"] == 1
        assert len(alerts["timeline"]) == 3
        assert report["unknown_kinds"] == {}
        buf = io.StringIO()
        telemetry_report.print_report(report, out=buf)
        text = buf.getvalue()
        assert "alerts (telemetry.monitor)" in text
        assert "STILL FIRING" in text
