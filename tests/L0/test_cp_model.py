"""Model-level context parallelism: TransformerConfig(context_parallel)
runs the whole GPT on sequence shards over the 'cp' axis.

Equivalence oracle: logits from the cp-sharded model (gathered over cp)
must match the unsharded model with the same params. Complements
test_context_parallel.py, which covers the ring/Ulysses primitives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.gpt import gpt_loss_fn
from apex_tpu.testing import shard_map
from apex_tpu.transformer import parallel_state

CP, SEQ, B = 4, 16, 2


def _cfg(**kw):
    base = dict(hidden_size=32, num_layers=2, num_attention_heads=4,
                vocab_size=64, max_position_embeddings=SEQ,
                compute_dtype=jnp.float32, use_flash_attention=False,
                position_embedding_type="rope")
    base.update(kw)
    return TransformerConfig(**base)


@pytest.mark.parametrize("variant",
                         ["mha", "gqa", "learned_pos", "ulysses"])
def test_cp_logits_match_unsharded(variant):
    kw = {}
    if variant == "gqa":
        kw = dict(num_query_groups=2)
    elif variant == "learned_pos":
        kw = dict(position_embedding_type="learned")
    elif variant == "ulysses":
        kw = dict(context_parallel_algo="ulysses")
    parallel_state.destroy_model_parallel()
    ref_cfg = _cfg(**kw)
    cp_cfg = _cfg(context_parallel=True, **kw)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (B, SEQ)))

    ref_model = GPTModel(ref_cfg)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)["params"]
    ref = ref_model.apply({"params": params}, tokens)

    parallel_state.initialize_model_parallel(
        context_parallel_size_=CP, devices=jax.devices()[:CP])
    mesh = parallel_state.get_mesh()
    cp_model = GPTModel(cp_cfg)

    @shard_map(mesh=mesh, in_specs=(P(), P(None, "cp")),
               out_specs=P(None, "cp", None))
    def run(p, toks):
        s_local = toks.shape[-1]
        rank = jax.lax.axis_index("cp")
        pos = (rank * s_local + jnp.arange(s_local))[None, :]
        return cp_model.apply({"params": p}, toks, pos)

    out = jax.jit(run)(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_cp_training_step_loss_matches():
    """Per-shard CE mean pmean'd over cp == unsharded mean loss; grads
    (pmean over cp) match the unsharded grads."""
    parallel_state.destroy_model_parallel()
    ref_cfg = _cfg()
    cp_cfg = _cfg(context_parallel=True)
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, 64, (B, SEQ)))
    labels = jnp.asarray(rng.randint(0, 64, (B, SEQ)))

    ref_model = GPTModel(ref_cfg)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)["params"]

    def ref_loss(p):
        return gpt_loss_fn(ref_model.apply({"params": p}, tokens), labels)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)

    parallel_state.initialize_model_parallel(
        context_parallel_size_=CP, devices=jax.devices()[:CP])
    mesh = parallel_state.get_mesh()
    cp_model = GPTModel(cp_cfg)

    @shard_map(mesh=mesh, in_specs=(P(), P(None, "cp"), P(None, "cp")),
               out_specs=(P(), P()))
    def step(p, toks, labs):
        s_local = toks.shape[-1]
        rank = jax.lax.axis_index("cp")
        pos = (rank * s_local + jnp.arange(s_local))[None, :]

        def loss_fn(q):
            return gpt_loss_fn(cp_model.apply({"params": q}, toks, pos),
                               labs)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        # params replicate over cp; each rank saw 1/cp of the tokens
        return (jax.lax.pmean(loss, "cp"),
                jax.tree_util.tree_map(lambda g: jax.lax.pmean(g, "cp"),
                                       grads))

    cp_l, cp_g = jax.jit(step)(params, tokens, labels)
    np.testing.assert_allclose(float(cp_l), float(ref_l), rtol=2e-4)
    for (pa, ga), (_, gb) in zip(
            jax.tree_util.tree_leaves_with_path(cp_g),
            jax.tree_util.tree_leaves_with_path(ref_g)):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   rtol=3e-4, atol=3e-4, err_msg=str(pa))


def test_cp_decode_rejected():
    parallel_state.destroy_model_parallel()
    cfg = _cfg(context_parallel=True)
    model = GPTModel(cfg, decode=True)
    with pytest.raises(ValueError, match="context parallelism"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
