"""Minimal GPT end-to-end training under full 3D parallelism.

Parity: reference tests/L0/run_transformer/test_gpt_minimal.py — build the
in-package GPT, run real training steps under the parallel runtime, and
assert the loss trends down. Here: pp=2 x dp=2 x tp=2 over the 8-device
CPU mesh, 1F1B pipeline schedule, sequence parallelism on, DP grad pmean,
model-parallel GradScaler, FusedAdam with master weights.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt_stage import GPTStage
from apex_tpu.models.transformer_lm import (
    TransformerConfig,
    is_sequence_parallel_param,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp.grad_scaler import GradScaler
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    allreduce_sequence_parallel_grads,
)

PP, DP, TP = 2, 2, 2
SEQ, MB, M = 16, 2, 2  # seq, microbatch, num microbatches


@pytest.fixture
def gpt_setup():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, pipeline_model_parallel_size_=PP,
        devices=jax.devices()[:8])
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2 * PP, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32,
        compute_dtype=jnp.bfloat16, sequence_parallel=True,
        use_flash_attention=False)
    yield mesh, cfg
    parallel_state.destroy_model_parallel()


def test_gpt_3d_parallel_training_loss_decreases(gpt_setup):
    mesh, cfg = gpt_setup
    stage = GPTStage(cfg, cfg.num_layers // PP)
    global_b = MB * M * DP
    rng = np.random.RandomState(0)
    # A learnable (repetitive) token stream so a few steps visibly reduce
    # loss: next token = (token + 1) % 32.
    base = rng.randint(0, 32, size=(global_b, 1))
    tokens = jnp.asarray((base + np.arange(SEQ)) % 32)
    labels = jnp.asarray((base + np.arange(1, SEQ + 1)) % 32)

    opt = FusedAdam(lr=5e-3, master_weights=True)
    scaler = GradScaler(enabled=True)
    tensor_shape = (SEQ // TP, MB, cfg.hidden_size)

    def stage_fn(params, h, mb, is_first):
        return stage.apply({"params": params}, mb["tokens"], h, is_first)

    def loss_fn(params, y, mb):
        return stage.apply({"params": params}, y, mb["labels"],
                           method=GPTStage.loss)

    def train_step(params, opt_state, scaler_state, tokens, labels):
        mbs = {"tokens": tokens.reshape(M, MB, SEQ),
               "labels": labels.reshape(M, MB, SEQ)}
        # scale the loss up by the live scale; unscale_grads divides it
        # back out (and pmaxes found_inf over tp x pp)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, params, mbs, num_microbatches=M,
            tensor_shape=tensor_shape, dtype=jnp.bfloat16,
            grad_scale=scaler_state.loss_scale, pp_size=PP)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "dp"), grads)
        grads = allreduce_sequence_parallel_grads(
            grads, is_sequence_parallel_param)
        grads, found_inf = scaler.unscale_grads(grads, scaler_state)
        new_params, new_opt_state = opt.step(
            grads, opt_state, params, found_inf=found_inf)
        new_scaler_state = scaler.update(scaler_state, found_inf)
        return new_params, new_opt_state, new_scaler_state, jnp.sum(losses)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P("pp"), P(), P("dp"), P("dp")),
        out_specs=(P("pp"), P("pp"), P(), P(("pp", "dp"))),
        check_vma=False)
    def sharded_step(stacked_params, stacked_opt, scaler_state, tok, lab):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        opt_state = jax.tree_util.tree_map(lambda a: a[0], stacked_opt)
        p, o, s, l = train_step(params, opt_state, scaler_state,
                                tok.reshape(-1, SEQ), lab.reshape(-1, SEQ))
        stack = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)  # noqa: E731
        return stack(p), stack(o), s, l.reshape(1, 1)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(), P(), P()), out_specs=P("pp"),
                       check_vma=False)
    def init_params(key, tok, lab):
        rank = jax.lax.axis_index("pp")
        key = jax.random.fold_in(key, rank)
        h0 = jnp.zeros(tensor_shape, jnp.bfloat16)
        variables = stage.init(key, tok[:MB], h0, jnp.asarray(False),
                               lab[:MB], method=GPTStage.full)
        return jax.tree_util.tree_map(lambda a: a[None], variables["params"])

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("pp"),
                       out_specs=P("pp"), check_vma=False)
    def init_opt(stacked_params):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        return jax.tree_util.tree_map(lambda a: a[None], opt.init(params))

    stacked_params = init_params(jax.random.PRNGKey(0), tokens[:MB],
                                 labels[:MB])
    stacked_opt = init_opt(stacked_params)
    scaler_state = scaler.init_state()

    step = jax.jit(sharded_step)
    losses = []
    state = (stacked_params, stacked_opt, scaler_state)
    for _ in range(12):
        *state, loss = step(*state, tokens, labels)
        # only the last pp stage contributes a nonzero loss; sum over the
        # (pp, dp) grid rows then average the dp replicas
        loss = np.asarray(loss)
        losses.append(float(loss.sum()) / DP / M)
    assert np.isfinite(losses).all()
    # learnable stream: loss must drop substantially from step 0
    assert losses[-1] < 0.7 * losses[0], losses
    # and monotonic-ish: the minimum is at the end half
    assert min(losses[6:]) < min(losses[:6])
