"""Minimal GPT end-to-end training under full 3D parallelism.

Parity: reference tests/L0/run_transformer/test_gpt_minimal.py — build the
in-package GPT, run real training steps under the parallel runtime, and
assert the loss trends down. Here: pp=2 x dp=2 x tp=2 over the 8-device
CPU mesh, 1F1B pipeline schedule, sequence parallelism on, DP grad pmean,
model-parallel GradScaler, FusedAdam with master weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.transformer_lm import TransformerConfig
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp.grad_scaler import GradScaler
from apex_tpu.transformer.testing.gpt_3d import build_gpt_3d_harness

PP, DP, TP = 2, 2, 2
SEQ, MB, M = 16, 2, 2  # seq, microbatch, num microbatches


@pytest.fixture
def gpt_setup():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, pipeline_model_parallel_size_=PP,
        devices=jax.devices()[:8])
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2 * PP, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32,
        compute_dtype=jnp.bfloat16, sequence_parallel=True,
        use_flash_attention=False)
    yield mesh, cfg
    parallel_state.destroy_model_parallel()


@pytest.mark.slow
def test_gpt_3d_parallel_training_loss_decreases(gpt_setup):
    mesh, cfg = gpt_setup
    global_b = MB * M * DP
    rng = np.random.RandomState(0)
    # A learnable (repetitive) token stream so a few steps visibly reduce
    # loss: next token = (token + 1) % 32.
    base = rng.randint(0, 32, size=(global_b, 1))
    tokens = jnp.asarray((base + np.arange(SEQ)) % 32)
    labels = jnp.asarray((base + np.arange(1, SEQ + 1)) % 32)

    opt = FusedAdam(lr=5e-3, master_weights=True)
    scaler = GradScaler(enabled=True)
    init_state, step = build_gpt_3d_harness(
        cfg, mesh, opt, scaler, pp=PP, seq=SEQ, microbatch=MB,
        num_microbatches=M)

    losses = []
    state = init_state(jax.random.PRNGKey(0), tokens, labels)
    for _ in range(12):
        *state, loss = step(*state, tokens, labels)
        # only the last pp stage contributes a nonzero loss; sum over the
        # (pp, dp) grid rows then average the dp replicas
        loss = np.asarray(loss)
        losses.append(float(loss.sum()) / DP / M)
    assert np.isfinite(losses).all()
    # learnable stream: loss must drop substantially from step 0
    assert losses[-1] < 0.7 * losses[0], losses
    # and monotonic-ish: the minimum is at the end half
    assert min(losses[6:]) < min(losses[:6])


@pytest.mark.slow
def test_gpt_3d_interleaved_vpp_training_loss_decreases():
    """Same 3D harness with virtual pipelining (vpp=2): 8 layers as 4
    global stages (2 chunks x 2 ranks), interleaved 1F1B. The real-model
    integration of forward_backward_pipelining_with_interleaving
    (reference test_pipeline_parallel_fwd_bwd.py virtual-chunk cases)."""
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, pipeline_model_parallel_size_=PP,
        virtual_pipeline_model_parallel_size_=2,
        devices=jax.devices()[:8])
    V = 2
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2 * PP * V, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32,
        compute_dtype=jnp.bfloat16, sequence_parallel=True,
        use_flash_attention=False)
    global_b = MB * M * DP
    rng = np.random.RandomState(0)
    base = rng.randint(0, 32, size=(global_b, 1))
    tokens = jnp.asarray((base + np.arange(SEQ)) % 32)
    labels = jnp.asarray((base + np.arange(1, SEQ + 1)) % 32)

    opt = FusedAdam(lr=5e-3, master_weights=True)
    scaler = GradScaler(enabled=True)
    init_state, step = build_gpt_3d_harness(
        cfg, mesh, opt, scaler, pp=PP, seq=SEQ, microbatch=MB,
        num_microbatches=M, vpp=V)

    losses = []
    state = init_state(jax.random.PRNGKey(0), tokens, labels)
    for _ in range(12):
        *state, loss = step(*state, tokens, labels)
        losses.append(float(np.asarray(loss).sum()) / DP / M)
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.7 * losses[0], losses
    # teardown is the conftest autouse _reset_parallel_state fixture
