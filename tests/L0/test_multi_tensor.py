"""Multi-tensor op numerics vs pure-numpy references.

Mirrors reference tests/L0/run_amp/test_multi_tensor_scale.py,
test_multi_tensor_axpby.py, test_multi_tensor_l2norm.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
)


def _tensors(rng, shapes, dtype=np.float32):
    return [jnp.asarray(rng.randn(*s).astype(dtype)) for s in shapes]


SHAPES = [(5,), (7, 3), (2, 4, 8)]


class TestMultiTensorScale:
    def test_scale(self, rng):
        xs = _tensors(rng, SHAPES)
        outs, noop = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, xs], 0.125)
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(np.asarray(o), np.asarray(x) * 0.125,
                                       rtol=1e-6)
        assert float(noop) == 0.0

    def test_overflow_detected(self, rng):
        xs = _tensors(rng, SHAPES)
        xs[1] = xs[1].at[0, 0].set(jnp.inf)
        _, noop = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, xs], 1.0)
        assert float(noop) == 1.0

    def test_nan_detected(self, rng):
        xs = _tensors(rng, SHAPES)
        xs[2] = xs[2].at[0, 0, 0].set(jnp.nan)
        _, noop = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, xs], 1.0)
        assert float(noop) == 1.0

    def test_dtype_cast(self, rng):
        xs = _tensors(rng, SHAPES)
        outs_b = [x.astype(jnp.bfloat16) for x in xs]
        outs, _ = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, outs_b], 2.0)
        for o in outs:
            assert o.dtype == jnp.bfloat16


class TestMultiTensorAxpby:
    def test_axpby(self, rng):
        xs = _tensors(rng, SHAPES)
        ys = _tensors(rng, SHAPES)
        outs, noop = multi_tensor_applier(
            multi_tensor_axpby, jnp.zeros(()), [xs, ys, xs], 2.0, -1.0)
        for x, y, o in zip(xs, ys, outs):
            np.testing.assert_allclose(
                np.asarray(o), 2.0 * np.asarray(x) - np.asarray(y), rtol=1e-6)
        assert float(noop) == 0.0


class TestMultiTensorL2Norm:
    def test_l2norm(self, rng):
        xs = _tensors(rng, SHAPES)
        total, per = multi_tensor_applier(
            multi_tensor_l2norm, jnp.zeros(()), [xs], True)
        flat = np.concatenate([np.asarray(x).ravel() for x in xs])
        np.testing.assert_allclose(float(total), np.linalg.norm(flat), rtol=1e-6)
        for x, p in zip(xs, np.asarray(per)):
            np.testing.assert_allclose(p, np.linalg.norm(np.asarray(x).ravel()),
                                       rtol=1e-6)


class TestLambStages:
    """Legacy two-stage LAMB (amp_C lamb_stage1/2 parity): composing the
    stages must match the fused multi_tensor_lamb update."""

    @pytest.mark.parametrize("weight_decay", [0.01, 0.0])
    def test_stages_match_fused(self, rng, weight_decay):
        # weight_decay=0.0 exercises the apply_trust gate: fused LAMB skips
        # the trust ratio for zero-decay tensors, so stage2 must too
        import jax.numpy as jnp
        from apex_tpu.ops import (
            multi_tensor_lamb,
            multi_tensor_lamb_stage1,
            multi_tensor_lamb_stage2,
        )

        n = 3
        grads = [jnp.asarray(rng.randn(5).astype(np.float32)) for _ in range(n)]
        params = [jnp.asarray(rng.randn(5).astype(np.float32)) for _ in range(n)]
        ms = [jnp.zeros(5, jnp.float32) for _ in range(n)]
        vs = [jnp.zeros(5, jnp.float32) for _ in range(n)]
        noop = jnp.zeros((), jnp.float32)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in grads))
        kw = dict(lr=0.01, beta1=0.9, beta2=0.99, eps=1e-6, step=1,
                  bias_correction=1, weight_decay=weight_decay,
                  grad_averaging=1, mode=1, global_grad_norm=gnorm,
                  max_grad_norm=1.0)

        new_p_f, new_m_f, new_v_f, _ = multi_tensor_lamb(
            noop, [grads, params, ms, vs], use_nvlamb=False, **kw)

        decay = [weight_decay] * n
        new_m, new_v, updates, _ = multi_tensor_lamb_stage1(
            noop, [grads, params, ms, vs, [None] * n],
            per_tensor_decay=decay, step=1, beta1=0.9, beta2=0.99,
            beta3=None, bias_correction=1, eps=1e-6, grad_averaging=1,
            mode=1, global_grad_norm=gnorm, max_global_grad_norm=1.0)
        new_p, _ = multi_tensor_lamb_stage2(noop, [params, updates],
                                            per_tensor_decay=decay, lr=0.01)

        for got, want in ((new_p, new_p_f), (new_m, new_m_f),
                          (new_v, new_v_f)):
            for a, b in zip(got, want):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6)
