"""Multi-tensor op numerics vs pure-numpy references.

Mirrors reference tests/L0/run_amp/test_multi_tensor_scale.py,
test_multi_tensor_axpby.py, test_multi_tensor_l2norm.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
)


def _tensors(rng, shapes, dtype=np.float32):
    return [jnp.asarray(rng.randn(*s).astype(dtype)) for s in shapes]


SHAPES = [(5,), (7, 3), (2, 4, 8)]


class TestMultiTensorScale:
    def test_scale(self, rng):
        xs = _tensors(rng, SHAPES)
        outs, noop = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, xs], 0.125)
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(np.asarray(o), np.asarray(x) * 0.125,
                                       rtol=1e-6)
        assert float(noop) == 0.0

    def test_overflow_detected(self, rng):
        xs = _tensors(rng, SHAPES)
        xs[1] = xs[1].at[0, 0].set(jnp.inf)
        _, noop = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, xs], 1.0)
        assert float(noop) == 1.0

    def test_nan_detected(self, rng):
        xs = _tensors(rng, SHAPES)
        xs[2] = xs[2].at[0, 0, 0].set(jnp.nan)
        _, noop = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, xs], 1.0)
        assert float(noop) == 1.0

    def test_dtype_cast(self, rng):
        xs = _tensors(rng, SHAPES)
        outs_b = [x.astype(jnp.bfloat16) for x in xs]
        outs, _ = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros(()), [xs, outs_b], 2.0)
        for o in outs:
            assert o.dtype == jnp.bfloat16


class TestMultiTensorAxpby:
    def test_axpby(self, rng):
        xs = _tensors(rng, SHAPES)
        ys = _tensors(rng, SHAPES)
        outs, noop = multi_tensor_applier(
            multi_tensor_axpby, jnp.zeros(()), [xs, ys, xs], 2.0, -1.0)
        for x, y, o in zip(xs, ys, outs):
            np.testing.assert_allclose(
                np.asarray(o), 2.0 * np.asarray(x) - np.asarray(y), rtol=1e-6)
        assert float(noop) == 0.0


class TestMultiTensorL2Norm:
    def test_l2norm(self, rng):
        xs = _tensors(rng, SHAPES)
        total, per = multi_tensor_applier(
            multi_tensor_l2norm, jnp.zeros(()), [xs], True)
        flat = np.concatenate([np.asarray(x).ravel() for x in xs])
        np.testing.assert_allclose(float(total), np.linalg.norm(flat), rtol=1e-6)
        for x, p in zip(xs, np.asarray(per)):
            np.testing.assert_allclose(p, np.linalg.norm(np.asarray(x).ravel()),
                                       rtol=1e-6)
