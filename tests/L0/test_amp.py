"""amp: opt levels, loss scaling, overflow skip, checkpoint round-trip.

Mirrors reference tests/L0/run_amp (test_basic_casts.py, test_checkpointing.py,
test_multiple_models_optimizers_losses.py patterns).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import LossScaler
from apex_tpu.amp._amp_state import _amp_state
from apex_tpu.optimizers import FusedAdam, FusedSGD


@pytest.fixture(autouse=True)
def _reset_amp():
    yield
    _amp_state.reset()


def make_params(rng):
    return {"dense": {"kernel": jnp.asarray(rng.randn(4, 4).astype(np.float32))},
            "bn": {"scale": jnp.ones((4,), jnp.float32)}}


class TestOptLevels:
    def test_o0_keeps_fp32(self, rng):
        params, opt = amp.initialize(make_params(rng), FusedAdam(lr=1e-3),
                                     opt_level="O0", verbosity=0)
        for l in jax.tree_util.tree_leaves(params):
            assert l.dtype == jnp.float32

    def test_o1_keeps_params_fp32(self, rng):
        params, opt = amp.initialize(make_params(rng), FusedAdam(lr=1e-3),
                                     opt_level="O1", verbosity=0)
        for l in jax.tree_util.tree_leaves(params):
            assert l.dtype == jnp.float32
        assert _amp_state.opt_properties.patch_torch_functions

    def test_o2_casts_but_keeps_bn(self, rng):
        params, opt = amp.initialize(make_params(rng), FusedAdam(lr=1e-3),
                                     opt_level="O2", verbosity=0)
        assert params["dense"]["kernel"].dtype == jnp.bfloat16
        assert params["bn"]["scale"].dtype == jnp.float32
        assert opt.master_weights

    def test_o3_casts_everything(self, rng):
        params, opt = amp.initialize(make_params(rng), FusedAdam(lr=1e-3),
                                     opt_level="O3", verbosity=0)
        assert params["dense"]["kernel"].dtype == jnp.bfloat16
        assert params["bn"]["scale"].dtype == jnp.bfloat16

    def test_bad_opt_level(self, rng):
        with pytest.raises(RuntimeError):
            amp.initialize(make_params(rng), None, opt_level="O4")


class TestLossScaler:
    def test_static_scale(self):
        s = LossScaler(128.0)
        assert not s.dynamic
        loss = jnp.asarray(2.0)
        assert float(s.scale(loss)) == 256.0

    def test_dynamic_halves_on_overflow(self):
        s = LossScaler("dynamic")
        state = s.init_state()
        assert float(state.loss_scale) == 2.0 ** 16
        state = s.update(state, jnp.ones((), jnp.float32))
        assert float(state.loss_scale) == 2.0 ** 15

    def test_dynamic_doubles_after_window(self):
        s = LossScaler("dynamic", init_scale=4.0, scale_window=3)
        state = s.init_state()
        for _ in range(3):
            state = s.update(state, jnp.zeros((), jnp.float32))
        assert float(state.loss_scale) == 8.0

    def test_unscale_detects_inf(self, rng):
        s = LossScaler("dynamic")
        grads = {"a": jnp.asarray([1.0, jnp.inf])}
        _, found = s.unscale_grads(grads, s.init_state())
        assert float(found) == 1.0

    def test_state_dict_roundtrip(self):
        s = LossScaler("dynamic")
        state = s.init_state()
        s._state = s.update(state, jnp.ones((), jnp.float32))
        sd = s.state_dict()
        s2 = LossScaler("dynamic")
        s2.load_state_dict(sd)
        assert float(s2._state.loss_scale) == float(s._state.loss_scale)


class TestAmpOptimizerStep:
    def test_o2_training_converges(self, rng):
        """O2 end-to-end: bf16 params + fp32 masters converge on a
        quadratic."""
        params = {"w": jnp.asarray(rng.randn(8).astype(np.float32))}
        target = jnp.asarray(rng.randn(8).astype(np.float32))
        params, opt = amp.initialize(params, FusedSGD(lr=0.1),
                                     opt_level="O2", verbosity=0)
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum((p["w"].astype(jnp.float32) - target) ** 2)

        losses = []
        for _ in range(50):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            scaled_grads = jax.tree_util.tree_map(
                lambda g: g * float(state["scaler"].loss_scale), grads)
            params, state = opt.step(scaled_grads, state, params)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.1

    def test_overflow_skips_and_backs_off(self, rng):
        params = {"w": jnp.ones((4,), jnp.float32)}
        params, opt = amp.initialize(params, FusedAdam(lr=1.0),
                                     opt_level="O2", loss_scale="dynamic",
                                     verbosity=0)
        state = opt.init(params)
        scale0 = float(state["scaler"].loss_scale)
        bad_grads = {"w": jnp.full((4,), jnp.inf)}
        new_params, state = opt.step(bad_grads, state, params)
        np.testing.assert_array_equal(
            np.asarray(new_params["w"], dtype=np.float32),
            np.asarray(params["w"], dtype=np.float32))
        assert float(state["scaler"].loss_scale) == scale0 / 2

    def test_scale_loss_context(self, rng):
        params = {"w": jnp.ones((4,), jnp.float32)}
        params, opt = amp.initialize(params, FusedAdam(lr=1e-3),
                                     opt_level="O2", loss_scale=8.0,
                                     verbosity=0)
        loss = jnp.asarray(3.0)
        with amp.scale_loss(loss, opt) as scaled:
            assert float(scaled) == 24.0


class TestStateDict:
    def test_amp_state_roundtrip(self, rng):
        params, opt = amp.initialize(make_params(rng), FusedAdam(lr=1e-3),
                                     opt_level="O2", num_losses=2,
                                     verbosity=0)
        sd = amp.state_dict()
        assert "loss_scaler0" in sd and "loss_scaler1" in sd
        amp.load_state_dict(sd)


class TestAutocastPolicy:
    def test_half_function_casts(self):
        @amp.half_function
        def f(x):
            return x

        x = jnp.ones((2,), jnp.float32)
        with amp.autocast():
            assert f(x).dtype == jnp.bfloat16
        assert f(x).dtype == jnp.float32

    def test_float_function(self):
        @amp.float_function
        def f(x):
            return x

        x = jnp.ones((2,), jnp.bfloat16)
        with amp.autocast():
            assert f(x).dtype == jnp.float32

    def test_promote_function(self):
        @amp.promote_function
        def f(x, y):
            return x + y

        with amp.autocast():
            out = f(jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.float32))
            assert out.dtype == jnp.float32

    def test_disable_casts(self):
        @amp.half_function
        def f(x):
            return x

        x = jnp.ones((2,), jnp.float32)
        with amp.autocast():
            with amp.disable_casts():
                assert f(x).dtype == jnp.float32
