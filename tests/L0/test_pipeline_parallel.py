"""Pipeline-parallel schedule correctness.

Mirrors reference tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py
(716 LoC): end-to-end pipelined fwd+bwd with toy models, asserting loss and
gradient equivalence vs the unpipelined computation.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.testing import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
    get_forward_backward_func,
)

PP = 4
M = 6  # microbatches
HID = 8
MB = 2  # microbatch size


def pp_mesh():
    return Mesh(np.asarray(jax.devices()[:PP]), ("pp",))


def stage_fn(params, h, mb, is_first):
    """One pipeline stage: a linear + gelu. On the global first stage the
    microbatch's input x is injected (the 'embedding'). ``h`` is None for
    the no-pipelining schedule (single stage owns the whole model)."""
    h = mb["x"] if h is None else jnp.where(is_first, mb["x"], h)
    return jax.nn.gelu(h @ params["w"] + params["b"])


def loss_fn(params, y, mb):
    return jnp.mean((y - mb["t"]) ** 2)


def make_data(rng):
    # stage-local params: every rank has its own stage weights -> emulate by
    # identical weights per rank for comparison vs a stacked reference.
    ws = rng.randn(PP, HID, HID).astype(np.float32) * 0.3
    bs = rng.randn(PP, HID).astype(np.float32) * 0.1
    xs = rng.randn(M, MB, HID).astype(np.float32)
    ts = rng.randn(M, MB, HID).astype(np.float32)
    return ws, bs, xs, ts


def reference_loss_and_grads(ws, bs, xs, ts):
    """Unpipelined reference: sequential stages over all microbatches."""
    def full(params, x, t):
        h = jnp.zeros_like(x) + x
        for i in range(PP):
            h = jax.nn.gelu(h @ params["w"][i] + params["b"][i])
        return jnp.mean((h - t) ** 2)

    params = {"w": jnp.asarray(ws), "b": jnp.asarray(bs)}

    def total(params):
        losses = [full(params, jnp.asarray(xs[m]), jnp.asarray(ts[m]))
                  for m in range(M)]
        return sum(losses) / M, jnp.stack(losses)

    (loss, losses), grads = jax.value_and_grad(total, has_aux=True)(params)
    return np.asarray(losses), grads


class TestNoPipelining:
    def test_matches_reference_single_stage(self, rng):
        w = rng.randn(HID, HID).astype(np.float32) * 0.3
        b = rng.randn(HID).astype(np.float32) * 0.1
        xs = rng.randn(M, MB, HID).astype(np.float32)
        ts = rng.randn(M, MB, HID).astype(np.float32)
        params = {"w": jnp.asarray(w), "b": jnp.asarray(b)}
        mbs = {"x": jnp.asarray(xs), "t": jnp.asarray(ts)}

        losses, grads = forward_backward_no_pipelining(
            stage_fn, loss_fn, params, mbs, num_microbatches=M)

        def ref(params):
            tot = 0.0
            for m in range(M):
                y = jax.nn.gelu(jnp.asarray(xs[m]) @ params["w"] + params["b"])
                tot = tot + jnp.mean((y - jnp.asarray(ts[m])) ** 2)
            return tot / M

        ref_grads = jax.grad(ref)(params)
        for a, b_ in zip(jax.tree_util.tree_leaves(grads),
                         jax.tree_util.tree_leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-5)


class TestPipelining1F1B:
    def test_matches_unpipelined_reference(self, rng):
        ws, bs, xs, ts = make_data(rng)
        ref_losses, ref_grads = reference_loss_and_grads(ws, bs, xs, ts)
        mesh = pp_mesh()
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=PP, devices=jax.devices()[:PP])

        # microbatch pytree: stage 0 sees x, last stage sees t; other
        # stages see zeros of the right shape (replicated feed).
        mbs = {"x": jnp.asarray(xs), "t": jnp.asarray(ts)}
        params_stacked = {"w": jnp.asarray(ws), "b": jnp.asarray(bs)}

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P("pp"), P(), P()), out_specs=(P("pp"), P("pp")))
        def run(p_stage, mb_x, mb_t):
            p = jax.tree_util.tree_map(lambda a: a[0], p_stage)
            mb = {"x": mb_x, "t": mb_t}
            losses, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, p, mb, num_microbatches=M,
                tensor_shape=(MB, HID), dtype=jnp.float32, pp_size=PP)
            grads = jax.tree_util.tree_map(lambda a: a[None], grads)
            return losses[None], grads

        losses, grads = run(params_stacked, mbs["x"], mbs["t"])
        # losses live on the last stage (row PP-1)
        np.testing.assert_allclose(np.asarray(losses)[PP - 1], ref_losses,
                                   rtol=1e-4, atol=1e-5)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(ref_grads[k]),
                rtol=1e-3, atol=1e-4)

    def test_get_forward_backward_func_dispatch(self):
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=4, devices=jax.devices()[:8])
        f = get_forward_backward_func(None, 4)
        assert f is forward_backward_pipelining_without_interleaving
        f = get_forward_backward_func(2, 4)
        assert f is forward_backward_pipelining_with_interleaving
        f = get_forward_backward_func(None, 1)
        assert f is forward_backward_no_pipelining


class TestPipeliningInterleaved:
    def test_matches_unpipelined_reference(self, rng):
        """V=2 virtual chunks on PP=2 ranks == 4 sequential stages."""
        V, P_ = 2, 2
        ws = rng.randn(V * P_, HID, HID).astype(np.float32) * 0.3
        bs = rng.randn(V * P_, HID).astype(np.float32) * 0.1
        xs = rng.randn(M, MB, HID).astype(np.float32)
        ts = rng.randn(M, MB, HID).astype(np.float32)

        # reference over 4 sequential stages (global stage c*P + r)
        def full(params, x, t):
            h = x
            for s in range(V * P_):
                h = jax.nn.gelu(h @ params["w"][s] + params["b"][s])
            return jnp.mean((h - t) ** 2)

        pref = {"w": jnp.asarray(ws), "b": jnp.asarray(bs)}

        def total(params):
            return sum(full(params, jnp.asarray(xs[m]), jnp.asarray(ts[m]))
                       for m in range(M)) / M

        ref_grads = jax.grad(total)(pref)

        mesh = Mesh(np.asarray(jax.devices()[:P_]), ("pp",))
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=P_, devices=jax.devices()[:P_])

        # rank r holds chunks [c, ...] with global stage c*P + r:
        # stacked leaf shape [P, V, ...] -> shard over pp axis
        w_rank = np.stack([[ws[c * P_ + r] for c in range(V)]
                           for r in range(P_)])
        b_rank = np.stack([[bs[c * P_ + r] for c in range(V)]
                           for r in range(P_)])

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P("pp"), P(), P()), out_specs=(P("pp"), P("pp")))
        def run(p_stage, mb_x, mb_t):
            p = jax.tree_util.tree_map(lambda a: a[0], p_stage)  # [V, ...]
            mb = {"x": mb_x, "t": mb_t}
            losses, grads = forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, p, mb, num_microbatches=M,
                tensor_shape=(MB, HID), dtype=jnp.float32, pp_size=P_,
                num_model_chunks=V)
            return losses[None], jax.tree_util.tree_map(
                lambda a: a[None], grads)

        losses, grads = run({"w": jnp.asarray(w_rank), "b": jnp.asarray(b_rank)},
                            jnp.asarray(xs), jnp.asarray(ts))
        # reassemble grads [P, V, ...] -> [S, ...]
        gw = np.asarray(grads["w"])
        gb = np.asarray(grads["b"])
        for r in range(P_):
            for c in range(V):
                s = c * P_ + r
                np.testing.assert_allclose(
                    gw[r, c], np.asarray(ref_grads["w"])[s],
                    rtol=1e-3, atol=1e-4)
                np.testing.assert_allclose(
                    gb[r, c], np.asarray(ref_grads["b"])[s],
                    rtol=1e-3, atol=1e-4)


class TestSchedulePlan:
    """VERDICT round-1 items 3+4: the 1F1B stash is O(P) (not O(M)) and
    the interleaved schedule genuinely shrinks the bubble (not V
    sequential passes). The schedules derive loop bounds and stash sizes
    from pipeline_schedule_plan, so asserting on it pins the real code."""

    def test_1f1b_stash_bounded_by_P_not_M(self):
        from apex_tpu.transformer.pipeline_parallel import (
            pipeline_schedule_plan)
        for P_, M_ in [(2, 64), (4, 128), (8, 512)]:
            plan = pipeline_schedule_plan(P_, M_)
            assert plan["stash"] == 2 * P_ - 1  # O(P)
            assert plan["stash"] < M_
        # fewer microbatches than in-flight bound: stash shrinks to M
        assert pipeline_schedule_plan(4, 2)["stash"] == 2

    def test_1f1b_tick_counts_match_reference_total(self):
        from apex_tpu.transformer.pipeline_parallel import (
            pipeline_schedule_plan)
        P_, M_ = 4, 16
        plan = pipeline_schedule_plan(P_, M_)
        # warmup fwd-only + steady fwd+bwd + cooldown bwd-only
        assert plan["warmup"] == P_ - 1
        assert plan["steady"] == M_
        assert plan["cooldown"] == P_ - 1
        # per-rank executed units = (M+P-1) fwd + (M+P-1) bwd — the
        # reference 1F1B pipeline total (M+P-1)(t_f+t_b), NOT the
        # 2(M+P-1) full ticks of a phase-split schedule
        assert plan["fwd_ticks"] == M_ + P_ - 1
        assert plan["bwd_ticks"] == M_ + P_ - 1

    def test_interleaved_bubble_shrinks_vs_sequential_passes(self):
        from apex_tpu.transformer.pipeline_parallel import (
            pipeline_schedule_plan)
        for P_, V_, M_ in [(4, 2, 8), (4, 4, 16), (8, 2, 16)]:
            plan = pipeline_schedule_plan(P_, M_, V_)
            # total ticks = M*V + overhead, overhead independent of M
            assert plan["total"] == M_ * V_ + (V_ * P_ + P_ - 2)
            # strictly better than V sequential full passes
            # (V * (M + 2P - 2) combined ticks), and the *extra* fwd/bwd
            # unit-slots shrink from 2V(P-1) to (VP-1) + (P-1)
            seq_ticks = V_ * pipeline_schedule_plan(P_, M_)["total"]
            assert plan["total"] < seq_ticks
            extra_units = (plan["fwd_ticks"] - M_ * V_) + (
                plan["bwd_ticks"] - M_ * V_)
            assert extra_units < 2 * V_ * (P_ - 1)
            # stash O(P*V), not O(M*V)
            assert plan["stash"] <= 2 * V_ * P_
            assert plan["stash"] < M_ * V_ or M_ * V_ <= 2 * V_ * P_

    def test_interleaved_requires_M_multiple_of_P(self):
        import pytest as _pytest
        mesh = pp_mesh()
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=PP, devices=jax.devices()[:PP])
        with _pytest.raises(ValueError, match="multiple"):
            @functools.partial(shard_map, mesh=mesh, in_specs=P(),
                               out_specs=P())
            def run(x):
                forward_backward_pipelining_with_interleaving(
                    stage_fn, loss_fn, {"w": x}, {"x": x},
                    num_microbatches=3, tensor_shape=(MB, HID),
                    pp_size=4, num_model_chunks=2)
                return x
            run(jnp.zeros((4, MB, HID)))


@pytest.mark.parametrize("P_,V_,M_", [
    (2, 1, 8),    # M > 2P-1: non-interleaved ring stash wraps
    (4, 1, 16),
    (2, 3, 8),    # M*V > 2VP: interleaved ring stash wraps
    (4, 2, 16),
])
def test_ring_stash_wraparound_parity(rng, P_, V_, M_):
    """Gradient parity for configs where the O(P) ring buffer actually
    wraps (slot = unit % S with S < M*V) — the riskiest schedule logic."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipeline_schedule_plan)
    assert pipeline_schedule_plan(P_, M_, V_)["stash"] < M_ * V_
    S_ = V_ * P_
    ws = rng.randn(S_, HID, HID).astype(np.float32) * 0.3
    xs = rng.randn(M_, MB, HID).astype(np.float32)
    ts = rng.randn(M_, MB, HID).astype(np.float32)

    def full(params, x, t):
        h = x
        for s in range(S_):
            h = jax.nn.gelu(h @ params[s])
        return jnp.mean((h - t) ** 2)

    def total(params):
        return sum(full(params, jnp.asarray(xs[m]), jnp.asarray(ts[m]))
                   for m in range(M_)) / M_

    ref_grads = np.asarray(jax.grad(total)(jnp.asarray(ws)))

    mesh = Mesh(np.asarray(jax.devices()[:P_]), ("pp",))
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=P_, devices=jax.devices()[:P_])

    def sfn(p, h, mb, is_first):
        h = jnp.where(is_first, mb["x"], h)
        return jax.nn.gelu(h @ p["w"])

    def lfn(p, y, mb):
        return jnp.mean((y - mb["t"]) ** 2)

    # rank r holds chunks c with global stage c*P + r, leaf [V, H, H]
    w_rank = np.stack([[ws[c * P_ + r] for c in range(V_)]
                       for r in range(P_)])

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P("pp"), P(), P()),
        out_specs=P("pp"))
    def run(pw, x, t):
        p = {"w": pw[0] if V_ > 1 else pw[0, 0]}
        fn = (forward_backward_pipelining_with_interleaving if V_ > 1
              else forward_backward_pipelining_without_interleaving)
        _, grads = fn(sfn, lfn, p, {"x": x, "t": t}, num_microbatches=M_,
                      tensor_shape=(MB, HID), dtype=jnp.float32,
                      pp_size=P_, num_model_chunks=V_)
        g = grads["w"]
        return g[None] if V_ > 1 else g[None, None]

    gw = np.asarray(run(jnp.asarray(w_rank), jnp.asarray(xs),
                        jnp.asarray(ts)))
    for r in range(P_):
        for c in range(V_):
            np.testing.assert_allclose(gw[r, c], ref_grads[c * P_ + r],
                                       rtol=1e-3, atol=1e-4)
