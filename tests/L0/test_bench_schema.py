"""tools/bench_schema_check over the repo's checked-in BENCH_*.json
files + the live bench._emit output format (ISSUE 2 satellite: the
bench JSON contract — incl. the telemetry fields — is now enforced)."""

import glob
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(ROOT, "tools"))
sys.path.insert(0, ROOT)

import bench_schema_check as schema  # noqa: E402


def test_checked_in_bench_jsons_valid():
    files = sorted(glob.glob(os.path.join(ROOT, "BENCH_*.json")))
    assert files, "no checked-in BENCH_*.json found"
    errors = []
    for path in files:
        schema.check_file(path, errors)
    assert errors == []


def test_cli_over_repo_root():
    assert schema.main([ROOT]) == 0


def test_wrapper_schema_rejects_bad_records():
    errors = schema.check_wrapper({"n": "one", "cmd": 3, "rc": 0},
                                  errors=[])
    joined = "\n".join(errors)
    assert "key 'n'" in joined
    assert "key 'cmd'" in joined
    assert "missing required key 'tail'" in joined
    assert "rc == 0 but no parsed metric line" in joined


def test_metric_line_requires_telemetry_fields_since_round7():
    line = {"metric": "m", "value": 1.0, "unit": "x/sec",
            "vs_baseline": 1.0, "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 10}
    # round 6: telemetry fields not yet required
    assert schema.check_metric_line(dict(line), round_n=6, errors=[]) == []
    errors = schema.check_metric_line(dict(line), round_n=7, errors=[])
    assert any("measured_comm_bytes_per_step" in e for e in errors)
    line.update(measured_comm_bytes_per_step=None,
                model_flops_per_step_xla=1e9)
    assert schema.check_metric_line(line, round_n=7, errors=[]) == []


def test_bench_error_contract_by_round():
    err = {"metric": "bench_error", "value": 0, "unit": "error",
           "vs_baseline": 0.0, "kind": "wedge"}
    assert schema.check_metric_line(dict(err), round_n=5, errors=[]) == []
    msgs = schema.check_metric_line(dict(err), round_n=6, errors=[])
    assert any("comm_bytes_per_step" in m for m in msgs)
    err["comm_bytes_per_step"] = None
    assert schema.check_metric_line(err, round_n=6, errors=[]) == []


def test_numerics_overhead_gated_at_round9():
    """ISSUE 4 satellite: numerics_overhead_pct (the ddp_numerics
    field) is defined from round 9 — older records carrying it are
    flagged, newer ones must hold a number or null."""
    line = {"metric": "ddp_numerics_steps_per_sec", "value": 1.0,
            "unit": "steps/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 10,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "numerics_overhead_pct": 3.2}
    assert schema.check_metric_line(dict(line), round_n=9, errors=[]) == []
    msgs = schema.check_metric_line(dict(line), round_n=8, errors=[])
    assert any("numerics_overhead_pct" in m for m in msgs)
    # absent stays valid at every round
    del line["numerics_overhead_pct"]
    assert schema.check_metric_line(dict(line), round_n=8, errors=[]) == []
    # type enforcement from round 9
    line["numerics_overhead_pct"] = "fast"
    msgs = schema.check_metric_line(dict(line), round_n=9, errors=[])
    assert any("must be numeric or null" in m for m in msgs)
    line["numerics_overhead_pct"] = None
    assert schema.check_metric_line(dict(line), round_n=9, errors=[]) == []


def test_memwatch_fields_gated_at_round10():
    """ISSUE 5 satellite: peak_hbm_bytes / hbm_headroom_pct /
    compile_count (the compile & memory observability fields) are
    required — nullable — from round 10; BENCH_r01-r06 records without
    them stay valid."""
    line = {"metric": "ddp_memwatch_steps_per_sec", "value": 1.0,
            "unit": "steps/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 10,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None}
    # round 9: not yet part of the contract
    assert schema.check_metric_line(dict(line), round_n=9, errors=[]) == []
    msgs = schema.check_metric_line(dict(line), round_n=10, errors=[])
    assert any("peak_hbm_bytes" in m for m in msgs)
    assert any("hbm_headroom_pct" in m for m in msgs)
    assert any("compile_count" in m for m in msgs)
    line.update(peak_hbm_bytes=123456, hbm_headroom_pct=87.5,
                compile_count=1)
    assert schema.check_metric_line(dict(line), round_n=10,
                                    errors=[]) == []
    # nullable: a config that measured neither still conforms
    line.update(peak_hbm_bytes=None, hbm_headroom_pct=None,
                compile_count=None)
    assert schema.check_metric_line(dict(line), round_n=10,
                                    errors=[]) == []
    # typed when present
    line["peak_hbm_bytes"] = "big"
    msgs = schema.check_metric_line(dict(line), round_n=10, errors=[])
    assert any("must be numeric or null" in m for m in msgs)
    line["peak_hbm_bytes"] = None
    line["compile_count"] = -2
    msgs = schema.check_metric_line(dict(line), round_n=10, errors=[])
    assert any("non-negative" in m for m in msgs)


def test_recovery_fields_gated_at_round13():
    """ISSUE 8 satellite: ddp_recovery's supervised-chaos accounting
    (restarts / mttr_steps / snapshot_restores / goodput_step_ratio)
    is required on ddp_recovery lines from round 13, and flagged on
    records from rounds where the fields did not exist."""
    base = {"metric": "ddp_recovery_steps_per_sec", "value": 1.0,
            "unit": "steps/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 10,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": None}
    line = dict(base, restarts=3, mttr_steps=2.7, snapshot_restores=2,
                goodput_step_ratio=0.64)
    assert schema.check_metric_line(dict(line), round_n=13, errors=[]) == []
    # a pre-13 record carrying them is flagged — the fields did not exist
    msgs = schema.check_metric_line(dict(line), round_n=12, errors=[])
    assert any("only defined" in m for m in msgs)
    # from 13, a ddp_recovery line without them is incomplete
    msgs = schema.check_metric_line(dict(base), round_n=13, errors=[])
    for key in ("restarts", "mttr_steps", "snapshot_restores",
                "goodput_step_ratio"):
        assert any(key in m for m in msgs)
    # other configs never need them
    other = dict(base, metric="gpt2_345m_tokens_per_sec_per_chip")
    assert schema.check_metric_line(other, round_n=13, errors=[]) == []
    # typed when present
    line["mttr_steps"] = "fast"
    msgs = schema.check_metric_line(dict(line), round_n=13, errors=[])
    assert any("must be numeric or null" in m for m in msgs)


def test_lint_violations_gated_at_round14():
    """ISSUE 9 satellite: lint_violations (the static HLO lint's
    finding count over the lowered step — apex_tpu.analysis) is
    required, nullable, on every successful metric line from round 14;
    a pre-round-14 record carrying it is flagged."""
    base = {"metric": "gpt2_345m_tokens_per_sec_per_chip", "value": 1.0,
            "unit": "tokens/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 10,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": None}
    # round 13: not yet part of the contract — absent is valid, and a
    # live line carrying it (bench._emit always writes the key) is
    # tolerated, same as the memwatch fields
    assert schema.check_metric_line(dict(base), round_n=13,
                                    errors=[]) == []
    assert schema.check_metric_line(dict(base, lint_violations=0),
                                    round_n=13, errors=[]) == []
    # from 14 the key is required
    msgs = schema.check_metric_line(dict(base), round_n=14, errors=[])
    assert any("lint_violations" in m for m in msgs)
    # nullable (bench ran without APEX_TPU_HLO_LINT=1) and zero both ok
    for val in (None, 0, 3):
        assert schema.check_metric_line(
            dict(base, lint_violations=val), round_n=14, errors=[]) == []
    # typed: negative or non-int rejected
    for bad in (-1, "clean", 1.5):
        msgs = schema.check_metric_line(
            dict(base, lint_violations=bad), round_n=14, errors=[])
        assert any("non-negative integer" in m for m in msgs)


def test_overlap_and_backend_fields_gated_at_round15():
    """ISSUE 10 satellite: the overlap contract (overlap_segments /
    comm_hidden_pct / baseline_step_ms on ddp_overlapped lines) and
    the one-shot backend probe verdict are defined from round 15 —
    overlap fields on older records are flagged, `backend` follows the
    tolerate-on-live-lines discipline."""
    base = {"metric": "gpt2_345m_tokens_per_sec_per_chip", "value": 1.0,
            "unit": "tokens/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 10,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": None, "lint_violations": None}
    # round 14: backend not yet required (tolerated when present with a
    # sane value), overlap fields did not exist
    assert schema.check_metric_line(dict(base), round_n=14,
                                    errors=[]) == []
    assert schema.check_metric_line(dict(base, backend="cpu-mesh"),
                                    round_n=14, errors=[]) == []
    msgs = schema.check_metric_line(dict(base, backend="gpu"),
                                    round_n=14, errors=[])
    assert any("backend" in m for m in msgs)
    msgs = schema.check_metric_line(dict(base, comm_hidden_pct=40.0),
                                    round_n=14, errors=[])
    assert any("only defined" in m for m in msgs)
    # round 15: backend required on every successful line
    msgs = schema.check_metric_line(dict(base), round_n=15, errors=[])
    assert any("backend" in m for m in msgs)
    base15 = dict(base, backend="cpu-mesh")
    assert schema.check_metric_line(dict(base15), round_n=15,
                                    errors=[]) == []
    for bogus in ("gpu", 3, True):
        msgs = schema.check_metric_line(dict(base15, backend=bogus),
                                        round_n=15, errors=[])
        assert any("backend" in m for m in msgs)
    # ddp_overlapped lines additionally need the overlap contract
    ovl = dict(base15, metric="ddp_overlapped_int8_steps_per_sec")
    msgs = schema.check_metric_line(dict(ovl), round_n=15, errors=[])
    assert sum("ddp_overlapped line missing" in m for m in msgs) == 3
    ovl.update(overlap_segments=4, comm_hidden_pct=47.7,
               baseline_step_ms=690.0)
    assert schema.check_metric_line(dict(ovl), round_n=15,
                                    errors=[]) == []
    # comm_hidden_pct is nullable (degenerate decomposition)
    assert schema.check_metric_line(dict(ovl, comm_hidden_pct=None),
                                    round_n=15, errors=[]) == []
    # non-overlapped lines never need the overlap fields
    assert schema.check_metric_line(dict(base15), round_n=15,
                                    errors=[]) == []


def test_fleet_fields_gated_at_round16():
    """ISSUE 11 satellite: the serve_fleet contract (per-tier p99
    TTFT, rebalance_latency_ms, replicas_respawned) is required on
    serve_fleet lines from round 16; pre-16 records carrying the
    fields are flagged, other configs never need them."""
    base = {"metric": "serve_fleet_tokens_per_sec", "value": 1.0,
            "unit": "tokens/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 0,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": 4, "lint_violations": None,
            "backend": "cpu-mesh"}
    msgs = schema.check_metric_line(dict(base), round_n=16, errors=[])
    for key in ("ttft_p99_ms_interactive", "ttft_p99_ms_batch",
                "rebalance_latency_ms", "replicas_respawned"):
        assert any(key in m for m in msgs)
    full = dict(base, ttft_p99_ms_interactive=2.0, ttft_p99_ms_batch=8.0,
                rebalance_latency_ms=1.2, replicas_respawned=1)
    assert schema.check_metric_line(dict(full), round_n=16,
                                    errors=[]) == []
    # nullable: a clean leg with no migration has no rebalance latency
    assert schema.check_metric_line(
        dict(full, rebalance_latency_ms=None), round_n=16,
        errors=[]) == []
    msgs = schema.check_metric_line(dict(full), round_n=15, errors=[])
    assert any("only defined from round 16" in m for m in msgs)
    msgs = schema.check_metric_line(
        dict(full, replicas_respawned="one"), round_n=16, errors=[])
    assert any("must be numeric or null" in m for m in msgs)
    other = dict(base, metric="gpt2_345m_tokens_per_sec_per_chip")
    assert schema.check_metric_line(other, round_n=16, errors=[]) == []


def test_serve_spec_fields_gated_at_round17():
    """ISSUE 12 satellite: the serve_spec contract
    (accepted_tokens_per_sec, acceptance_rate, prefix_hit_rate,
    ttft_p50_prefix_hit_ms) is required on serve_spec lines from round
    17; pre-17 records carrying the fields are flagged, other configs
    never need them."""
    base = {"metric": "serve_spec_accepted_tokens_per_sec",
            "value": 1200.0, "unit": "tokens/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 0,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": 9, "lint_violations": None,
            "backend": "cpu-mesh"}
    msgs = schema.check_metric_line(dict(base), round_n=17, errors=[])
    for key in ("accepted_tokens_per_sec", "acceptance_rate",
                "prefix_hit_rate", "ttft_p50_prefix_hit_ms"):
        assert any(key in m for m in msgs)
    full = dict(base, accepted_tokens_per_sec=1200.0,
                acceptance_rate=0.88, prefix_hit_rate=0.62,
                ttft_p50_prefix_hit_ms=44.3)
    assert schema.check_metric_line(dict(full), round_n=17,
                                    errors=[]) == []
    # nullable: a trace that never hit the store has no hit-TTFT p50
    assert schema.check_metric_line(
        dict(full, ttft_p50_prefix_hit_ms=None), round_n=17,
        errors=[]) == []
    msgs = schema.check_metric_line(dict(full), round_n=16, errors=[])
    assert any("only defined from round 17" in m for m in msgs)
    msgs = schema.check_metric_line(
        dict(full, acceptance_rate="high"), round_n=17, errors=[])
    assert any("must be numeric or null" in m for m in msgs)
    other = dict(base, metric="serve_decode_tokens_per_sec_per_chip",
                 ttft_p50_ms=1.0, ttft_p99_ms=2.0,
                 tok_latency_p50_ms=0.5, tok_latency_p99_ms=1.0,
                 kv_cache_bytes=1024)
    assert schema.check_metric_line(other, round_n=17, errors=[]) == []


def test_static_comm_gated_at_round18():
    """ISSUE 13 satellite: static_comm_bytes_per_step (the collective
    dataflow graph's ring-model wire bytes parsed from the lowered
    step — apex_tpu.analysis.sharding) is required, nullable, on every
    successful metric line from round 18; a pre-round-18 record
    carrying a measured value is flagged (the field did not exist
    yet), while the always-written null key on live lines is
    tolerated, same as lint_violations."""
    base = {"metric": "gpt2_345m_tokens_per_sec_per_chip", "value": 1.0,
            "unit": "tokens/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 1.0, "mfu": 0.1,
            "comm_bytes_per_step": 10,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": None, "lint_violations": None,
            "backend": "cpu-mesh"}
    # round 17: absent is valid and the always-written key is
    # tolerated on LIVE lines (lint_violations discipline)
    assert schema.check_metric_line(dict(base), round_n=17,
                                    errors=[]) == []
    assert schema.check_metric_line(
        dict(base, static_comm_bytes_per_step=None), round_n=17,
        errors=[]) == []
    # ... but a CHECKED-IN pre-18 record carrying a measured value is
    # flagged — the field did not exist at capture time
    wrapper = {"n": 17, "cmd": "python bench.py", "rc": 0, "tail": "",
               "parsed": dict(base, static_comm_bytes_per_step=1820)}
    msgs = schema.check_wrapper(wrapper, errors=[])
    assert any("only defined from round 18" in m for m in msgs)
    assert schema.check_wrapper(
        {"n": 18, "cmd": "c", "rc": 0, "tail": "",
         "parsed": dict(base, static_comm_bytes_per_step=1820)},
        errors=[]) == []
    # from 18 the key is required
    msgs = schema.check_metric_line(dict(base), round_n=18, errors=[])
    assert any("static_comm_bytes_per_step" in m for m in msgs)
    # nullable (no step measured) and measured values both ok
    for val in (None, 0, 1820, 58695.0):
        assert schema.check_metric_line(
            dict(base, static_comm_bytes_per_step=val), round_n=18,
            errors=[]) == []
    # typed: negative or non-numeric rejected
    for bad in (-1, "many", True):
        msgs = schema.check_metric_line(
            dict(base, static_comm_bytes_per_step=bad), round_n=18,
            errors=[])
        assert any("non-negative number" in m for m in msgs)


def test_kernels_fields_gated_at_round19():
    """ISSUE 14 satellite: the kernels capture contract — per-family
    kernel-vs-XLA timings on kernels lines, the int4 dual-quantization
    wire model on ddp_compressed lines — is required from round 19;
    pre-19 records carrying the fields are flagged, other configs
    never need them."""
    base = {"metric": "kernels_speedup_geomean", "value": 1.0,
            "unit": "x", "vs_baseline": 1.0,
            "tflops_per_sec": 0.0, "mfu": 0.0,
            "comm_bytes_per_step": 0,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": None, "lint_violations": None,
            "static_comm_bytes_per_step": None,
            "backend": "cpu-mesh"}
    # round 19: every per-family timing pair is required
    msgs = schema.check_metric_line(dict(base), round_n=19, errors=[])
    for key in schema.KERNELS_REQUIRED_FIELDS:
        assert any(key in m for m in msgs)
    full = dict(base, **{k: 1.5 for k in
                         schema.KERNELS_REQUIRED_FIELDS})
    assert schema.check_metric_line(dict(full), round_n=19,
                                    errors=[]) == []
    # nullable (a family whose leg crashed records null)
    assert schema.check_metric_line(
        dict(full, lamb_kernel_ms=None), round_n=19, errors=[]) == []
    # pre-19 records carrying them are flagged
    msgs = schema.check_metric_line(dict(full), round_n=18, errors=[])
    assert any("only defined from round 19" in m for m in msgs)
    # typed
    msgs = schema.check_metric_line(
        dict(full, adam_xla_ms="fast"), round_n=19, errors=[])
    assert any("must be numeric or null" in m for m in msgs)

    # ddp_compressed: comm_bytes_per_step_int4 required from 19
    ddp = dict(base, metric="ddp_compressed_int8_steps_per_sec",
               value=1.1, unit="steps/sec")
    msgs = schema.check_metric_line(dict(ddp), round_n=19, errors=[])
    assert any("comm_bytes_per_step_int4" in m for m in msgs)
    assert schema.check_metric_line(
        dict(ddp, comm_bytes_per_step_int4=23275007), round_n=19,
        errors=[]) == []
    msgs = schema.check_metric_line(
        dict(ddp, comm_bytes_per_step_int4=23275007), round_n=18,
        errors=[])
    assert any("only defined from round 19" in m for m in msgs)
    # other configs never need the kernels fields at round 19
    assert schema.check_metric_line(dict(base, metric="resnet50_amp_o2"),
                                    round_n=19, errors=[]) == []


def test_pp_tp_dp_fields_gated_at_round22():
    """ISSUE 17 satellite: a pp_tp_dp metric line must carry the 1F1B
    bubble fraction next to its analytic model, the schedule shape,
    the baseline-vs-overlapped step times, the per-axis comm dicts
    WITH the pipe axis priced, and the 3-D reshard verdict from round
    22; pre-22 records carrying the pipeline-only fields are flagged,
    other configs never need them."""
    base = {"metric": "pp_tp_dp_steps_per_sec", "value": 46.0,
            "unit": "steps/sec", "vs_baseline": 1.0,
            "tflops_per_sec": 0.0, "mfu": 0.0,
            "comm_bytes_per_step": 35608,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": None, "lint_violations": None,
            "static_comm_bytes_per_step": None,
            "backend": "cpu-mesh"}
    axis = {"data": 35364, "model": 245760, "pipe": 102928}
    full = dict(base, bubble_fraction=0.13, bubble_fraction_model=0.2,
                pipeline_stages=2, microbatches=4,
                baseline_step_ms=24.1, overlapped_step_ms=21.5,
                measured_comm_bytes_per_axis=dict(axis),
                static_comm_bytes_per_axis=dict(axis),
                reshard_bitexact=True)
    assert schema.check_metric_line(dict(full), round_n=22,
                                    errors=[]) == []
    # round 22: every pipeline field is required on pp_tp_dp lines
    msgs = schema.check_metric_line(dict(base), round_n=22, errors=[])
    for key in schema.PP_TP_DP_REQUIRED_FIELDS:
        assert any(key in m for m in msgs)
    # the per-axis dicts must price the pipe axis
    two_axis = {"data": 1, "model": 2}
    msgs = schema.check_metric_line(
        dict(full, measured_comm_bytes_per_axis=two_axis),
        round_n=22, errors=[])
    assert any("must price the 'pipe' axis" in m for m in msgs)
    # nullable (single-device run measures nothing) and typed
    assert schema.check_metric_line(
        dict(full, bubble_fraction=None,
             measured_comm_bytes_per_axis=None), round_n=22,
        errors=[]) == []
    msgs = schema.check_metric_line(
        dict(full, bubble_fraction="small"), round_n=22, errors=[])
    assert any("must be numeric" in m for m in msgs)
    msgs = schema.check_metric_line(
        dict(full, static_comm_bytes_per_axis={"pipe": "many"}),
        round_n=22, errors=[])
    assert any("axis-name" in m for m in msgs)
    # pre-22 checked-in records carrying the pipeline-only fields are
    # flagged — the fields did not exist at capture time
    wrapper = {"n": 21, "cmd": "python bench.py pp_tp_dp", "rc": 0,
               "tail": "", "parsed": dict(full)}
    msgs = schema.check_wrapper(wrapper, errors=[])
    assert any("only defined from round 22" in m for m in msgs)
    assert schema.check_wrapper(
        {"n": 22, "cmd": "c", "rc": 0, "tail": "",
         "parsed": dict(full)}, errors=[]) == []
    # other configs never need the pipeline fields at round 22, and
    # tp_dp lines keep their own (round-20) contract untouched
    assert schema.check_metric_line(dict(base, metric="resnet50_amp_o2"),
                                    round_n=22, errors=[]) == []
    tp = dict(base, metric="tp_dp_steps_per_sec",
              baseline_step_ms=1.0, overlapped_step_ms=0.9,
              measured_comm_bytes_per_axis={"data": 1, "model": 2},
              static_comm_bytes_per_axis={"data": 1, "model": 2},
              reshard_bitexact=True)
    assert schema.check_metric_line(dict(tp), round_n=22,
                                    errors=[]) == []


def test_serve_migrate_fields_gated_at_round23():
    """ISSUE 18 satellite: a serve_migrate metric line must carry the
    KV-state migration contract from round 23 — the short/long-context
    migration wall-times (the flat-cost claim), the fleet handoff byte
    count, the loud checksum-fallback count, and the fleet-wide prefix
    hit rate, all nullable; pre-23 records carrying any of them are
    flagged, other configs never need them."""
    base = {"metric": "serve_migrate_migration_ms", "value": 12.7,
            "unit": "ms", "vs_baseline": 1.0,
            "tflops_per_sec": 0.0, "mfu": 0.0,
            "comm_bytes_per_step": 0,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": None, "lint_violations": None,
            "static_comm_bytes_per_step": None,
            "backend": "cpu-mesh"}
    full = dict(base, migration_ms_short_ctx=14.5,
                migration_ms_long_ctx=12.7, kv_handoff_bytes=131080,
                fallback_reprefills=0, fleet_prefix_hit_rate=0.09)
    assert schema.check_metric_line(dict(full), round_n=23,
                                    errors=[]) == []
    # round 23: every migration field is required on serve_migrate lines
    msgs = schema.check_metric_line(dict(base), round_n=23, errors=[])
    for key in schema.SERVE_MIGRATE_REQUIRED_FIELDS:
        assert any(key in m for m in msgs)
    # nullable (a smoke host that skipped a leg stays honest) and typed
    assert schema.check_metric_line(
        dict(full, fleet_prefix_hit_rate=None,
             migration_ms_long_ctx=None), round_n=23, errors=[]) == []
    msgs = schema.check_metric_line(
        dict(full, kv_handoff_bytes="lots"), round_n=23, errors=[])
    assert any("must be numeric" in m for m in msgs)
    # pre-23 checked-in records carrying the migration-only fields are
    # flagged — the fields did not exist at capture time
    wrapper = {"n": 22, "cmd": "python bench.py serve_migrate",
               "rc": 0, "tail": "", "parsed": dict(full)}
    msgs = schema.check_wrapper(wrapper, errors=[])
    assert any("only defined from round 23" in m for m in msgs)
    assert schema.check_wrapper(
        {"n": 23, "cmd": "c", "rc": 0, "tail": "",
         "parsed": dict(full)}, errors=[]) == []
    # other configs never need the migration fields at round 23, and
    # serve_fleet lines keep their own (round-16) contract untouched
    assert schema.check_metric_line(dict(base, metric="resnet50_amp_o2"),
                                    round_n=23, errors=[]) == []
    fleet = dict(base, metric="serve_fleet_tokens_per_sec",
                 ttft_p99_ms_interactive=1.0, ttft_p99_ms_batch=2.0,
                 rebalance_latency_ms=3.0, replicas_respawned=1)
    assert schema.check_metric_line(dict(fleet), round_n=23,
                                    errors=[]) == []


def test_trace_overhead_fields_gated_at_round24():
    """ISSUE 19 satellite: a trace_overhead metric line must carry the
    causal-tracing contract from round 24 — span_count, the on-vs-off
    overhead percentage, both leg step times, and the disabled-leg
    event count (which must be 0 — the zero-overhead-off proof), all
    nullable; pre-24 records carrying any of them are flagged, other
    configs never need them."""
    base = {"metric": "trace_overhead_step_ms", "value": 11.5,
            "unit": "ms", "vs_baseline": 1.0,
            "tflops_per_sec": 0.01, "mfu": 0.0001,
            "comm_bytes_per_step": 0,
            "measured_comm_bytes_per_step": None,
            "model_flops_per_step_xla": None,
            "peak_hbm_bytes": None, "hbm_headroom_pct": None,
            "compile_count": 1, "lint_violations": None,
            "static_comm_bytes_per_step": None,
            "backend": "cpu-mesh"}
    full = dict(base, span_count=60, tracing_overhead_pct=0.8,
                untraced_step_ms=11.1, traced_step_ms=11.2,
                disabled_leg_events=0)
    assert schema.check_metric_line(dict(full), round_n=24,
                                    errors=[]) == []
    # round 24: every tracing field is required on trace_overhead lines
    msgs = schema.check_metric_line(dict(base), round_n=24, errors=[])
    for key in schema.TRACE_OVERHEAD_REQUIRED_FIELDS:
        assert any(key in m for m in msgs)
    # nullable (a host that skipped a leg stays honest) and typed
    assert schema.check_metric_line(
        dict(full, tracing_overhead_pct=None, untraced_step_ms=None),
        round_n=24, errors=[]) == []
    msgs = schema.check_metric_line(
        dict(full, span_count="many"), round_n=24, errors=[])
    assert any("must be numeric" in m for m in msgs)
    # a nonzero disabled-leg event count is a contract violation, not
    # just a number — the disabled registry recorded something
    msgs = schema.check_metric_line(
        dict(full, disabled_leg_events=3), round_n=24, errors=[])
    assert any("zero-overhead-off" in m for m in msgs)
    # pre-24 checked-in records carrying the tracing-only fields are
    # flagged — the fields did not exist at capture time
    wrapper = {"n": 23, "cmd": "python bench.py trace_overhead",
               "rc": 0, "tail": "", "parsed": dict(full)}
    msgs = schema.check_wrapper(wrapper, errors=[])
    assert any("only defined from round 24" in m for m in msgs)
    assert schema.check_wrapper(
        {"n": 24, "cmd": "c", "rc": 0, "tail": "",
         "parsed": dict(full)}, errors=[]) == []
    # other configs never need the tracing fields at round 24, and
    # serve_migrate lines keep their own (round-23) contract untouched
    assert schema.check_metric_line(dict(base, metric="resnet50_amp_o2"),
                                    round_n=24, errors=[]) == []
    migrate = dict(base, metric="serve_migrate_migration_ms",
                   migration_ms_short_ctx=14.5,
                   migration_ms_long_ctx=12.7, kv_handoff_bytes=131080,
                   fallback_reprefills=0, fleet_prefix_hit_rate=0.09)
    assert schema.check_metric_line(dict(migrate), round_n=24,
                                    errors=[]) == []


def test_live_emit_passes_current_schema(capsys):
    """What bench._emit prints today must satisfy the round-14
    (current) metric-line contract — telemetry + memwatch + lint
    fields included."""
    import bench

    bench._emit("unit_test_metric", 12.5, "things/sec",
                flops_per_step=1e9, steps=10, dt=1.0,
                **bench._comm_fields(n_elements=1000))
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert schema.check_metric_line(line, round_n=7, errors=[]) == []
    assert schema.check_metric_line(line, round_n=10, errors=[]) == []
    assert schema.check_metric_line(line, round_n=14, errors=[]) == []
    assert schema.check_metric_line(line, round_n=15, errors=[]) == []
    assert schema.check_metric_line(line, round_n=18, errors=[]) == []
    assert line["backend"] == "cpu-mesh"  # the tests' virtual mesh
    assert line["measured_comm_bytes_per_step"] is None  # none staged
    assert line["peak_hbm_bytes"] is None                # none staged
    assert line["compile_count"] is None                 # none staged
    assert line["lint_violations"] is None               # none staged
    assert line["static_comm_bytes_per_step"] is None    # none staged
    assert "comm_bytes_per_step" in line


def test_live_bench_error_passes_current_schema(capsys):
    import bench

    bench._emit_bench_error("unit test error", "crash")
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert schema.check_metric_line(line, round_n=7, errors=[]) == []


@pytest.mark.parametrize("bad", [
    {"metric": "m"},                              # missing most keys
    {"metric": "m", "value": True, "unit": "u",   # bool is not numeric
     "vs_baseline": 1.0},
])
def test_metric_line_rejects(bad):
    assert schema.check_metric_line(bad, errors=[]) != []
