"""contrib/gqa_decode: the streaming KV-cache decode kernel must be
token-exact against the einsum decode path — interpreter mode runs the
REAL kernel dataflow (tile index clamping, online softmax, scalar
prefetch) on the CPU mesh, and the end-to-end tests drive it through
``generate()`` so the model-integration gate (s == 1, no alibi) is what
is actually tested."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib import gqa_decode
from apex_tpu.models import GPTModel, TransformerConfig, generate
from apex_tpu.transformer import parallel_state


@pytest.fixture(autouse=True)
def _interpret():
    parallel_state.destroy_model_parallel()
    gqa_decode.force_interpret(True)
    yield
    gqa_decode.force_interpret(False)


@pytest.mark.parametrize("g,rep", [(2, 2), (4, 1), (1, 4)])
@pytest.mark.parametrize("window,softcap", [(None, None), (7, None),
                                            (None, 30.0), (6, 25.0)])
def test_kernel_matches_reference(g, rep, window, softcap):
    """GQA/MHA/MQA head layouts x {window, softcap}: kernel == einsum
    oracle at several live lengths incl. tile-boundary cases."""
    rng = np.random.RandomState(g * 10 + rep)
    b, d, T = 2, 16, 64
    q = jnp.asarray(rng.randn(b, g, rep, d).astype(np.float32))
    k = jnp.asarray(rng.randn(T, b, g, d).astype(np.float32))
    v = jnp.asarray(rng.randn(T, b, g, d).astype(np.float32))
    for length in (1, 5, 32, 33, 64):
        want = gqa_decode.gqa_decode_reference(
            q, k, v, length, 0.25, window=window, softcap=softcap)
        got = gqa_decode.gqa_flash_decode(
            q, k, v, length, 0.25, window=window, softcap=softcap,
            block_t=32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def _gen_cfg(**kw):
    return TransformerConfig(
        hidden_size=48, num_layers=2, num_attention_heads=4,
        vocab_size=96, max_position_embeddings=32,
        compute_dtype=jnp.float32, use_flash_attention=False,
        normalization="rmsnorm", position_embedding_type="rope",
        activation="swiglu", num_query_groups=2, **kw)


@pytest.mark.parametrize("case", ["plain", "window", "gemma2"])
def test_generate_token_exact_kernel_vs_einsum(case, monkeypatch):
    """End-to-end greedy decode: the kernel path (forced interpret) must
    emit exactly the tokens the einsum path emits — through the real
    model gate (single-token steps only; the prefill chunk stays on
    the chunked einsum)."""
    kw = {}
    if case == "window":
        kw = dict(sliding_window=5)
    elif case == "gemma2":
        kw = dict(sliding_window=5, sliding_window_pattern=2,
                  sandwich_norm=True, attn_logit_softcapping=30.0,
                  query_pre_attn_scalar=20.0)
    cfg = _gen_cfg(**kw)
    model = GPTModel(cfg, decode=True)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, 96, size=(2, 9)))
    params = model.init(jax.random.PRNGKey(2), prompt)["params"]

    out_kernel = generate(model, params, prompt, 10)

    monkeypatch.setenv("APEX_TPU_DECODE_FLASH", "0")
    gqa_decode.force_interpret(False)
    # fresh jit cache entries: the flag is read at trace time
    from apex_tpu.models import generation as gen_mod

    gen_mod._compiled.cache_clear()
    out_einsum = generate(model, params, prompt, 10)
    np.testing.assert_array_equal(np.asarray(out_kernel),
                                  np.asarray(out_einsum))


def test_alibi_stays_on_einsum(monkeypatch):
    """ALiBi decode must NOT take the kernel (it carries no position
    bias): with the kernel gate ON (interpret), tokens must equal the
    flag-off einsum run — if a future edit dropped the alibi exclusion
    from the gate, the slope bias would vanish and tokens diverge."""
    cfg = TransformerConfig(
        hidden_size=48, num_layers=2, num_attention_heads=4,
        vocab_size=96, max_position_embeddings=32,
        compute_dtype=jnp.float32, use_flash_attention=False,
        position_embedding_type="alibi")
    model = GPTModel(cfg, decode=True)
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, 96, size=(1, 6)))
    params = model.init(jax.random.PRNGKey(4), prompt)["params"]
    out_gated = generate(model, params, prompt, 6)

    from apex_tpu.models import generation as gen_mod

    monkeypatch.setenv("APEX_TPU_DECODE_FLASH", "0")
    gqa_decode.force_interpret(False)
    gen_mod._compiled.cache_clear()
    out_einsum = generate(model, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out_gated),
                                  np.asarray(out_einsum))


def test_block_ladder_nondivisible_buffers():
    """A 1280-long buffer is not a 512-multiple but IS a 256-multiple:
    the ladder must pick 256 and keep the kernel (review finding) —
    parity at a length crossing several 256-tiles."""
    from apex_tpu.contrib._pallas_gate import choose_block

    assert choose_block(1280, 512) == 256
    assert choose_block(1536, 512) == 512
    assert choose_block(100, 512) == 100
    assert choose_block(1283, 512) is None

    rng = np.random.RandomState(0)
    b, g, rep, d, T = 1, 2, 2, 8, 1280
    q = jnp.asarray(rng.randn(b, g, rep, d).astype(np.float32))
    k = jnp.asarray(rng.randn(T, b, g, d).astype(np.float32))
    v = jnp.asarray(rng.randn(T, b, g, d).astype(np.float32))
    assert gqa_decode.use_flash(T)
    want = gqa_decode.gqa_decode_reference(q, k, v, 700, 0.3)
    got = gqa_decode.gqa_flash_decode(q, k, v, 700, 0.3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
