"""Megatron-style launch-command parity for the harness argument parser.

Parity: reference apex/transformer/testing/arguments.py — external
Megatron/NeMo launch scripts must parse unchanged, dependent values must
derive the same way (padded vocab, data-parallel split, virtual-pipeline
geometry), and cross-flag violations must fail loudly.
"""

import pytest

from apex_tpu.transformer.testing.arguments import parse_args


@pytest.fixture(autouse=True)
def _world(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "8")


def test_megatron_launch_command_parses():
    # a realistic Megatron-LM pretraining command line, verbatim flags
    args = parse_args(args=[
        "--num-layers", "24", "--hidden-size", "1024",
        "--num-attention-heads", "16", "--seq-length", "1024",
        "--max-position-embeddings", "1024",
        "--micro-batch-size", "4", "--global-batch-size", "8",
        "--lr", "0.00015", "--train-iters", "500000",
        "--lr-decay-iters", "320000", "--lr-decay-style", "cosine",
        "--vocab-file", "gpt2-vocab.json", "--merge-file", "gpt2-merges.txt",
        "--data-path", "my-gpt2_text_document", "--split", "949,50,1",
        "--weight-decay", "0.01", "--clip-grad", "1.0",
        "--lr-warmup-fraction", ".01", "--activations-checkpoint-method",
        "uniform", "--bf16", "--tensor-model-parallel-size", "2",
        "--pipeline-model-parallel-size", "2", "--sequence-parallel",
    ])
    assert args.data_parallel_size == 2  # 8 / (tp=2 * pp=2)
    assert args.ffn_hidden_size == 4096
    assert args.kv_channels == 64
    assert args.sequence_parallel
    assert args.bf16 and not args.fp16
    assert args.encoder_seq_length == 1024


def test_padded_vocab_derivation():
    args = parse_args(args=["--vocab-size", "50257",
                            "--make-vocab-size-divisible-by", "128",
                            "--tensor-model-parallel-size", "2"])
    assert args.padded_vocab_size == 50432  # next multiple of 256
    assert args.padded_vocab_size % 256 == 0


def test_virtual_pipeline_from_layers_per_stage():
    args = parse_args(args=[
        "--num-layers", "16", "--pipeline-model-parallel-size", "4",
        "--num-layers-per-virtual-pipeline-stage", "2"])
    assert args.virtual_pipeline_model_parallel_size == 2

    with pytest.raises(ValueError, match="divide"):
        parse_args(args=[
            "--num-layers", "16", "--pipeline-model-parallel-size", "4",
            "--num-layers-per-virtual-pipeline-stage", "3"])


def test_deprecated_aliases_fold_in():
    args = parse_args(args=["--model-parallel-size", "4",
                            "--batch-size", "16"])
    assert args.tensor_model_parallel_size == 4
    assert args.micro_batch_size == 16
    assert args.data_parallel_size == 2


def test_checkpoint_activations_maps_to_recompute():
    args = parse_args(args=["--checkpoint-activations"])
    assert args.recompute_granularity == "full"
    assert args.recompute_method == "uniform"
    sel = parse_args(args=["--recompute-activations"])
    assert sel.recompute_granularity == "selective"


def test_train_samples_bounds_iterations():
    args = parse_args(args=["--train-samples", "1000",
                            "--micro-batch-size", "1",
                            "--global-batch-size", "10"])
    assert args.train_iters == 100


def test_negated_store_false_flags():
    args = parse_args(args=["--no-bias-gelu-fusion",
                            "--no-masked-softmax-fusion"])
    assert not args.bias_gelu_fusion
    assert not args.masked_softmax_fusion
    assert args.bias_dropout_fusion  # untouched default stays on


def test_invalid_combinations_raise():
    with pytest.raises(ValueError, match="mutually exclusive"):
        parse_args(args=["--fp16", "--bf16"])
    with pytest.raises(ValueError, match="not divisible"):
        parse_args(args=["--tensor-model-parallel-size", "3"])
    with pytest.raises(ValueError, match="split rank"):
        parse_args(args=["--pipeline-model-parallel-size", "2",
                         "--pipeline-model-parallel-split-rank", "5"])
    with pytest.raises(ValueError, match="standalone-embedding"):
        parse_args(args=["--standalone-embedding-stage"])


def test_vision_and_retriever_tails_parse():
    # flags the TPU harness never consumes must still parse (ported
    # launch scripts carry them)
    args = parse_args(args=[
        "--vision-pretraining", "--vision-backbone-type", "swin",
        "--dino-teacher-temp", "0.05", "--ict-head-size", "128",
        "--retriever-report-topk-accuracies", "1", "5", "20",
        "--indexer-batch-size", "64"])
    assert args.swin_backbone_type == "tiny"
    assert args.retriever_report_topk_accuracies == [1, 5, 20]


def test_extra_args_provider_and_defaults():
    def extra(parser):
        parser.add_argument("--my-extra", type=int, default=None)
        return parser

    args = parse_args(extra_args_provider=extra,
                      defaults={"my_extra": 7, "seq_length": 64},
                      args=[])
    assert args.my_extra == 7


def test_unknown_args_ignored_by_default():
    args = parse_args(args=["--definitely-not-a-flag", "x",
                            "--hidden-size", "128"])
    assert args.hidden_size == 128
