"""Mixture-of-experts: routing, SwitchMLP, expert parallelism.

No reference counterpart (juncongmoo/apex has no MoE — SURVEY.md §2.3);
tests follow the house style of test_transformer_tp.py: numerics vs
hand-computed references on a single device, then ep-sharded vs local
equivalence on the virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.testing import shard_map
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import (
    SwitchMLP,
    compute_routing,
    is_expert_param,
    moe_loss_from_variables,
)
from apex_tpu.transformer.moe.router import expert_capacity


class TestRouting:
    def test_top1_dispatch_and_capacity_drop(self):
        # 4 tokens, 2 experts; tokens 0,1,2 prefer expert 0, token 3
        # prefers expert 1. Capacity 2 -> token 2 is dropped.
        logits = jnp.array([[2.0, 0.0],
                            [2.0, 0.0],
                            [2.0, 0.0],
                            [0.0, 2.0]])
        r = compute_routing(logits, top_k=1, capacity=2)
        d = np.asarray(r.dispatch_mask)
        # tokens 0,1 fill expert-0 slots 0,1 in arrival order
        assert d[0, 0, 0] == 1 and d[1, 0, 1] == 1
        assert d[2].sum() == 0  # dropped
        assert d[3, 1, 0] == 1
        probs = np.asarray(r.probs)
        c = np.asarray(r.combine_weights)
        np.testing.assert_allclose(c[0, 0, 0], probs[0, 0], rtol=1e-6)
        np.testing.assert_allclose(c[3, 1, 0], probs[3, 1], rtol=1e-6)
        assert c[2].sum() == 0
        np.testing.assert_allclose(float(r.dropped_fraction), 0.25)

    def test_top2_normalized_weights(self):
        logits = jnp.array([[1.0, 0.5, -1.0],
                            [0.2, 1.4, 0.3]])
        r = compute_routing(logits, top_k=2, capacity=2)
        # each token keeps both choices; normalized weights sum to 1
        w = np.asarray(r.combine_weights).sum(axis=(1, 2))
        np.testing.assert_allclose(w, [1.0, 1.0], rtol=1e-5)
        assert np.asarray(r.dispatch_mask).sum() == 4

    def test_aux_loss_balanced_is_one(self):
        # perfectly balanced hard assignments with near-uniform probs:
        # f_e = 1/E and P_e ~ 1/E -> aux = E * sum f*P ~ 1
        eps = 1e-3
        logits = jnp.array([[eps, 0.0], [0.0, eps]] * 8)
        r = compute_routing(logits, top_k=1, capacity=16)
        np.testing.assert_allclose(float(r.aux_loss), 1.0, atol=1e-3)

    def test_aux_loss_penalizes_collapse(self):
        all_to_one = jnp.tile(jnp.array([[4.0, 0.0]]), (16, 1))
        r = compute_routing(all_to_one, top_k=1, capacity=16)
        assert float(r.aux_loss) > 1.5  # E * 1 * P_0, P_0 ~ 0.98

    def test_z_loss(self):
        logits = jnp.zeros((4, 4))
        r = compute_routing(logits, top_k=1, capacity=4)
        np.testing.assert_allclose(float(r.z_loss), np.log(4.0) ** 2,
                                   rtol=1e-5)

    def test_capacity_rounding(self):
        assert expert_capacity(1024, 8, 1, 1.25) == 160
        # tiny raw capacities round up to the TPU lane multiple too
        assert expert_capacity(16, 8, 1, 1.0) == 8


class TestExpertChoiceRouting:
    def test_each_expert_fills_capacity(self):
        from apex_tpu.transformer.moe import compute_expert_choice_routing

        logits = jnp.asarray(np.random.RandomState(0).randn(8, 3),
                             jnp.float32)
        r = compute_expert_choice_routing(logits, capacity=2)
        d = np.asarray(r.dispatch_mask)  # [T, E, C]
        # every expert fills exactly its 2 slots — balanced by construction
        np.testing.assert_array_equal(d.sum(axis=(0, 2)), [2, 2, 2])
        assert float(r.aux_loss) == 0.0
        # combine weight at a filled slot equals that token's prob
        probs = np.asarray(r.probs)
        c = np.asarray(r.combine_weights)
        t, e, s = np.argwhere(d > 0)[0]
        np.testing.assert_allclose(c[t, e, s], probs[t, e], rtol=1e-6)

    def test_expert_picks_its_top_tokens(self):
        from apex_tpu.transformer.moe import compute_expert_choice_routing

        # expert 0 strongly prefers tokens 1 and 3
        logits = jnp.array([[0.0, 1.0],
                            [5.0, 0.0],
                            [0.1, 1.0],
                            [4.0, 0.0]])
        r = compute_expert_choice_routing(logits, capacity=2)
        d = np.asarray(r.dispatch_mask)
        assert d[1, 0].sum() == 1 and d[3, 0].sum() == 1
        # tokens 0 and 2 were not chosen by expert 0
        assert d[0, 0].sum() == 0 and d[2, 0].sum() == 0

    def test_dropped_fraction_counts_unpicked_tokens(self):
        from apex_tpu.transformer.moe import compute_expert_choice_routing

        # 4 tokens, 1 expert, capacity 2 -> 2 tokens unpicked
        logits = jnp.asarray(np.random.RandomState(1).randn(4, 1),
                             jnp.float32)
        r = compute_expert_choice_routing(logits, capacity=2)
        np.testing.assert_allclose(float(r.dropped_fraction), 0.5)

    def test_switch_mlp_expert_choice_grads(self):
        layer = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=4,
                          capacity_factor=2.0, router_type="expert_choice",
                          compute_dtype=jnp.float32)
        x = jnp.asarray(np.random.RandomState(0).randn(8, 2, 16), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]

        def loss(p):
            return jnp.sum(layer.apply({"params": p}, x,
                                       mutable=["moe_losses"])[0] ** 2)

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["router"]["gate_weight"]).sum()) > 0
        assert float(jnp.abs(g["experts"]["w1"]).sum()) > 0

    @pytest.mark.slow  # tier-1 budget (round 18): EP-vs-local parity
    # is covered by test_ep4_matches_local and the expert-choice
    # routing by test_switch_mlp_expert_choice_grads
    def test_expert_choice_ep_matches_local(self):
        E, ep = 4, 4
        rng = np.random.RandomState(7)
        params = {
            "router": {"gate_weight": jnp.asarray(
                rng.randn(16, E) * 0.2, jnp.float32)},
            "experts": {
                "w1": jnp.asarray(rng.randn(E, 16, 32) * 0.1, jnp.float32),
                "b1": jnp.zeros((E, 32), jnp.float32),
                "w2": jnp.asarray(rng.randn(E, 32, 16) * 0.1, jnp.float32),
                "b2": jnp.zeros((E, 16), jnp.float32),
            },
        }
        x = jnp.asarray(rng.randn(8, ep, 16), jnp.float32)
        parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=ep, devices=jax.devices()[:ep])
        mesh = parallel_state.get_mesh()
        layer = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=E,
                          capacity_factor=2.0, router_type="expert_choice",
                          compute_dtype=jnp.float32)

        saved = parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE
        parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = 1
        ref = jnp.concatenate(
            [layer.apply({"params": params}, x[:, i:i + 1])
             for i in range(ep)], axis=1)
        parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = saved

        pspec = {"router": {"gate_weight": P()},
                 "experts": {k: P("ep") for k in params["experts"]}}

        @shard_map(mesh=mesh, in_specs=(pspec, P(None, "ep", None)),
                   out_specs=P(None, "ep", None))
        def run(p, xs):
            return layer.apply({"params": p}, xs)

        np.testing.assert_allclose(np.asarray(run(params, x)),
                                   np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_unknown_router_type_raises(self):
        layer = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=2,
                          router_type="nonsense", compute_dtype=jnp.float32)
        x = jnp.ones((4, 1, 16))
        with pytest.raises(ValueError, match="router_type"):
            layer.init(jax.random.PRNGKey(0), x)

    @pytest.mark.slow  # tier-1 budget: routing units above cover EC
    def test_gpt_expert_choice_config(self):
        from apex_tpu.models import GPTModel, TransformerConfig
        from apex_tpu.models.gpt import gpt_loss_fn

        parallel_state.destroy_model_parallel()
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            compute_dtype=jnp.float32, use_flash_attention=False,
            num_moe_experts=4, moe_router_type="expert_choice")
        model = GPTModel(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 64, size=(2, 16)))
        variables = model.init(jax.random.PRNGKey(0), tokens)

        def loss_fn(p):
            logits, _ = model.apply({"params": p}, tokens,
                                    mutable=["moe_losses"])
            return gpt_loss_fn(logits, jnp.roll(tokens, -1, axis=-1))

        loss, g = jax.value_and_grad(loss_fn)(variables["params"])
        assert np.isfinite(float(loss))
        router_g = g["transformer"]["layer_0"]["mlp"]["router"]["gate_weight"]
        assert float(jnp.abs(router_g).sum()) > 0


class TestSwitchMLP:
    def _make(self, num_experts=4, top_k=1, capacity=64, hidden=16, ffn=32):
        layer = SwitchMLP(hidden_size=hidden, ffn_hidden_size=ffn,
                          num_experts=num_experts, top_k=top_k,
                          capacity_factor=8.0,  # ample: no drops
                          compute_dtype=jnp.float32)
        x = jnp.asarray(np.random.RandomState(0).randn(8, 2, hidden),
                        jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        return layer, params, x

    def test_single_expert_equals_dense_mlp(self):
        """E=1 with ample capacity routes every token through the one
        expert with weight 1 — output must equal the plain FFN."""
        layer, params, x = self._make(num_experts=1)
        out = layer.apply({"params": params}, x)
        e = params["experts"]
        t = x.reshape(-1, x.shape[-1])
        h1 = t @ np.asarray(e["w1"])[0] + np.asarray(e["b1"])[0]
        ref = jax.nn.gelu(h1) @ np.asarray(e["w2"])[0] + np.asarray(e["b2"])[0]
        np.testing.assert_allclose(np.asarray(out).reshape(-1, x.shape[-1]),
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_moe_losses_sown(self):
        layer, params, x = self._make()
        out, mut = layer.apply({"params": params}, x,
                               mutable=["moe_losses"])
        total = moe_loss_from_variables(mut, aux_loss_coeff=1.0)
        assert float(total) > 0
        assert out.shape == x.shape

    def test_grads_flow_to_router_and_experts(self):
        layer, params, x = self._make()

        def loss(p):
            out, mut = layer.apply({"params": p}, x, mutable=["moe_losses"])
            return jnp.sum(out ** 2) + moe_loss_from_variables(mut)

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["router"]["gate_weight"]).sum()) > 0
        assert float(jnp.abs(g["experts"]["w1"]).sum()) > 0

    def test_router_jitter_needs_rng_stream(self):
        """moe_jitter_eps perturbs routing only when a 'jitter' rng is
        supplied; without the stream the layer stays deterministic."""
        hidden = 16
        layer = SwitchMLP(hidden_size=hidden, ffn_hidden_size=32,
                          num_experts=4, capacity_factor=8.0,
                          jitter_eps=0.3, compute_dtype=jnp.float32)
        x = jnp.asarray(np.random.RandomState(3).randn(8, 2, hidden),
                        jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        base = layer.apply({"params": params}, x)
        again = layer.apply({"params": params}, x)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(again))
        jittered = layer.apply({"params": params}, x,
                               rngs={"jitter": jax.random.PRNGKey(9)})
        assert not np.allclose(np.asarray(base), np.asarray(jittered))

    def test_is_expert_param(self):
        assert is_expert_param("transformer/layer_0/mlp/experts/w1")
        assert not is_expert_param("transformer/layer_0/mlp/router/gate_weight")
        # segment match, not substring: dense modules merely containing
        # the word must not be classified as expert shards
        assert not is_expert_param("blk/experts_gate/kernel")
        assert not is_expert_param("blk/shared_experts_norm/scale")

    @pytest.mark.slow
    def test_jitter_key_forced_tp_uniform(self):
        """Even an adversarial per-tp-rank jitter key (the dropout-key
        discipline) must yield identical routing on every tp rank."""
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2, devices=jax.devices()[:2])
        mesh = parallel_state.get_mesh()
        layer = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=2,
                          capacity_factor=4.0, jitter_eps=0.3,
                          compute_dtype=jnp.float32)
        x = jnp.asarray(np.random.RandomState(11).randn(8, 2, 16),
                        jnp.float32)

        @shard_map(mesh=mesh, in_specs=P(), out_specs=P("tp"))
        def run(xs):
            params = layer.init(jax.random.PRNGKey(0), xs)["params"]
            key = jax.random.fold_in(jax.random.PRNGKey(5),
                                     jax.lax.axis_index("tp"))
            return layer.apply({"params": params}, xs,
                               rngs={"jitter": key})[None]

        outs = np.asarray(run(x))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)


class TestExpertParallel:
    """ep-sharded SwitchMLP == per-shard local runs (the ep axis only
    moves expert shards; routing is per-device over local tokens)."""

    def _params_and_input(self, hidden=16, ffn=32, E=4, seq=8, b=4):
        rng = np.random.RandomState(7)
        params = {
            "router": {"gate_weight": jnp.asarray(
                rng.randn(hidden, E) * 0.2, jnp.float32)},
            "experts": {
                "w1": jnp.asarray(rng.randn(E, hidden, ffn) * 0.1, jnp.float32),
                "b1": jnp.zeros((E, ffn), jnp.float32),
                "w2": jnp.asarray(rng.randn(E, ffn, hidden) * 0.1, jnp.float32),
                "b2": jnp.zeros((E, hidden), jnp.float32),
            },
        }
        x = jnp.asarray(rng.randn(seq, b, hidden), jnp.float32)
        return params, x

    def test_ep4_matches_local(self):
        E, ep = 4, 4
        params, x = self._params_and_input(E=E, b=ep)
        parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=ep, devices=jax.devices()[:ep])
        mesh = parallel_state.get_mesh()
        assert "ep" in mesh.shape and mesh.shape["ep"] == ep

        layer = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=E,
                          capacity_factor=8.0, compute_dtype=jnp.float32)

        # reference: each batch shard routed independently with all experts
        parallel_state_ep = parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE
        parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = 1
        ref = jnp.concatenate(
            [layer.apply({"params": params}, x[:, i:i + 1])
             for i in range(ep)], axis=1)
        parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = parallel_state_ep

        pspec = {"router": {"gate_weight": P()},
                 "experts": {k: P("ep") for k in params["experts"]}}

        @shard_map(mesh=mesh,
                   in_specs=(pspec, P(None, "ep", None)),
                   out_specs=P(None, "ep", None))
        def run(p, xs):
            return layer.apply({"params": p}, xs)

        out = run(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.slow  # tier-1 budget: ep4_matches_local covers the parity
    def test_ep_grads_match_local(self):
        E, ep = 4, 4
        params, x = self._params_and_input(E=E, b=ep)
        parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=ep, devices=jax.devices()[:ep])
        mesh = parallel_state.get_mesh()
        layer = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=E,
                          capacity_factor=8.0, compute_dtype=jnp.float32)

        def local_loss(p, xs):
            return jnp.sum(layer.apply({"params": p}, xs) ** 2)

        # reference: sum of per-shard losses/grads with ep disabled
        saved = parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE
        parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = 1
        ref_grads = jax.tree_util.tree_map(
            lambda *g: sum(g),
            *[jax.grad(local_loss)(params, x[:, i:i + 1]) for i in range(ep)])
        parallel_state._EXPERT_MODEL_PARALLEL_WORLD_SIZE = saved

        pspec = {"router": {"gate_weight": P()},
                 "experts": {k: P("ep") for k in params["experts"]}}

        @shard_map(mesh=mesh,
                   in_specs=(pspec, P(None, "ep", None)),
                   out_specs=pspec)
        def grads(p, xs):
            g = jax.grad(local_loss)(p, xs)
            # dense params replicate over ep: grad sync is the dp x ep
            # reduction (get_data_parallel_axes) — here just ep.
            g["router"]["gate_weight"] = jax.lax.psum(
                g["router"]["gate_weight"], "ep")
            return g

        g = grads(params, x)
        np.testing.assert_allclose(np.asarray(g["router"]["gate_weight"]),
                                   np.asarray(ref_grads["router"]["gate_weight"]),
                                   rtol=2e-4, atol=2e-4)
        for k in ("w1", "b1", "w2", "b2"):
            np.testing.assert_allclose(np.asarray(g["experts"][k]),
                                       np.asarray(ref_grads["experts"][k]),
                                       rtol=2e-4, atol=2e-4)


class TestParallelStateEP:
    def test_ep_grid(self):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2, expert_model_parallel_size_=2,
            devices=jax.devices()[:8])
        assert parallel_state.get_expert_model_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_axes() == ("dp", "ep")
        mesh = parallel_state.get_mesh()
        assert mesh.shape == {"pp": 1, "dp": 2, "ep": 2, "tp": 2}

    def test_ep_default_absent(self):
        parallel_state.initialize_model_parallel(devices=jax.devices()[:8])
        assert parallel_state.get_data_parallel_axes() == ("dp",)
        assert "ep" not in parallel_state.get_mesh().shape

    def test_bad_ep_grid_raises(self):
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(
                expert_model_parallel_size_=3, devices=jax.devices()[:8])


class TestSequenceParallelMoE:
    @pytest.mark.slow
    def test_sp_matches_non_sp_on_tp_mesh(self):
        """SwitchMLP under sequence parallelism (seq-sharded input,
        gather on entry / scatter on exit) == the non-SP layer on the
        full sequence, for both outputs and parameter gradients."""
        TP, SEQ, B, HID = 4, 8, 2, 16
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=TP, devices=jax.devices()[:TP])
        mesh = parallel_state.get_mesh()
        rng = np.random.RandomState(5)
        params = {
            "router": {"gate_weight": jnp.asarray(
                rng.randn(HID, 2) * 0.2, jnp.float32)},
            "experts": {
                "w1": jnp.asarray(rng.randn(2, HID, 32) * 0.1, jnp.float32),
                "b1": jnp.zeros((2, 32), jnp.float32),
                "w2": jnp.asarray(rng.randn(2, 32, HID) * 0.1, jnp.float32),
                "b2": jnp.zeros((2, HID), jnp.float32),
            },
        }
        x = jnp.asarray(rng.randn(SEQ, B, HID), jnp.float32)

        # ffn shards over tp; experts replicated over... E=2 local (ep=1)
        pspec = {"router": {"gate_weight": P()},
                 "experts": {"w1": P(None, None, "tp"), "b1": P(None, "tp"),
                             "w2": P(None, "tp", None), "b2": P()}}

        def make(sp):
            return SwitchMLP(hidden_size=HID, ffn_hidden_size=32,
                             num_experts=2, capacity_factor=8.0,
                             compute_dtype=jnp.float32,
                             sequence_parallel_enabled=sp)

        def loss(layer, p, xs):
            return jnp.sum(layer.apply({"params": p}, xs) ** 2)

        @shard_map(mesh=mesh, in_specs=(pspec, P("tp")),
                   out_specs=(P("tp"), pspec))
        def run_sp(p, xs):
            layer = make(True)
            out = layer.apply({"params": p}, xs)
            g = jax.grad(lambda q: loss(layer, q, xs))(p)
            # tp-sharded wgrads are complete per shard; replicated params
            # (router, b2) get identical grads on every rank under SP's
            # full-seq routing, so no extra reduction is needed.
            return out, g

        @shard_map(mesh=mesh, in_specs=(pspec, P()), out_specs=(P(), pspec))
        def run_full(p, xs):
            layer = make(False)
            out = layer.apply({"params": p}, xs)
            g = jax.grad(lambda q: loss(layer, q, xs))(p)
            return out, g

        out_sp, g_sp = run_sp(params, x)
        out_full, g_full = run_full(params, x)
        np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_full),
                                   rtol=2e-4, atol=2e-4)
        for (pa, ga), (_, gb) in zip(
                jax.tree_util.tree_leaves_with_path(g_sp),
                jax.tree_util.tree_leaves_with_path(g_full)):
            np.testing.assert_allclose(
                np.asarray(ga), np.asarray(gb), rtol=2e-4, atol=2e-4,
                err_msg=str(pa))

    def test_bert_with_moe_layers(self):
        """The BERT family shares ParallelTransformer, so the MoE config
        knobs apply there too."""
        from apex_tpu.models import BertModel, TransformerConfig
        from apex_tpu.transformer.enums import AttnMaskType

        parallel_state.destroy_model_parallel()
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            compute_dtype=jnp.float32, use_flash_attention=False,
            attn_mask_type=AttnMaskType.padding, num_moe_experts=2)
        model = BertModel(cfg)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        mask = jnp.ones((2, 16), jnp.int32)
        ttype = jnp.zeros((2, 16), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens, mask, ttype)
        (mlm, nsp), mut = model.apply(
            {"params": variables["params"]}, tokens, mask, ttype,
            mutable=["moe_losses"])
        assert np.isfinite(np.asarray(mlm)).all()
        assert float(moe_loss_from_variables(mut, 1.0)) > 0


class TestDDPExpertSync:
    """Production DDP sync paths honor the split replica-set rule:
    dense grads average over dp x ep, expert shards over dp alone."""

    def _mesh(self):
        parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=2, devices=jax.devices()[:4])
        return parallel_state.get_mesh()  # dp=2, ep=2

    def _check(self, sync_fn):
        from apex_tpu.parallel.distributed import (
            all_reduce_gradients,
            all_reduce_gradients_bucketed,
        )

        mesh = self._mesh()

        @shard_map(mesh=mesh, in_specs=(), out_specs=(P(), P("ep")))
        def run():
            dpr = jax.lax.axis_index("dp").astype(jnp.float32)
            epr = jax.lax.axis_index("ep").astype(jnp.float32)
            grads = {"dense": (dpr * 2 + epr).reshape(1),
                     "mlp": {"experts": {"w1": (dpr * 10 + epr).reshape(1)}}}
            fn = (all_reduce_gradients_bucketed if sync_fn == "bucketed"
                  else all_reduce_gradients)
            out = fn(grads, axis_name=("dp", "ep"),
                     expert_param_predicate=is_expert_param,
                     expert_axis_name="dp")
            return out["dense"], out["mlp"]["experts"]["w1"]

        dense, expert = run()
        # dense: mean over all 4 cells of dp*2+ep = {0,1,2,3} -> 1.5
        np.testing.assert_allclose(np.asarray(dense), [1.5])
        # expert (per ep rank r): mean over dp of dp*10+r -> 5+r
        np.testing.assert_allclose(np.asarray(expert), [5.0, 6.0])

    def test_per_leaf_sync(self):
        self._check("per_leaf")

    def test_bucketed_sync(self):
        self._check("bucketed")

    def test_ddp_class_sync_and_module_mode_guard(self):
        from apex_tpu.parallel import DistributedDataParallel

        mesh = self._mesh()
        ddp = DistributedDataParallel(
            axis_name=("dp", "ep"), expert_param_predicate=is_expert_param,
            expert_axis_name="dp")

        @shard_map(mesh=mesh, in_specs=(), out_specs=P("ep"))
        def run():
            dpr = jax.lax.axis_index("dp").astype(jnp.float32)
            epr = jax.lax.axis_index("ep").astype(jnp.float32)
            g = ddp.sync({"experts": {"w": (dpr * 10 + epr).reshape(1)}})
            return g["experts"]["w"]

        np.testing.assert_allclose(np.asarray(run()), [5.0, 6.0])
        with pytest.raises(NotImplementedError):
            ddp(lambda p: p)

    def test_moe_under_pp_refused(self):
        """The pipelined harness cannot thread router aux losses across
        stages; MoE configs must be rejected, not silently untrained."""
        from apex_tpu.models.transformer_lm import TransformerConfig
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.amp.grad_scaler import GradScaler
        from apex_tpu.transformer.testing.gpt_3d import build_gpt_3d_harness

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=2, devices=jax.devices()[:2])
        cfg = TransformerConfig(
            hidden_size=32, num_layers=6, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            use_flash_attention=False, num_moe_experts=2, moe_layer_freq=2)
        with pytest.raises(ValueError, match="gpt_moe"):
            build_gpt_3d_harness(cfg, mesh, FusedAdam(lr=1e-3),
                                 GradScaler(enabled=False), pp=2, seq=16,
                                 microbatch=1, num_microbatches=2)

    def test_aux_loss_drop_warns(self):
        import warnings as w

        from apex_tpu.transformer.moe import layer as moe_layer

        parallel_state.destroy_model_parallel()
        layer = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=2,
                          compute_dtype=jnp.float32)
        x = jnp.ones((4, 1, 16))
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        moe_layer._WARNED_DROPPED_LOSSES = False  # once-per-process flag
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            layer.apply({"params": params}, x)  # no mutable -> warn
        assert any("moe_losses" in str(c.message) for c in caught)
        moe_layer._WARNED_DROPPED_LOSSES = False
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            layer.apply({"params": params}, x, mutable=["moe_losses"])
        assert not any("moe_losses" in str(c.message) for c in caught)
        # eval opt-out
        quiet = SwitchMLP(hidden_size=16, ffn_hidden_size=32, num_experts=2,
                          compute_dtype=jnp.float32,
                          warn_on_dropped_losses=False)
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            quiet.apply({"params": params}, x)
        assert not any("moe_losses" in str(c.message) for c in caught)


class TestGPTMoEEndToEnd:
    @pytest.mark.slow
    def test_moe_gpt_ep_training_loss_decreases(self):
        """dp=2 x ep=2 x tp=2 MoE GPT: loss trends down over real steps
        (the ep analog of test_gpt_minimal's 3D run)."""
        from apex_tpu.models.transformer_lm import TransformerConfig
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.testing.gpt_moe import build_gpt_moe_harness

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2, expert_model_parallel_size_=2,
            devices=jax.devices()[:8])
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            compute_dtype=jnp.float32, use_flash_attention=False,
            num_moe_experts=4, moe_capacity_factor=2.0)
        SEQ, B = 16, 8  # dp*ep = 4 cells x 2 per-cell batch
        rng = np.random.RandomState(0)
        data = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(B, SEQ + 1)))
        tokens, labels = data[:, :-1], data[:, 1:]

        opt = FusedAdam(lr=1e-2)
        init_state, step = build_gpt_moe_harness(cfg, mesh, opt)
        params, opt_state = init_state(jax.random.PRNGKey(0), tokens)
        losses = []
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, tokens, labels)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9, losses


class TestMoEWithZeRO:
    def test_distributed_fused_adam_with_expert_params(self):
        """ZeRO (dp-sharded) Adam + expert parallelism: dense grads
        pre-averaged over ep, expert shards left per-cell; resulting
        updates match a hand-computed Adam step per replica set."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.parallel.distributed import all_reduce_gradients

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=2, devices=jax.devices()[:4])
        assert mesh.shape["dp"] == 2 and mesh.shape["ep"] == 2
        opt = DistributedFusedAdam(lr=0.1, weight_decay=0.0)

        @shard_map(mesh=mesh, in_specs=(), out_specs=(P(), P("ep")))
        def run():
            dpr = jax.lax.axis_index("dp").astype(jnp.float32)
            epr = jax.lax.axis_index("ep").astype(jnp.float32)
            params = {"dense": jnp.zeros((4,)),
                      "blk": {"experts": {"w": jnp.zeros((4,))}}}
            grads = {"dense": jnp.full((4,), dpr * 2 + epr),
                     "blk": {"experts": {"w": jnp.full((4,), dpr * 10 + epr)}}}
            grads = all_reduce_gradients(
                grads, axis_name="ep", expert_param_predicate=is_expert_param,
                expert_axis_name=())
            opt_state = opt.init(params)
            new_params, _ = opt.step(grads, opt_state, params)
            return new_params["dense"], new_params["blk"]["experts"]["w"][None]

        dense, expert = run()
        # First Adam step moves each param by -lr * sign(grad) (bias
        # correction cancels); all synced grads here are positive.
        np.testing.assert_allclose(np.asarray(dense), -0.1 * np.ones(4),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(expert),
                                   -0.1 * np.ones((2, 4)), rtol=1e-5)

    def test_zero_dense_grads_identical_across_ep(self):
        """After the pre-sync + ZeRO step, dense params remain bitwise
        identical across ep ranks (the divergence the composition rule
        prevents)."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.parallel.distributed import all_reduce_gradients

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=2, devices=jax.devices()[:4])
        opt = DistributedFusedAdam(lr=0.05)
        rng = np.random.RandomState(3)
        base = jnp.asarray(rng.randn(8), jnp.float32)

        @shard_map(mesh=mesh, in_specs=P(), out_specs=P("ep"))
        def run(b):
            dpr = jax.lax.axis_index("dp").astype(jnp.float32)
            epr = jax.lax.axis_index("ep").astype(jnp.float32)
            params = {"dense": b, "mlp": {"experts": {"w": b * 0}}}
            grads = {"dense": b * (1 + dpr) * (1 + epr),
                     "mlp": {"experts": {"w": b + dpr + epr}}}
            grads = all_reduce_gradients(
                grads, axis_name="ep", expert_param_predicate=is_expert_param,
                expert_axis_name=())
            state = opt.init(params)
            new_params, _ = opt.step(grads, state, params)
            return new_params["dense"][None]

        per_ep = np.asarray(run(base))  # [ep, 8]
        np.testing.assert_array_equal(per_ep[0], per_ep[1])


class TestMoECheckpoint:
    @pytest.mark.slow
    def test_moe_ep_training_state_roundtrip(self, tmp_path):
        """ep-sharded MoE training state survives save/restore: the
        resumed run reproduces the uninterrupted run's losses exactly."""
        from apex_tpu import checkpoint
        from apex_tpu.models.transformer_lm import TransformerConfig
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.testing.gpt_moe import build_gpt_moe_harness

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            expert_model_parallel_size_=2, devices=jax.devices()[:2])
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            compute_dtype=jnp.float32, use_flash_attention=False,
            num_moe_experts=2, moe_capacity_factor=2.0)
        SEQ, B = 16, 4
        rng = np.random.RandomState(0)
        data = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(B, SEQ + 1)))
        tokens, labels = data[:, :-1], data[:, 1:]

        opt = FusedAdam(lr=1e-2)
        init_state, step = build_gpt_moe_harness(cfg, mesh, opt)
        params, opt_state = init_state(jax.random.PRNGKey(0), tokens)
        for _ in range(2):
            params, opt_state, _ = step(params, opt_state, tokens, labels)

        checkpoint.save_training_state(str(tmp_path), 2, params, opt_state)

        ref = []
        p, o = params, opt_state
        for _ in range(2):
            p, o, loss = step(p, o, tokens, labels)
            ref.append(float(loss))

        restored = checkpoint.restore_training_state(str(tmp_path))
        p, o = restored["params"], restored["opt_state"]
        resumed = []
        for _ in range(2):
            p, o, loss = step(p, o, tokens, labels)
            resumed.append(float(loss))
        np.testing.assert_allclose(resumed, ref, rtol=1e-6)


class TestGPTMoE:
    @pytest.mark.slow  # tier-1 budget (round 23): bert_with_moe_layers + ep4_matches_local cover MoE training
    def test_gpt_with_moe_layers_trains(self):
        from apex_tpu.models import GPTModel, TransformerConfig

        parallel_state.destroy_model_parallel()
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            compute_dtype=jnp.float32, use_flash_attention=False,
            num_moe_experts=4, moe_layer_freq=2)  # layer 0 MoE, layer 1 dense
        model = GPTModel(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 64, size=(2, 16)))
        variables = model.init(jax.random.PRNGKey(0), tokens)
        flat = jax.tree_util.tree_leaves_with_path(variables["params"])
        paths = ["/".join(str(k.key) for k in p) for p, _ in flat]
        assert any("layer_0/mlp/experts" in p for p in paths)
        assert any("layer_1/mlp/dense_h_to_4h" in p for p in paths)

        from apex_tpu.models.gpt import gpt_loss_fn

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p}, tokens, mutable=["moe_losses"])
            labels = jnp.roll(tokens, -1, axis=-1)
            return gpt_loss_fn(logits, labels) + moe_loss_from_variables(
                mut, cfg.moe_aux_loss_coeff, cfg.moe_z_loss_coeff)

        loss, g = jax.value_and_grad(loss_fn)(variables["params"])
        assert np.isfinite(float(loss))
        router_g = g["transformer"]["layer_0"]["mlp"]["router"]["gate_weight"]
        assert float(jnp.abs(router_g).sum()) > 0


class TestMoEPipelineParallel:
    """Round-2: MoE composes with pipeline parallelism (uniform stack).
    Round 1 refused this; the schedule's aux_loss contract now backprops
    each stage's router losses from its own backward unit."""

    def _run(self, aux_coeff, steps=6):
        from apex_tpu.models.transformer_lm import TransformerConfig
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.amp.grad_scaler import GradScaler
        from apex_tpu.transformer.testing.gpt_3d import build_gpt_3d_harness

        PP_, DP_, TP_ = 2, 2, 2
        SEQ_, MB_, M_ = 16, 2, 2
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=TP_,
            pipeline_model_parallel_size_=PP_, devices=jax.devices()[:8])
        cfg = TransformerConfig(
            hidden_size=64, num_layers=2 * PP_, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=32,
            compute_dtype=jnp.bfloat16, sequence_parallel=True,
            use_flash_attention=False, num_moe_experts=2,
            moe_layer_freq=1, moe_capacity_factor=2.0,
            moe_aux_loss_coeff=aux_coeff)
        global_b = MB_ * M_ * DP_
        rng = np.random.RandomState(0)
        base = rng.randint(0, 32, size=(global_b, 1))
        tokens = jnp.asarray((base + np.arange(SEQ_)) % 32)
        labels = jnp.asarray((base + np.arange(1, SEQ_ + 1)) % 32)
        opt = FusedAdam(lr=5e-3, master_weights=True)
        scaler = GradScaler(enabled=True)
        init_state, step = build_gpt_3d_harness(
            cfg, mesh, opt, scaler, pp=PP_, seq=SEQ_, microbatch=MB_,
            num_microbatches=M_)
        state = init_state(jax.random.PRNGKey(0), tokens, labels)
        losses = []
        for _ in range(steps):
            *state, loss = step(*state, tokens, labels)
            losses.append(float(np.asarray(loss).sum()) / DP_ / M_)
        parallel_state.destroy_model_parallel()
        return losses, state[0]

    # jax < 0.6: the per-stage aux-loss pullback trips an AssertionError
    # inside lax.gather's transpose rule (old-jax bug, fixed upstream);
    # the non-aux MoE+pp paths above still cover the composition there.
    _OLD_JAX = tuple(
        int(x) for x in jax.__version__.split(".")[:2]) < (0, 6)

    @pytest.mark.skipif(_OLD_JAX, reason="jax<0.6 gather-transpose bug "
                        "in the pp aux-loss pullback")
    def test_moe_pp_training_loss_decreases(self):
        losses, _ = self._run(aux_coeff=1e-2, steps=10)
        assert np.isfinite(losses).all()
        assert losses[-1] < 0.8 * losses[0], losses

    @pytest.mark.skipif(_OLD_JAX, reason="jax<0.6 gather-transpose bug "
                        "in the pp aux-loss pullback")
    def test_router_aux_grads_reach_first_stage(self):
        """The aux coefficient must change the FIRST pipeline stage's
        router update — proof the per-stage aux cotangent flows (with
        last-stage-only loss it could only reach stage P-1)."""
        _, params_a = self._run(aux_coeff=0.0, steps=1)
        _, params_b = self._run(aux_coeff=10.0, steps=1)

        def router_leaf(params):
            flat = jax.tree_util.tree_flatten_with_path(params)[0]
            leaves = [v for k, v in flat if "router" in jax.tree_util.keystr(k)]
            assert leaves, [jax.tree_util.keystr(k) for k, _ in flat][:8]
            return np.asarray(leaves[0])  # [pp, ...] stacked rows

        ra, rb = router_leaf(params_a), router_leaf(params_b)
        # first pipeline stage's router row differs between coefficients
        assert not np.allclose(ra[0], rb[0], atol=1e-7), \
            "aux loss did not reach the first stage's router"

    def test_refuses_expert_parallel_mesh(self):
        from apex_tpu.models.transformer_lm import TransformerConfig
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.amp.grad_scaler import GradScaler
        from apex_tpu.transformer.testing.gpt_3d import build_gpt_3d_harness

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=2, expert_model_parallel_size_=2,
            devices=jax.devices()[:8])
        cfg = TransformerConfig(
            hidden_size=64, num_layers=4, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=32,
            num_moe_experts=2, moe_layer_freq=1)
        with pytest.raises(ValueError, match="expert parallelism"):
            build_gpt_3d_harness(cfg, mesh, FusedAdam(lr=1e-3),
                                 GradScaler(enabled=False), pp=2, seq=16,
                                 microbatch=2, num_microbatches=2)
