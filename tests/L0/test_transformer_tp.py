"""Tensor-parallel mappings, layers, and vocab-parallel cross entropy.

Mirrors reference tests/L0/run_transformer/test_mapping.py, test_layers.py
(TP layers vs non-parallel reference), test_cross_entropy.py,
test_parallel_state.py, test_microbatches.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.testing import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
    scatter_to_sequence_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    vocab_parallel_cross_entropy,
)


def tp_mesh(tp=4):
    devices = np.asarray(jax.devices()[:tp])
    return Mesh(devices, ("tp",))


class TestParallelState:
    def test_grid_math(self):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2,
            devices=jax.devices()[:8])
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        assert parallel_state.get_pipeline_model_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_world_size() == 2
        assert parallel_state.get_model_parallel_world_size() == 4
        assert parallel_state.model_parallel_is_initialized()

    def test_bad_grid_raises(self):
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size_=3, pipeline_model_parallel_size_=1,
                devices=jax.devices()[:8])

    def test_destroy(self):
        parallel_state.initialize_model_parallel(devices=jax.devices()[:8])
        parallel_state.destroy_model_parallel()
        assert not parallel_state.model_parallel_is_initialized()

    def test_virtual_pipeline_requires_pp(self):
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size_=1,
                pipeline_model_parallel_size_=1,
                virtual_pipeline_model_parallel_size_=2,
                devices=jax.devices()[:8])

    def test_split_rank_predicates(self):
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=4,
            pipeline_model_parallel_split_rank_=2,
            devices=jax.devices()[:8])
        parallel_state.set_pipeline_model_parallel_rank(1)
        assert parallel_state.is_pipeline_stage_before_split()
        parallel_state.set_pipeline_model_parallel_rank(2)
        assert parallel_state.is_pipeline_stage_after_split()


class TestMappings:
    """Forward + backward semantics of each region op
    (reference test_mapping.py)."""

    def setup_method(self, method):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=4, devices=jax.devices()[:4])

    def _run(self, fn, x, in_spec, out_spec):
        mesh = tp_mesh(4)
        return shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec)(x)

    def test_copy_identity_fwd_psum_bwd(self, rng):
        x = jnp.asarray(rng.randn(4, 6).astype(np.float32))

        def f(x_):
            return copy_to_tensor_model_parallel_region(x_)

        out = self._run(f, x, P(), P())
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

        def g(x_):
            return jax.grad(lambda a: jnp.sum(
                copy_to_tensor_model_parallel_region(a)))(x_)

        grads = self._run(g, x, P(), P())
        # each replica contributes ones; psum over 4 -> 4
        np.testing.assert_array_equal(np.asarray(grads),
                                      4 * np.ones_like(np.asarray(x)))

    def test_reduce_fwd(self, rng):
        x = jnp.asarray(rng.randn(4, 8).astype(np.float32))

        def f(x_):
            return reduce_from_tensor_model_parallel_region(x_)

        # shard over dim0: psum of shards
        out = self._run(f, x, P("tp"), P("tp"))
        # each device's shard [1, 8] -> psum across devices sums all rows
        expected = np.broadcast_to(np.asarray(x).sum(0, keepdims=True), (4, 8))
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)

    def test_scatter_gather_roundtrip(self, rng):
        x = jnp.asarray(rng.randn(2, 8).astype(np.float32))

        def f(x_):
            s = scatter_to_tensor_model_parallel_region(x_)
            return gather_from_tensor_model_parallel_region(s)

        out = self._run(f, x, P(), P())
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_sequence_parallel_roundtrip(self, rng):
        x = jnp.asarray(rng.randn(8, 3).astype(np.float32))

        def f(x_):
            s = scatter_to_sequence_parallel_region(x_)
            return gather_from_sequence_parallel_region(s, False)

        out = self._run(f, x, P(), P())
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_reduce_scatter_fwd(self, rng):
        x = jnp.asarray(rng.randn(8, 2).astype(np.float32))

        def f(x_):
            return reduce_scatter_to_sequence_parallel_region(x_)

        # replicated input -> each shard = 4 * its slice
        out = self._run(f, x, P(), P("tp"))
        np.testing.assert_allclose(np.asarray(out), 4 * np.asarray(x),
                                   rtol=1e-6)


class TestColumnRowParallel:
    """TP layers match a non-parallel reference (reference test_layers.py)."""

    def setup_method(self, method):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=4, devices=jax.devices()[:4])

    def test_column_times_row_matches_dense(self, rng):
        mesh = tp_mesh(4)
        B, H, F = 2, 8, 16
        x = jnp.asarray(rng.randn(B, H).astype(np.float32))
        col = ColumnParallelLinear(input_size=H, output_size=F,
                                   gather_output=False, bias=True)
        row = RowParallelLinear(input_size=F, output_size=H,
                                input_is_parallel=True, bias=True)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                           out_specs=P())
        def init_and_apply(key, x_):
            cp = col.init(key, x_)
            h = col.apply(cp, x_)
            rp = row.init(jax.random.fold_in(key, 7), h)
            y = row.apply(rp, h)
            return y, cp, rp

        y, cp, rp = init_and_apply(jax.random.PRNGKey(0), x)

        # Reference: gather the full weights and do a dense matmul.
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), P(), P()), out_specs=P())
        def dense_ref(x_, cp_, rp_):
            wc = jax.lax.all_gather(cp_["params"]["weight"], "tp", axis=1,
                                    tiled=True)
            bc = jax.lax.all_gather(cp_["params"]["bias"], "tp", axis=0,
                                    tiled=True)
            wr = jax.lax.all_gather(rp_["params"]["weight"], "tp", axis=0,
                                    tiled=True)
            br = rp_["params"]["bias"]
            h = x_ @ wc + bc
            return h @ wr + br

        expected = dense_ref(x, cp, rp)
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected),
                                   rtol=1e-4, atol=1e-5)

    def test_gather_output(self, rng):
        mesh = tp_mesh(4)
        x = jnp.asarray(rng.randn(2, 8).astype(np.float32))
        col = ColumnParallelLinear(input_size=8, output_size=16,
                                   gather_output=True, bias=False)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                           out_specs=P())
        def f(key, x_):
            p = col.init(key, x_)
            return col.apply(p, x_)

        y = f(jax.random.PRNGKey(0), x)
        assert y.shape == (2, 16)

    def test_sequence_parallel_column(self, rng):
        """SP column linear: seq-sharded input, gathered internally."""
        mesh = tp_mesh(4)
        S, B, H, F = 8, 2, 8, 16
        x = jnp.asarray(rng.randn(S, B, H).astype(np.float32))
        col = ColumnParallelLinear(input_size=H, output_size=F,
                                   gather_output=False, bias=False,
                                   sequence_parallel_enabled=True)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("tp")),
                           out_specs=P())
        def f(key, x_shard):
            p = col.init(key, x_shard)
            return col.apply(p, x_shard), p

        y, p = f(jax.random.PRNGKey(0), x)
        assert y.shape == (S, 2, F // 4)  # full seq, sharded feature

    def test_vocab_parallel_embedding(self, rng):
        mesh = tp_mesh(4)
        V, D = 16, 8
        ids = jnp.asarray(rng.randint(0, V, size=(2, 5)))
        emb = VocabParallelEmbedding(num_embeddings=V, embedding_dim=D)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                           out_specs=(P(), P()))
        def f(key, ids_):
            p = emb.init(key, ids_)
            return emb.apply(p, ids_), p["params"]["weight"]

        out, wshard = f(jax.random.PRNGKey(0), ids)
        assert out.shape == (2, 5, D)

        # reference lookup from the gathered table
        @functools.partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P())
        def gather_w(w):
            return jax.lax.all_gather(w, "tp", axis=0, tiled=True)

        full_w = np.asarray(gather_w(wshard))[:V]
        expected = full_w[np.asarray(ids)]
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


class TestVocabParallelCrossEntropy:
    def setup_method(self, method):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=4, devices=jax.devices()[:4])

    def test_matches_dense_cross_entropy(self, rng):
        mesh = tp_mesh(4)
        B, S, V = 2, 3, 16
        logits = jnp.asarray(rng.randn(B, S, V).astype(np.float32))
        target = jnp.asarray(rng.randint(0, V, size=(B, S)))

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(None, None, "tp"), P()),
                           out_specs=P())
        def f(logits_shard, tgt):
            return vocab_parallel_cross_entropy(logits_shard, tgt)

        loss = np.asarray(f(logits, target))
        # dense reference
        lse = np.log(np.exp(np.asarray(logits) -
                            np.asarray(logits).max(-1, keepdims=True)).sum(-1))
        picked = np.take_along_axis(
            np.asarray(logits) - np.asarray(logits).max(-1, keepdims=True),
            np.asarray(target)[..., None], axis=-1)[..., 0]
        expected = lse - picked
        np.testing.assert_allclose(loss, expected, rtol=1e-4, atol=1e-5)

    def test_gradient_is_softmax_minus_onehot(self, rng):
        mesh = tp_mesh(4)
        B, V = 4, 8
        logits = jnp.asarray(rng.randn(B, V).astype(np.float32))
        target = jnp.asarray(rng.randint(0, V, size=(B,)))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(None, "tp"), P()),
                           out_specs=P(None, "tp"))
        def g(logits_shard, tgt):
            return jax.grad(
                lambda l: jnp.sum(vocab_parallel_cross_entropy(l, tgt))
            )(logits_shard)

        grads = np.asarray(g(logits, target))
        p = np.exp(np.asarray(logits))
        p /= p.sum(-1, keepdims=True)
        onehot = np.eye(V)[np.asarray(target)]
        np.testing.assert_allclose(grads, p - onehot, rtol=1e-4, atol=1e-5)

    def test_label_smoothing(self, rng):
        mesh = tp_mesh(4)
        logits = jnp.asarray(rng.randn(4, 16).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 16, size=(4,)))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(None, "tp"), P()), out_specs=P())
        def f(l, t):
            return vocab_parallel_cross_entropy(l, t, label_smoothing=0.1)

        loss = np.asarray(f(logits, target))
        assert np.all(np.isfinite(loss))
