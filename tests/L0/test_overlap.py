"""Overlapped backward/collective training step (ISSUE 10).

Evidence layers:

- **Parity**: the overlapped step is BIT-IDENTICAL to the bucketed
  baseline for fp32 and bf16 payloads, and for int8 whenever the
  segment buckets land on quantization-block boundaries with
  ``fold_average=False`` — including the error-feedback residual over
  50 chained steps (the EF state machine is the same machine, just in
  the bucket domain). Ragged buckets shift the block grid and stay
  within the documented per-block quantization bound.
- **Composition**: ``guarded_update`` reverts params AND the
  bucket-domain residual bit-exactly on an injected-NaN skip; the
  8-device e2e step holds one compile under ``assert_no_recompiles``.
- **Structure**: the lowered HLO interleaves collectives with backward
  compute (vs the baseline's trailing block), the
  ``overlap-serialization`` rule runs clean on the real step at a
  meaningful threshold, and the segment/bucket spans land interleaved
  in the telemetry JSONL.
- **ZeRO**: ``overlap=True`` optimizers match their monolithic
  selves (Adam fp32 bit-exact; LAMB to fp32 summation-order noise),
  and the segmented driver matches step-on-segments bit-exactly.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import (
    DistributedDataParallel,
    OverlappedDataParallel,
    overlapped_zero_step,
    plan_overlap,
)

BLOCK = 256


def _params(hidden, depth, seed=0):
    rng = np.random.RandomState(seed)
    params = {}
    for i in range(depth):
        params[f"w{i}"] = jnp.asarray(
            rng.randn(hidden, hidden).astype(np.float32)
            / np.sqrt(hidden))
        params[f"b{i}"] = jnp.asarray(
            0.01 * rng.randn(hidden).astype(np.float32))
    return params


def _seg_params(params, depth):
    return [{f"w{i}": params[f"w{i}"], f"b{i}": params[f"b{i}"]}
            for i in range(depth)]


def _data(mesh, hidden, batch=2, seed=1):
    rng = np.random.RandomState(seed)
    n = batch * mesh.devices.size
    return (jnp.asarray(rng.randn(n, hidden).astype(np.float32)),
            jnp.asarray(rng.randn(n, hidden).astype(np.float32)))


def _loss(p, xb, yb, depth):
    h = xb
    for i in range(depth):
        h = jnp.tanh(h @ p[f"w{i}"] + p[f"b{i}"])
    return jnp.mean((h - yb) ** 2)


def _segment_fns(depth, yb):
    """One segment per layer; the last closes over ``yb`` and returns
    the scalar loss."""
    segs = [lambda pk, h, i=i: jnp.tanh(h @ pk[f"w{i}"] + pk[f"b{i}"])
            for i in range(depth - 1)]

    def last(pk, h, i=depth - 1):
        h = jnp.tanh(h @ pk[f"w{i}"] + pk[f"b{i}"])
        return jnp.mean((h - yb) ** 2)

    segs.append(last)
    return segs


def _baseline_step(mesh, depth, **ddp_kw):
    ddp = DistributedDataParallel(axis_name="dp", **ddp_kw)
    is_int8 = ddp_kw.get("compress") == "int8"

    def fn(p, res, xb, yb):
        loss, grads = jax.value_and_grad(
            lambda q: _loss(q, xb, yb, depth))(p)
        if is_int8:
            grads, res = ddp.sync(grads, res)
        else:
            grads = ddp.sync(grads)
        p = jax.tree_util.tree_map(lambda w, g: w - 0.05 * g, p, grads)
        return p, res, loss

    return ddp, jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P()), check_vma=False))


def _overlap_step(mesh, depth, **odp_kw):
    odp = OverlappedDataParallel(axis_name="dp", **odp_kw)
    is_int8 = odp_kw.get("compress") == "int8"

    def fn(sp, res, xb, yb):
        segs = _segment_fns(depth, yb)
        if is_int8:
            loss, synced, res = odp.value_and_sync(segs, sp, xb,
                                                   residual=res)
        else:
            loss, synced = odp.value_and_sync(segs, sp, xb)
        sp = [jax.tree_util.tree_map(lambda w, g: w - 0.05 * g, pk, gk)
              for pk, gk in zip(sp, synced)]
        return sp, res, loss

    return odp, jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P()), check_vma=False))


def _assert_tree_equal(a, b, what=""):
    for ka, kb in zip(sorted(a), sorted(b)):
        assert np.array_equal(np.asarray(a[ka]), np.asarray(b[kb])), \
            f"{what}{ka}: max delta " \
            f"{np.abs(np.asarray(a[ka]) - np.asarray(b[kb])).max()}"


# ---------------------------------------------------------------------------
# host-side planning + API contract
# ---------------------------------------------------------------------------

class TestPlanAndApi:
    def test_plan_never_spans_segments_and_caps_buckets(self):
        seg_params = [
            {"w": np.zeros((512, 16), np.float32),
             "b": np.zeros((16,), np.float32)},
            {"w": np.zeros((256, 16), np.float32)},
        ]
        plan = plan_overlap(seg_params, message_size=4096)
        assert len(plan) == 2
        # segment 0: 8192-elem w splits into 2 buckets, b rides alone
        sizes0 = [b.n for b in plan[0]]
        assert sum(sizes0) == 512 * 16 + 16
        assert all(b.n <= 4096 or len(b.leaf_idx) == 1 for b in plan[0])
        # bucket indices are SEGMENT-local
        assert all(i < 2 for b in plan[0] for i in b.leaf_idx)
        assert [b.n for b in plan[1]] == [4096]

    def test_init_residual_is_block_domain(self):
        odp = OverlappedDataParallel(compress="int8")
        seg_params = [{"w": np.zeros((300,), np.float32)}]
        res = odp.init_residual(seg_params)
        assert len(res) == 1 and len(res[0]) == 1
        assert res[0][0].shape == (2, BLOCK)  # 300 -> 2 blocks
        assert res[0][0].dtype == jnp.float32

    def test_residual_to_tree_strips_padding(self):
        odp = OverlappedDataParallel(compress="int8")
        seg_params = [{"w": np.zeros((300,), np.float32)}]
        res = [(jnp.arange(512, dtype=jnp.float32).reshape(2, BLOCK),)]
        tree = odp.residual_to_tree(seg_params, res)
        assert tree[0]["w"].shape == (300,)
        assert np.array_equal(np.asarray(tree[0]["w"]),
                              np.arange(300, dtype=np.float32))

    def test_segment_count_mismatch_raises(self):
        odp = OverlappedDataParallel()
        with pytest.raises(ValueError, match="segment fns"):
            odp.value_and_sync([lambda p, h: h], [{}, {}], None)

    def test_non_scalar_loss_raises(self):
        odp = OverlappedDataParallel()
        with pytest.raises(ValueError, match="scalar loss"):
            odp.value_and_sync(
                [lambda p, h: h * p["w"]],
                [{"w": jnp.ones((4,))}], jnp.ones((4,)))

    def test_unknown_compress_mode_raises(self):
        with pytest.raises(ValueError, match="compression mode"):
            OverlappedDataParallel(compress="fp8")


# ---------------------------------------------------------------------------
# parity vs the bucketed baseline
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestParity:
    def test_fp32_bit_identical(self, dp_mesh):
        mesh = dp_mesh(8)
        depth, hidden = 2, 64
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        _, base = _baseline_step(mesh, depth)
        _, ovl = _overlap_step(mesh, depth)
        p_b, r_b = params, jnp.zeros(())
        sp_o, r_o = _seg_params(params, depth), jnp.zeros(())
        for _ in range(2):
            p_b, r_b, loss_b = base(p_b, r_b, x, y)
            sp_o, r_o, loss_o = ovl(sp_o, r_o, x, y)
        assert float(loss_b) == float(loss_o)
        for i in range(depth):
            _assert_tree_equal(
                {k: p_b[k] for k in sp_o[i]}, sp_o[i], "fp32 ")

    @pytest.mark.slow  # tier-1 budget (round 23): int8_ragged_within_block_bound covers the int8 path
    def test_int8_block_aligned_bit_identical_50_steps(self, dp_mesh):
        """EF residual equivalence over 50 steps: with block-aligned
        segment buckets (every leaf a multiple of 256 elements) and
        ``fold_average=False``, the overlapped int8 step IS the
        bucketed baseline — same quantization grid, same psum, same
        error feedback — so params AND residual stay bit-identical for
        the whole run."""
        mesh = dp_mesh(8)
        depth, hidden = 2, BLOCK  # w: 256 blocks, b: 1 block — aligned
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        ddp, base = _baseline_step(mesh, depth, compress="int8")
        odp, ovl = _overlap_step(mesh, depth, compress="int8",
                                 fold_average=False)
        seg_params = _seg_params(params, depth)
        p_b, r_b = params, ddp.init_residual(params)
        sp_o, r_o = seg_params, odp.init_residual(seg_params)
        for step in range(50):
            p_b, r_b, loss_b = base(p_b, r_b, x, y)
            sp_o, r_o, loss_o = ovl(sp_o, r_o, x, y)
        assert float(loss_b) == float(loss_o)
        for i in range(depth):
            _assert_tree_equal(
                {k: p_b[k] for k in sp_o[i]}, sp_o[i], "int8 params ")
        res_tree = odp.residual_to_tree(seg_params, r_o)
        for i in range(depth):
            _assert_tree_equal(
                {k: r_b[k] for k in res_tree[i]}, res_tree[i],
                "int8 residual ")

    def test_int8_ragged_within_block_bound(self, dp_mesh):
        """Ragged buckets (leaf sizes not block multiples) shift the
        quantization grid vs the monolithic flat layout: the synced
        result still lands within the per-block symmetric-int8 bound
        of the exact fp32 mean."""
        mesh = dp_mesh(8)
        depth, hidden = 2, 96  # w: 9216 (36 blocks), b: 96 — ragged
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        odp = OverlappedDataParallel(axis_name="dp", compress="int8")

        def fn(sp, xb, yb):
            segs = _segment_fns(depth, yb)
            loss, synced, _ = odp.value_and_sync(segs, sp, xb)
            exact, grads = jax.value_and_grad(
                lambda q: _loss(q, xb, yb, depth))(
                {k: v for seg in sp for k, v in seg.items()})
            mean = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, "dp") / 8.0, grads)
            gmax = jax.tree_util.tree_map(
                lambda g: jax.lax.pmax(jnp.max(jnp.abs(g)), "dp"),
                grads)
            return synced, mean, gmax

        step = jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P()), check_vma=False))
        synced, mean, gmax = step(_seg_params(params, depth), x, y)
        for i in range(depth):
            for k in synced[i]:
                s = np.asarray(synced[i][k])
                m = np.asarray(mean[k])
                # per-replica rounding error <= scale/2 with the shared
                # (pmax) block scale <= per-replica-max absmax / 127;
                # averaged over replicas it stays <= absmax/254 —
                # assert with 2x margin
                bound = max(float(gmax[k]), 1e-6) / 127.0
                assert np.abs(s - m).max() <= bound, k

    def test_bf16_bit_identical(self, dp_mesh):
        mesh = dp_mesh(8)
        depth, hidden = 2, 64
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        _, base = _baseline_step(mesh, depth, compress="bf16")
        _, ovl = _overlap_step(mesh, depth, compress="bf16")
        p_b, _, loss_b = base(params, jnp.zeros(()), x, y)
        sp_o, _, loss_o = ovl(_seg_params(params, depth),
                              jnp.zeros(()), x, y)
        assert float(loss_b) == float(loss_o)
        for i in range(depth):
            _assert_tree_equal(
                {k: p_b[k] for k in sp_o[i]}, sp_o[i], "bf16 ")

    def test_fold_average_within_rounding(self, dp_mesh):
        """``fold_average=True`` moves the 1/world divide into the
        dequant scales — at most one extra fp32 rounding per element."""
        mesh = dp_mesh(8)
        depth, hidden = 2, 64
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        outs = {}
        for fold in (False, True):
            odp, ovl = _overlap_step(mesh, depth, compress="int8",
                                     fold_average=fold)
            sp = _seg_params(params, depth)
            sp, _, _ = ovl(sp, odp.init_residual(sp), x, y)
            outs[fold] = sp
        for i in range(depth):
            for k in outs[True][i]:
                a = np.asarray(outs[True][i][k])
                b = np.asarray(outs[False][i][k])
                np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)


# ---------------------------------------------------------------------------
# guard composition: skip-and-revert over the bucket-domain residual
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestGuardRevert:
    def test_injected_nan_skips_and_reverts_bit_exact(self, dp_mesh):
        from apex_tpu import resilience
        from apex_tpu.resilience import faults

        mesh = dp_mesh(8)
        depth, hidden = 2, 64
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        odp = OverlappedDataParallel(axis_name="dp", compress="int8",
                                     guard_flag=True)

        def fn(sp, res, gst, step, xb, yb):
            xb = faults.inject_nan(xb, step, nan_step=1)
            segs = _segment_fns(depth, yb)
            loss, synced, new_res, flag = odp.value_and_sync(
                segs, sp, xb, residual=res)

            def commit(g, st):
                prev_sp, _ = st
                new_sp = [jax.tree_util.tree_map(
                    lambda w, gg: w - 0.05 * gg, pk, gk)
                    for pk, gk in zip(prev_sp, g)]
                return (new_sp, new_res)

            (sp, res), gst = resilience.guarded_update(
                synced, commit, (sp, res), gst, axis_name="dp",
                flag=flag)
            return sp, res, gst, loss

        step_fn = jax.jit(jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P(), P()), check_vma=False))

        sp = _seg_params(params, depth)
        res = odp.init_residual(sp)
        gst = resilience.init_guard_state()
        # step 0: clean
        sp, res, gst, _ = step_fn(sp, res, gst,
                                  jnp.zeros((), jnp.int32), x, y)
        assert int(gst.total_skips) == 0
        before = (jax.tree_util.tree_map(np.asarray, sp),
                  jax.tree_util.tree_map(np.asarray, res))
        # step 1: poisoned -> skipped, params AND bucket-domain
        # residual revert bit-exactly
        sp, res, gst, _ = step_fn(sp, res, gst,
                                  jnp.ones((), jnp.int32), x, y)
        assert int(gst.total_skips) == 1
        assert int(gst.last_skipped) == 1
        for b_leaf, a_leaf in zip(
                jax.tree_util.tree_leaves(before),
                jax.tree_util.tree_leaves((sp, res))):
            assert np.array_equal(b_leaf, np.asarray(a_leaf))
        # step 2: clean again — streak resets, state moves
        sp, res, gst, _ = step_fn(sp, res, gst,
                                  2 * jnp.ones((), jnp.int32), x, y)
        assert int(gst.consecutive_skips) == 0
        assert not np.array_equal(
            np.asarray(jax.tree_util.tree_leaves(sp)[0]),
            jax.tree_util.tree_leaves(before)[0])


# ---------------------------------------------------------------------------
# one compile + lint + HLO structure + spans
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestStructure:
    def test_e2e_no_recompiles(self):
        from apex_tpu.analysis.targets import ddp_overlapped_step
        from apex_tpu.telemetry.compile_watch import assert_no_recompiles

        fn, args, _ = ddp_overlapped_step()
        sp, res, x, y = args
        # call 1 compiles (uncommitted inputs), call 2 sees the
        # committed outputs' signature — steady state from there
        out = fn(sp, res, x, y)
        out = fn(out[0], out[1], x, y)
        with assert_no_recompiles():
            for _ in range(3):
                out = fn(out[0], out[1], x, y)
        float(out[2])

    def test_overlap_serialization_rule_meaningfully_clean(self):
        """The overlapped target passes the new rule with the
        threshold dropped BELOW its bucket sizes — the buckets are
        genuinely independent, not just too small to check."""
        from apex_tpu.analysis import LintConfig, assert_clean_hlo
        from apex_tpu.analysis.targets import ddp_overlapped_step

        fn, args, _ = ddp_overlapped_step()
        report = assert_clean_hlo(
            fn, *args, rules="overlap-serialization",
            config=LintConfig(overlap_min_bytes=1024))
        assert report.rules_run == ("overlap-serialization",)

    def test_hlo_interleaves_collectives_with_backward(self):
        from apex_tpu.analysis import hlo
        from apex_tpu.analysis.targets import (ddp_int8_step,
                                               ddp_overlapped_step)

        fn, args, _ = ddp_overlapped_step()
        r = hlo.collective_compute_interleaving(
            fn.lower(*args).as_text())
        assert r["interleaved"], r
        assert r["compute_after_first_collective"] > 0
        # the bucketed baseline at the same size: one trailing block
        fn2, args2, _ = ddp_int8_step()
        r2 = hlo.collective_compute_interleaving(
            fn2.lower(*args2).as_text())
        assert not r2["interleaved"], r2

    def test_spans_interleave_in_jsonl(self, tmp_path):
        import glob

        from apex_tpu.analysis.targets import ddp_overlapped_step
        from apex_tpu.telemetry import MetricsRegistry, use_registry

        with use_registry(MetricsRegistry(jsonl_dir=str(tmp_path))) \
                as reg:
            fn, args, _ = ddp_overlapped_step()
            fn.lower(*args)  # spans fire at trace time
            reg.flush()
        events = []
        for path in glob.glob(str(tmp_path / "*.jsonl")):
            with open(path) as f:
                events.extend(json.loads(line) for line in f
                              if line.strip())
        assert [e for e in events if e["kind"] == "overlap"
                and e.get("name") == "plan"]
        roles = [e.get("role") for e in events if e["kind"] == "span"
                 and str(e.get("name", "")).startswith("ddp_overlap_")]
        seg_pos = [i for i, r in enumerate(roles) if r == "segment"]
        assert len(seg_pos) >= 2
        assert any(r == "bucket" and seg_pos[0] < i < seg_pos[-1]
                   for i, r in enumerate(roles)), roles


# ---------------------------------------------------------------------------
# ZeRO overlap mode
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestZeroOverlap:
    def _run(self, mesh, opt, params, x, y, depth, steps=2,
             segmented=False):
        def step_fn(p, state, xb, yb):
            if segmented:
                def lf(sp):
                    merged = {k: v for seg in sp for k, v in
                              seg.items()}
                    return _loss(merged, xb, yb, depth)

                loss, grads = jax.value_and_grad(lf)(p)
                p2, state = opt.step(list(grads), state, list(p))
            else:
                loss, grads = jax.value_and_grad(
                    lambda q: _loss(q, xb, yb, depth))(p)
                p2, state = opt.step(grads, state, p)
            return p2, state, loss

        step = jax.jit(jax.shard_map(
            step_fn, mesh=mesh, in_specs=(P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P()), check_vma=False))
        with mesh:
            state = jax.jit(lambda p: jax.shard_map(
                opt.init, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)(p))(params)
        p = params
        for _ in range(steps):
            p, state, loss = step(p, state, x, y)
        return p, state, float(loss)

    def test_adam_fp32_overlap_bit_identical(self, dp_mesh):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        mesh = dp_mesh(8)
        depth, hidden = 2, 64
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        p_b, _, loss_b = self._run(
            mesh, DistributedFusedAdam(lr=1e-2), params, x, y, depth)
        p_o, _, loss_o = self._run(
            mesh, DistributedFusedAdam(lr=1e-2, overlap=True,
                                       message_size=hidden * hidden),
            params, x, y, depth)
        assert loss_b == loss_o
        _assert_tree_equal(p_b, p_o, "zero adam fp32 ")

    def test_lamb_overlap_matches_within_summation_order(self, dp_mesh):
        from apex_tpu.contrib.optimizers import DistributedFusedLAMB

        mesh = dp_mesh(8)
        depth, hidden = 2, 64
        params = _params(hidden, depth)
        x, y = _data(mesh, hidden)
        p_b, _, _ = self._run(
            mesh, DistributedFusedLAMB(lr=1e-2), params, x, y, depth)
        p_o, _, _ = self._run(
            mesh, DistributedFusedLAMB(lr=1e-2, overlap=True,
                                       message_size=hidden * hidden),
            params, x, y, depth)
        for k in p_b:
            np.testing.assert_allclose(
                np.asarray(p_b[k]), np.asarray(p_o[k]),
                atol=1e-6, rtol=1e-5)

    def test_driver_matches_step_on_segments_bit_exact(self, dp_mesh):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        mesh = dp_mesh(8)
        depth, hidden = 2, 64
        params = _params(hidden, depth)
        seg_params = _seg_params(params, depth)
        x, y = _data(mesh, hidden)
        opt = DistributedFusedAdam(lr=1e-2, compress=True,
                                   overlap=True)
        # reference: opt.step over the segment list (monolithic grad)
        p_ref, _, loss_ref = self._run(mesh, opt, seg_params, x, y,
                                       depth, segmented=True)

        # driver: segmented backward with per-bucket scatter+update
        def drv(sp, state, xb, yb):
            segs = _segment_fns(depth, yb)
            loss, sp, state = overlapped_zero_step(segs, sp, opt,
                                                   state, xb)
            return sp, state, loss

        step = jax.jit(jax.shard_map(
            drv, mesh=mesh, in_specs=(P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P()), check_vma=False))
        with mesh:
            state = jax.jit(lambda p: jax.shard_map(
                opt.init, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)(p))(seg_params)
        sp = seg_params
        for _ in range(2):
            sp, state, loss = step(sp, state, x, y)
        assert float(loss) == loss_ref
        for i in range(depth):
            _assert_tree_equal(p_ref[i], sp[i], "zero driver ")

    def test_driver_requires_overlap_optimizer(self):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        with pytest.raises(ValueError, match="overlap=True"):
            overlapped_zero_step(
                [lambda p, h: h], [{}],
                DistributedFusedAdam(), {"step": 0}, None)

    def test_state_dict_full_consolidates_overlap_state(self):
        """The bucket-partitioned state consolidates into the SAME
        format-1 dict the monolithic layout writes (PR-15 bugfix: this
        used to raise NotImplementedError, stranding overlap=True runs
        without an elastic checkpoint tier), and a state whose bucket
        layout does not match the plan refuses loudly."""
        from apex_tpu.contrib.optimizers import (DistributedFusedAdam,
                                                 DistributedFusedLAMB)

        rng = np.random.RandomState(3)
        params = {"w": jnp.asarray(rng.randn(512, 2)
                                   .astype(np.float32)),
                  "b": jnp.asarray(rng.randn(24).astype(np.float32))}
        n = 512 * 2 + 24
        full0 = {"format": 1, "n_elements": n, "step": np.int32(9),
                 "master": rng.randn(n).astype(np.float32),
                 "exp_avg": rng.randn(n).astype(np.float32),
                 "exp_avg_sq": np.abs(rng.randn(n)).astype(np.float32),
                 "grad_residual": (rng.randn(n) * 1e-3)
                 .astype(np.float32)}
        for cls in (DistributedFusedAdam, DistributedFusedLAMB):
            opt = cls(overlap=True, compress=True, message_size=512)
            st = opt.load_state_dict_resharded(full0, params, world=8)
            assert "buckets" in st
            back = opt.state_dict_full(st, params, world=8)
            assert back["optimizer"] == cls.__name__
            for k in ("master", "exp_avg", "exp_avg_sq",
                      "grad_residual"):
                np.testing.assert_array_equal(back[k], full0[k])
            assert int(back["step"]) == 9
            with pytest.raises(ValueError, match="bucket state layout"):
                opt.state_dict_full(
                    {"step": jnp.zeros((), jnp.int32), "buckets": ()},
                    params, world=8)


# ---------------------------------------------------------------------------
# bench contract
# ---------------------------------------------------------------------------

@pytest.mark.multi_device
class TestBenchContract:
    def test_ddp_overlapped_emits_round15_contract(self, capsys):
        import os as _os
        import sys as _sys

        root = _os.path.dirname(_os.path.dirname(
            _os.path.dirname(_os.path.abspath(__file__))))
        for p in (root, _os.path.join(root, "tools")):
            if p not in _sys.path:
                _sys.path.insert(0, p)
        import bench
        import bench_schema_check as schema
        from apex_tpu.parallel import compression

        ret = bench.bench_ddp_overlapped(2, 1, hidden=128, depth=2)
        line = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        # round 18: the line now carries a MEASURED
        # static_comm_bytes_per_step (defined from round 18, so the
        # live line is checked against the current contract), agreeing
        # with the trace-measured bytes — the in-bench 25% gate would
        # have crashed the bench otherwise
        assert schema.check_metric_line(line, round_n=18,
                                        errors=[]) == []
        assert line["static_comm_bytes_per_step"] is not None
        assert line["measured_comm_bytes_per_step"] > 0
        assert abs(line["static_comm_bytes_per_step"]
                   - line["measured_comm_bytes_per_step"]) \
            <= 0.25 * line["measured_comm_bytes_per_step"]
        assert line["backend"] == "cpu-mesh"
        assert line["compile_count"] == 1
        assert line["overlap_segments"] == 2
        assert line["baseline_step_ms"] > 0
        assert "comm_hidden_pct" in line
        # identical comm-byte model to ddp_compressed: same element
        # count, same int8 payload
        n = line["grad_elements"]
        assert line["comm_bytes_per_step"] == \
            compression.estimate_allreduce_bytes(n, world=8,
                                                 compress="int8")
        assert ret["overlap_buckets"] >= 2
