"""amp O1 universal op interception via the shim namespaces.

Mirrors reference tests/L0/run_amp/test_basic_casts.py +
test_promotion.py: user code written against ``apex_tpu.amp.jnp`` (instead
of ``jax.numpy``) gets white-list ops in bf16, black-list ops in fp32 and
promote ops in the widest input dtype once ``amp.initialize(...,
opt_level="O1")`` has run — without decorating anything (reference
amp/amp.py:74-183 namespace patching; cast lists amp/lists/).
"""

import jax
import jax.numpy as real_jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import jnp as ajnp
from apex_tpu.amp import lax as alax
from apex_tpu.amp import nn as ann
from apex_tpu.amp.policy import DtypePolicy, set_global_policy


@pytest.fixture(autouse=True)
def _reset_policy():
    yield
    set_global_policy(DtypePolicy(enabled=False))


def plain_flax_style_model(params, x):
    """A user model written against the shim: two matmuls, a gelu, a
    softmax head and a cross-entropy-ish loss — no apex_tpu layers, no
    decorators."""
    h = ajnp.matmul(x, params["w1"])
    h = ann.gelu(h)
    h = ajnp.matmul(h, params["w2"])
    p = ann.log_softmax(h)
    return h, p, -ajnp.mean(ajnp.sum(p * params["onehot"], axis=-1))


def _params(rng):
    return {
        "w1": real_jnp.asarray(rng.randn(16, 32), real_jnp.float32),
        "w2": real_jnp.asarray(rng.randn(32, 8), real_jnp.float32),
        "onehot": real_jnp.asarray(np.eye(8)[rng.randint(0, 8, 4)],
                                   real_jnp.float32),
    }


class TestO1Interception:
    def test_disabled_passthrough(self, rng):
        params = _params(rng)
        x = real_jnp.asarray(rng.randn(4, 16), real_jnp.float32)
        h, p, loss = plain_flax_style_model(params, x)
        assert h.dtype == real_jnp.float32
        assert p.dtype == real_jnp.float32
        assert loss.dtype == real_jnp.float32

    def test_o1_casts_user_ops(self, rng):
        params = _params(rng)
        x = real_jnp.asarray(rng.randn(4, 16), real_jnp.float32)
        amp.initialize(params, None, opt_level="O1", verbosity=0)
        h, p, loss = plain_flax_style_model(params, x)
        # white list: matmuls ran (and produced) bf16
        assert h.dtype == real_jnp.bfloat16
        # black list: softmax + loss chain in fp32
        assert p.dtype == real_jnp.float32
        assert loss.dtype == real_jnp.float32

    def test_o1_under_jit(self, rng):
        params = _params(rng)
        x = real_jnp.asarray(rng.randn(4, 16), real_jnp.float32)
        amp.initialize(params, None, opt_level="O1", verbosity=0)
        h, p, loss = jax.jit(plain_flax_style_model)(params, x)
        assert h.dtype == real_jnp.bfloat16
        assert p.dtype == real_jnp.float32
        assert real_jnp.isfinite(loss)

    def test_o0_does_not_enable_shim(self, rng):
        params = _params(rng)
        x = real_jnp.asarray(rng.randn(4, 16), real_jnp.float32)
        amp.initialize(params, None, opt_level="O0", verbosity=0)
        h, _, _ = plain_flax_style_model(params, x)
        assert h.dtype == real_jnp.float32

    def test_autocast_block_overrides(self, rng):
        x = real_jnp.asarray(rng.randn(4, 16), real_jnp.float32)
        w = real_jnp.asarray(rng.randn(16, 16), real_jnp.float32)
        with amp.autocast():
            assert ajnp.matmul(x, w).dtype == real_jnp.bfloat16
        assert ajnp.matmul(x, w).dtype == real_jnp.float32

    def test_float_list_upcasts_bf16_inputs(self, rng):
        xb = real_jnp.asarray(rng.randn(4, 8), real_jnp.bfloat16)
        with amp.autocast():
            assert ajnp.sum(xb).dtype == real_jnp.float32
            assert ajnp.exp(xb).dtype == real_jnp.float32
            assert ann.softmax(xb).dtype == real_jnp.float32

    def test_promote_mixed_dtypes(self, rng):
        a = real_jnp.asarray(rng.randn(4, 8), real_jnp.bfloat16)
        b = real_jnp.asarray(rng.randn(4, 8), real_jnp.float32)
        with amp.autocast():
            assert ajnp.add(a, b).dtype == real_jnp.float32
            assert ajnp.concatenate([a, b]).dtype == real_jnp.float32

    def test_lax_conv_half(self, rng):
        x = real_jnp.asarray(rng.randn(2, 8, 8, 3), real_jnp.float32)
        k = real_jnp.asarray(rng.randn(3, 3, 3, 4), real_jnp.float32)
        with amp.autocast():
            y = alax.conv_general_dilated(
                x, k, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert y.dtype == real_jnp.bfloat16

    def test_unlisted_ops_forwarded(self):
        # the shim tracks jax.numpy's surface for everything unlisted
        assert ajnp.arange(4).dtype == real_jnp.arange(4).dtype
        assert ajnp.pi == real_jnp.pi
        np.testing.assert_array_equal(
            np.asarray(ajnp.tril(real_jnp.ones((3, 3)))),
            np.tril(np.ones((3, 3))))

    def test_grads_flow_through_shim(self, rng):
        params = _params(rng)
        x = real_jnp.asarray(rng.randn(4, 16), real_jnp.float32)
        amp.initialize(params, None, opt_level="O1", verbosity=0)
        grads = jax.grad(
            lambda p: plain_flax_style_model(p, x)[2])(params)
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf, np.float32)).all()
        # master-grad dtype preserved: grads of fp32 params come back fp32
        assert grads["w1"].dtype == real_jnp.float32
