"""Contrib parity tier 3: FastLayerNorm, conv_bias_relu, cudnn_gbn,
deprecated optimizers, memory buffers, testing harness, multiproc.

Mirrors the reference per-extension numerics pattern
(apex/contrib/test/<pkg>/test_*.py): each fused entry point vs a plain
jnp/flax oracle.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.conv_bias_relu import (
    conv_bias,
    conv_bias_mask_relu,
    conv_bias_relu,
    conv_frozen_scale_bias_relu,
)
from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
from apex_tpu.contrib.layer_norm import FastLayerNorm, _fast_layer_norm
from apex_tpu.transformer.tensor_parallel.memory import (
    MemoryBuffer,
    RingMemBuffer,
)


# -- FastLayerNorm ----------------------------------------------------------

def test_fast_layer_norm_matches_oracle(rng):
    x = jnp.asarray(rng.randn(4, 32).astype(np.float32))
    w = jnp.asarray(rng.randn(32).astype(np.float32))
    b = jnp.asarray(rng.randn(32).astype(np.float32))
    out = _fast_layer_norm(x, w, b, 1e-5)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / jnp.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fast_layer_norm_module_checkpoint_compat(rng):
    from apex_tpu.normalization import FusedLayerNorm

    x = jnp.asarray(rng.randn(2, 16).astype(np.float32))
    fast = FastLayerNorm(hidden_size=16)
    p = fast.init(jax.random.PRNGKey(0), x)
    # param names interchange with FusedLayerNorm
    fused = FusedLayerNorm(normalized_shape=16)
    out_fast = fast.apply(p, x)
    out_fused = fused.apply(p, x)
    np.testing.assert_allclose(np.asarray(out_fast), np.asarray(out_fused),
                               rtol=1e-6, atol=1e-6)


# -- conv_bias_relu ---------------------------------------------------------

def _conv_ref(x, w, padding, stride):
    from jax import lax

    return lax.conv_general_dilated(
        x.astype(jnp.float32), w.astype(jnp.float32), (stride, stride),
        ((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "OHWI", "NHWC"))


@pytest.fixture
def conv_inputs(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
    w = jnp.asarray(rng.randn(6, 3, 3, 4).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(6).astype(np.float32))
    return x, w, b


def test_conv_bias_relu(conv_inputs):
    x, w, b = conv_inputs
    out = conv_bias_relu(x, w, b, padding=1, stride=1)
    ref = jnp.maximum(_conv_ref(x, w, 1, 1) + b, 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    assert out.shape == (2, 8, 8, 6)


def test_conv_bias_stride2(conv_inputs):
    x, w, b = conv_inputs
    out = conv_bias(x, w, b, padding=1, stride=2)
    ref = _conv_ref(x, w, 1, 2) + b
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    assert out.shape == (2, 4, 4, 6)


def test_conv_bias_mask_relu(conv_inputs, rng):
    x, w, b = conv_inputs
    mask = jnp.asarray((rng.rand(2, 8, 8, 6) > 0.5).astype(np.float32))
    out = conv_bias_mask_relu(x, w, b, mask, padding=1, stride=1)
    ref = jnp.maximum((_conv_ref(x, w, 1, 1) + b) * mask, 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_conv_frozen_scale_bias_relu(conv_inputs, rng):
    x, w, b = conv_inputs
    scale = jnp.asarray(rng.rand(6).astype(np.float32) + 0.5)
    out = conv_frozen_scale_bias_relu(x, w, scale, b, padding=1, stride=1)
    ref = jnp.maximum(_conv_ref(x, w, 1, 1) * scale + b, 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_conv_bias_relu_bf16_keeps_dtype(conv_inputs):
    x, w, b = conv_inputs
    out = conv_bias_relu(x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
                         b, padding=1, stride=1)
    assert out.dtype == jnp.bfloat16


# -- cudnn_gbn --------------------------------------------------------------

def test_group_batch_norm_single_group_matches_flax(rng):
    import flax.linen as nn

    x = jnp.asarray(rng.randn(4, 6, 6, 8).astype(np.float32))
    gbn = GroupBatchNorm2d(num_features=8, group_size=1)
    vs = gbn.init(jax.random.PRNGKey(0), x, use_running_average=False)
    out, _ = gbn.apply(vs, x, use_running_average=False,
                       mutable=["batch_stats"])
    ref_bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                          epsilon=1e-5)
    ref_vs = ref_bn.init(jax.random.PRNGKey(0), x)
    ref, _ = ref_bn.apply(ref_vs, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_group_batch_norm_input_validation():
    gbn = GroupBatchNorm2d(num_features=8)
    with pytest.raises(ValueError, match="4D"):
        gbn.init(jax.random.PRNGKey(0), jnp.zeros((2, 8)))
    with pytest.raises(ValueError, match="channels"):
        gbn.init(jax.random.PRNGKey(0), jnp.zeros((2, 4, 4, 3)))


# -- deprecated contrib optimizers -----------------------------------------

def test_deprecated_optimizers_warn_and_step(rng):
    from apex_tpu.contrib.optimizers import FusedAdam, FusedSGD

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        opt = FusedAdam(lr=1e-3, use_mt=True)  # old kwarg accepted
        assert any(issubclass(x.category, DeprecationWarning) for x in w)
    params = {"w": jnp.asarray(rng.randn(4).astype(np.float32))}
    grads = {"w": jnp.ones(4, jnp.float32)}
    state = opt.init(params)
    new_params, _ = opt.step(grads, state, params)
    assert not np.allclose(np.asarray(new_params["w"]),
                           np.asarray(params["w"]))

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        FusedSGD(lr=0.1)
        assert any(issubclass(x.category, DeprecationWarning) for x in w)


# -- memory buffers ---------------------------------------------------------

def test_memory_buffer_views_and_overflow():
    buf = MemoryBuffer("test", 16, np.float32, track_usage=True)
    a = buf.add((2, 4))
    b = buf.add((8,))
    assert a.shape == (2, 4) and b.shape == (8,)
    a[:] = 1.0  # views alias the backing store
    assert buf.get_data()[:8].sum() == 8.0
    with pytest.raises(MemoryError):
        buf.add((1,))
    buf.reset()
    assert not buf.is_in_use()
    assert buf.add((16,)).shape == (16,)


def test_ring_mem_buffer_rotation():
    ring = RingMemBuffer("ring", 2, 8, np.float32)
    b0 = ring.get_next_buffer()
    b1 = ring.get_next_buffer()
    assert b0 is not b1
    b0.add((4,))
    with pytest.raises(RuntimeError):
        for _ in range(2):  # wraps to b0 which is in use
            ring.get_next_buffer()


# -- testing harness --------------------------------------------------------

def test_arguments_and_global_vars():
    from apex_tpu.transformer.testing import (
        arguments,
        global_vars,
    )

    args = arguments.parse_args(args=[
        "--num-layers", "4", "--hidden-size", "32",
        "--num-attention-heads", "4", "--micro-batch-size", "2",
        "--vocab-size", "1000", "--bf16"])
    assert args.padded_vocab_size == 1024  # rounded to 128*tp
    assert args.ffn_hidden_size == 128
    assert args.data_parallel_size >= 1
    global_vars.destroy_global_vars()
    global_vars.set_global_variables(args)
    assert global_vars.get_args() is args
    assert global_vars.get_num_microbatches() >= 1
    global_vars.get_timers()("tick").start()
    global_vars.get_timers()("tick").stop()
    global_vars.destroy_global_vars()


def test_model_providers_from_args():
    from apex_tpu.transformer.testing import (
        bert_model_provider,
        global_vars,
        gpt_model_provider,
        parse_args,
    )

    global_vars.destroy_global_vars()
    args = parse_args(args=["--num-layers", "2", "--hidden-size", "32",
                            "--num-attention-heads", "4",
                            "--vocab-size", "256"])
    global_vars.set_global_variables(args)
    gpt = gpt_model_provider()
    bert = bert_model_provider()
    tokens = jnp.zeros((2, 8), jnp.int32)
    p = gpt.init(jax.random.PRNGKey(0), tokens)
    logits = gpt.apply(p, tokens)
    assert logits.shape == (2, 8, args.padded_vocab_size)
    pb = bert.init(jax.random.PRNGKey(0), tokens)
    mlm, nsp = bert.apply(pb, tokens)
    assert mlm.shape == (2, 8, args.padded_vocab_size)
    global_vars.destroy_global_vars()


def test_multiproc_env_wiring(tmp_path):
    import subprocess
    import sys

    script = tmp_path / "probe.py"
    script.write_text(
        "import os\n"
        "print(os.environ.get('APEX_TPU_COORDINATOR'),"
        " os.environ.get('APEX_TPU_NUM_PROCESSES'),"
        " os.environ.get('APEX_TPU_PROCESS_ID'))\n")
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc",
         "--nnodes", "4", "--node_rank", "2",
         "--coordinator", "host0:1234", str(script)],
        capture_output=True, text=True, cwd=repo_root)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "host0:1234 4 2"


# -- sparsity permutation search --------------------------------------------

def test_permutation_search_improves_retained_magnitude(rng):
    from apex_tpu.contrib.sparsity.permutation_lib import (
        apply_permutation_in_C_dim,
        permutation_improvement,
        search_for_good_permutation,
        sum_after_2_to_4,
    )

    # adversarial layout: large weights concentrated in the same 4-groups
    # so the 2:4 mask must drop some; permuting spreads them out
    w = rng.randn(8, 16).astype(np.float32) * 0.1
    w[:, :4] += np.sign(w[:, :4]) * 3.0  # one hot group
    w = jnp.asarray(w)

    perm, w_perm = search_for_good_permutation(w, num_iters=30)
    before, after = permutation_improvement(w, perm)
    assert after > before, (before, after)
    # permuted result matches applying perm to the original
    np.testing.assert_allclose(
        np.asarray(apply_permutation_in_C_dim(w, perm)), np.asarray(w_perm),
        rtol=1e-6, atol=1e-6)
    # perm is a permutation
    assert sorted(perm.tolist()) == list(range(16))
    # identity on already-uniform weights: no spurious swaps reduce kept sum
    u = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    pu, wu = search_for_good_permutation(u, num_iters=5)
    assert float(sum_after_2_to_4(wu)) >= float(sum_after_2_to_4(u)) - 1e-6


def test_permutation_k_dim_inverse(rng):
    from apex_tpu.contrib.sparsity.permutation_lib import (
        apply_permutation_in_C_dim,
        apply_permutation_in_K_dim,
    )

    # consumer permutes C; producer permutes K with the same perm: the
    # composition y = W2 @ relu-free (W1 x) is preserved for linear chains
    w1 = jnp.asarray(rng.randn(16, 8).astype(np.float32))  # [C=16 out, 8 in]
    w2 = jnp.asarray(rng.randn(4, 16).astype(np.float32))  # consumes C=16
    x = jnp.asarray(rng.randn(8).astype(np.float32))
    perm = np.asarray(rng.permutation(16))
    y_ref = w2 @ (w1 @ x)
    y_perm = apply_permutation_in_C_dim(w2, perm) @ (
        apply_permutation_in_K_dim(w1, perm) @ x)
    np.testing.assert_allclose(np.asarray(y_perm), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
