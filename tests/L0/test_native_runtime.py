"""Native runtime (apex_tpu_C) + data loader + bucketed allreduce tests.

Mirrors the reference's apex_C flatten/unflatten usage in DDP
(apex/parallel/distributed.py:15-35) and its bucket-structure logic
(287-320); the prefetch loader mirrors examples/imagenet data_prefetcher.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import _C
from apex_tpu.data import PrefetchLoader
from apex_tpu.parallel.distributed import (
    all_reduce_gradients,
    all_reduce_gradients_bucketed,
    plan_buckets,
)


def test_native_extension_is_built():
    assert _C.HAVE_NATIVE, "apex_tpu_C should be built in this environment"


@pytest.fixture(params=["native", "fallback"])
def c_impl(request, monkeypatch):
    """Run the _C entry points through both the native extension and the
    numpy fallback (the APEX_TPU_NO_EXT build)."""
    if request.param == "fallback":
        monkeypatch.setattr(_C, "_ext", None)
    return request.param


def test_flatten_unflatten_roundtrip(rng, c_impl):
    arrays = [rng.randn(*s).astype(np.float32)
              for s in [(3, 4), (7,), (2, 2, 2)]]
    total = sum(a.size for a in arrays)
    flat = np.zeros(total, np.float32)
    nbytes = _C.flatten(arrays, flat)
    assert nbytes == total * 4
    outs = [np.zeros_like(a) for a in arrays]
    _C.unflatten_into(flat, outs)
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)


def test_flatten_out_too_small(c_impl):
    with pytest.raises(ValueError):
        _C.flatten([np.zeros(4, np.float32)], np.zeros(2, np.float32))


def test_flatten_noncontiguous_out_raises(rng, c_impl):
    # non-contiguous out must raise in BOTH impls (the numpy fallback once
    # silently dropped the writes into a reshape temporary)
    arrays = [np.ones((2, 2), np.float32)]
    out = np.zeros((4, 2), np.float32).T[:, ::1]  # transposed view
    assert not out.flags["C_CONTIGUOUS"]
    with pytest.raises(ValueError, match="contiguous"):
        _C.flatten(arrays, out)
    with pytest.raises(ValueError, match="contiguous"):
        _C.unflatten_into(np.zeros(8, np.float32), [out])


def test_assign_buckets_semantics(c_impl):
    # greedy in-order: consecutive tensors share until cap exceeded
    assert _C.assign_buckets([4, 4, 4, 4], 8) == [0, 0, 1, 1]
    assert _C.assign_buckets([10, 1, 1], 8) == [0, 1, 1]  # oversized alone
    assert _C.assign_buckets([], 8) == []
    with pytest.raises(ValueError):
        _C.assign_buckets([1], 0)


def test_pack_batch_matches_stack(rng, c_impl):
    samples = [rng.randn(4, 5).astype(np.float32) for _ in range(8)]
    out = np.zeros((8, 4, 5), np.float32)
    assert _C.pack_batch(samples, out) == 8
    np.testing.assert_array_equal(out, np.stack(samples))


def test_pack_batch_size_mismatch(c_impl):
    with pytest.raises(ValueError):
        _C.pack_batch([np.zeros(3, np.float32), np.zeros(4, np.float32)],
                      np.zeros(7, np.float32))


def test_prefetch_loader_batches(rng):
    xs = [rng.randn(4).astype(np.float32) for _ in range(10)]
    loader = PrefetchLoader(xs, batch_size=4, drop_last=True)
    batches = list(loader)
    assert len(batches) == 2
    np.testing.assert_array_equal(batches[0], np.stack(xs[:4]))
    np.testing.assert_array_equal(batches[1], np.stack(xs[4:8]))


def test_prefetch_loader_tuples_and_device_put(rng):
    samples = [(rng.randn(3).astype(np.float32), np.int32(i))
               for i in range(6)]
    loader = PrefetchLoader(samples, batch_size=3, drop_last=False,
                            device_put=jax.device_put)
    batches = list(loader)
    assert len(batches) == 2
    x, y = batches[0]
    assert isinstance(x, jax.Array) and x.shape == (3, 3)
    np.testing.assert_array_equal(np.asarray(y), np.arange(3))


def test_prefetch_loader_propagates_errors():
    def bad():
        yield np.zeros(2, np.float32)
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(PrefetchLoader(bad(), batch_size=1))


def test_plan_buckets_dtype_segregated():
    leaves = [jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.bfloat16),
              jnp.zeros(4, jnp.float32), jnp.zeros(8, jnp.float32)]
    buckets = plan_buckets(leaves, message_size=8)
    # fp32 leaves (0, 2, 3): [0, 2] fit in 8, [3] overflows; bf16: [1]
    assert [sorted(b) for b in buckets] == [[0, 2], [3], [1]]


def test_bucketed_allreduce_matches_per_leaf(rng):
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("dp",))
    grads = {
        "a": jnp.asarray(rng.randn(4, 3, 5).astype(np.float32)),
        "b": jnp.asarray(rng.randn(4, 7).astype(np.float32)),
        "c": jnp.asarray(rng.randn(4, 2, 2).astype(np.float32)).astype(jnp.bfloat16),
    }

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"), check_vma=False)
    def bucketed(g):
        return all_reduce_gradients_bucketed(g, "dp", message_size=8)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"), check_vma=False)
    def per_leaf(g):
        return all_reduce_gradients(g, "dp")

    out_b = bucketed(grads)
    out_l = per_leaf(grads)
    for k in grads:
        np.testing.assert_allclose(
            np.asarray(out_b[k], np.float32), np.asarray(out_l[k], np.float32),
            rtol=1e-6, atol=1e-6)


def test_prefetch_loader_early_break_releases_worker(rng):
    import threading

    xs = [rng.randn(4).astype(np.float32) for _ in range(64)]
    before = threading.active_count()
    for _ in range(5):
        for batch in PrefetchLoader(xs, batch_size=4, prefetch=1):
            break  # consumer abandons the iterator immediately
    import time
    deadline = time.time() + 6
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before, "worker threads leaked"


def test_prefetch_loader_shape_mismatch_raises(rng):
    samples = [rng.randn(2, 3).astype(np.float32),
               rng.randn(3, 2).astype(np.float32)]  # same nbytes!
    with pytest.raises(ValueError, match="mismatch"):
        list(PrefetchLoader(samples, batch_size=2))
