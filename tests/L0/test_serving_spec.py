"""Speculative + prefix-cached serving (ISSUE 12): the two serving
multipliers promoted into ServeEngine.

The contract under test:

- **token identity** — the speculative engine (draft-k proposals, one
  fused chunked verification per dispatch, per-slot MIXED acceptance)
  and the prefix-cached engine (KV rows seeded from the host-side
  store, suffix-bucket prefill) each emit EXACTLY the plain engine's
  greedy streams, in bf16 and int8 cache modes, composed or alone;
- **flat ladder** — both multipliers swap executable bodies, never add
  ladder entries: ``compile_count`` == the bucket-ladder size and a
  warm trace compiles nothing (``assert_no_recompiles``);
- **fault-path composition** — a poisoned slot mid-speculative-round
  quarantines exactly that slot (healthy slots' streams untouched),
  and a transient decode failure retries through the PR-7 machinery
  unchanged;
- **store/span primitives** — ``KVCacheSpec.update_rows_span`` keeps
  untouched int8 blocks bit-identical, ``PrefixStore`` LRU/covers
  semantics, shared-prefix ``synthetic_trace`` determinism.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.resilience import faults
from apex_tpu.serving import (
    PrefixStore,
    Request,
    Scheduler,
    ServeConfig,
    ServeEngine,
    synthetic_trace,
)
from apex_tpu.telemetry import assert_no_recompiles
from apex_tpu.telemetry.registry import MetricsRegistry, use_registry
from apex_tpu.transformer import parallel_state

VOCAB = 96


def _cfg(layers=2, hidden=48, **kw):
    base = dict(
        hidden_size=hidden, num_layers=layers, num_attention_heads=4,
        vocab_size=VOCAB, max_position_embeddings=64,
        compute_dtype=jnp.float32, use_flash_attention=False,
        normalization="rmsnorm", position_embedding_type="rope",
        activation="swiglu", num_query_groups=2)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(autouse=True)
def _single_device():
    parallel_state.destroy_model_parallel()


@pytest.fixture(scope="module")
def models():
    parallel_state.destroy_model_parallel()
    rng = np.random.RandomState(0)
    tcfg = _cfg()
    target = GPTModel(tcfg, decode=True)
    tparams = GPTModel(tcfg).init(
        jax.random.PRNGKey(1),
        jnp.asarray(rng.randint(0, VOCAB, (1, 8))))["params"]
    dcfg = _cfg(layers=1, hidden=32)
    draft = GPTModel(dcfg, decode=True)
    dparams = GPTModel(dcfg).init(
        jax.random.PRNGKey(7),
        jnp.asarray(rng.randint(0, VOCAB, (1, 8))))["params"]
    return target, tparams, draft, dparams


def _serve_cfg(**kw):
    base = dict(batch_buckets=(2, 4), prefill_buckets=(8, 16),
                num_slots=4, eos_token_id=None, temperature=0.0)
    base.update(kw)
    return ServeConfig(**base)


def _trace(n=6, seed=5, max_new=(5, 8)):
    return synthetic_trace(
        n, seed=seed, mean_interarrival=0.4, prompt_lens=(3, 5),
        max_new=max_new, vocab_size=VOCAB, shared_prefix_len=7,
        shared_frac=0.8)


def _streams(completed):
    return {c.rid: np.asarray(c.tokens).tolist() for c in completed}


@pytest.fixture(scope="module")
def plain_engine(models):
    target, tparams, _, _ = models
    return ServeEngine(target, tparams, _serve_cfg())


@pytest.fixture(scope="module")
def spec_engine(models):
    """Speculative + prefix-cached engine (the composed configuration
    the serve_spec bench ships) shared across the module — engine AOT
    compile is the dominant test cost."""
    target, tparams, draft, dparams = models
    return ServeEngine(target, tparams, _serve_cfg(
        draft_model=draft, draft_params=dparams, num_draft_tokens=3,
        prefix_cache=True, prefix_min_len=4))


@pytest.fixture(scope="module")
def plain_streams(plain_engine):
    done, _ = plain_engine.serve(_trace())
    return _streams(done)


def test_spec_engine_token_identical_and_flat(spec_engine,
                                              plain_streams):
    """The flagship acceptance: a mixed-length continuous-batching
    trace through the speculative + prefix-cached engine is
    token-identical to the plain engine, with the ladder flat and a
    warm trace compiling NOTHING (the fused draft/verify epilogue and
    the seeded prefill are the same executables traffic already
    used)."""
    # ladder size is invariant: 2 batch-buckets x 2 prefill-buckets
    # + 2 decode = 6 executables, draft or not
    assert spec_engine.compile_count == 2 * 2 + 2
    assert spec_engine.spec_enabled
    assert spec_engine.decode_headroom == 3
    done, stats = spec_engine.serve(_trace())       # warm trace
    assert _streams(done) == plain_streams
    with assert_no_recompiles():
        done2, stats2 = spec_engine.serve(_trace())
    assert _streams(done2) == plain_streams
    # the draft is independent (partial agreement) — acceptance must
    # be real but NOT vacuous, and every token is target-verified
    assert stats2["spec_proposed"] > 0
    assert 0 <= stats2["acceptance_rate"] <= 1
    assert stats2["accepted_tokens_per_sec"] > 0
    # the shared-prefix trace must actually hit the store by now
    assert stats2["prefix_hits"] > 0
    assert stats2["prefix_hit_rate"] > 0


def test_prefix_cache_alone_token_identical(models, plain_streams):
    """Prefix cache without speculation: seeded suffix prefills are
    token-exact, hits accumulate across requests, and the TTFT split
    lands in stats."""
    target, tparams, _, _ = models
    eng = ServeEngine(target, tparams, _serve_cfg(
        prefix_cache=True, prefix_min_len=4))
    done, stats = eng.serve(_trace())
    assert _streams(done) == plain_streams
    assert stats["prefix_lookups"] > 0
    assert stats["prefix_hits"] > 0
    assert stats["prefix_store_entries"] > 0
    assert stats["prefix_store_bytes"] > 0
    assert stats["ttft_p50_prefix_hit_ms"] is not None
    # a fresh identical trace hits harder (every prompt already cached)
    done2, stats2 = eng.serve(_trace())
    assert _streams(done2) == plain_streams
    assert stats2["prefix_hits"] > stats["prefix_hits"]


@pytest.mark.slow
def test_int8_spec_prefix_token_identical(models):
    """int8 store composition: the speculative window re-quantizes
    only its k+1 positions, and a prefix hit seeds the RAW
    full-precision rows (so the suffix forward sees what a cold
    prefill saw and re-quantization reproduces the cold bits), so the
    composed int8 engine matches the plain int8 engine
    token-for-token."""
    target, tparams, draft, dparams = models
    base = ServeEngine(target, tparams, _serve_cfg(cache_mode="int8"))
    done_a, _ = base.serve(_trace())
    eng = ServeEngine(target, tparams, _serve_cfg(
        cache_mode="int8", draft_model=draft, draft_params=dparams,
        num_draft_tokens=3, prefix_cache=True, prefix_min_len=4))
    done_b, stats = eng.serve(_trace())
    assert _streams(done_b) == _streams(done_a)
    assert stats["prefix_hits"] > 0


def test_spec_quarantine_poisons_only_one_slot(models, spec_engine,
                                               plain_streams):
    """PR-7 composition: a slot-NaN injected mid-speculative-round
    evicts exactly that request as ``poisoned`` (KV rows of BOTH
    stores reset in-graph) while the other slots keep their exact
    greedy streams, and a transient decode failure is absorbed by one
    retry."""
    sched = Scheduler(spec_engine)
    for r in _trace():
        sched.submit(r)
    nan_armed = fail_armed = False
    try:
        while sched.pending or sched.active:
            if not nan_armed and len(sched.active) >= 2:
                faults.arm_slot_nan(sorted(sched.active)[0],
                                    spec_engine._decode_calls)
                nan_armed = True
            elif nan_armed and not fail_armed and sched.active:
                faults.arm_decode_failure(spec_engine._decode_calls,
                                          transient=True)
                fail_armed = True
            if not sched.active and sched.pending and \
                    min(r.arrival for r in sched.pending) > sched.tick:
                sched.tick = min(r.arrival for r in sched.pending)
            sched.step()
    finally:
        faults.disarm_slot_nan()
        faults.disarm_decode_failure()
    stats = sched.stats()
    assert nan_armed and fail_armed
    assert stats["requests_quarantined"] == 1
    assert stats["requests_failed"] == 0
    assert stats["decode_retries"] >= 1
    # every non-poisoned request still matches the plain engine
    got = _streams(sched.completed)
    poisoned = [c.rid for c in sched.completed
                if c.finish_reason == "poisoned"]
    assert len(poisoned) == 1
    for rid, toks in got.items():
        if rid not in poisoned:
            assert toks == plain_streams[rid], f"rid {rid} diverged"


def test_spec_budget_headroom_rejected(spec_engine):
    """Admission accounts for the speculative window: a request whose
    prompt + budget would let the draft overshoot the position buffer
    is rejected ``budget_too_long`` instead of corrupting the cache
    tail."""
    sched = Scheduler(spec_engine)
    max_len = spec_engine.max_len
    k = spec_engine.decode_headroom
    prompt = np.zeros((5,), np.int32)
    ok = sched.submit(Request(rid=901, prompt=prompt,
                              max_new_tokens=max_len - 5 - k + 1))
    assert not ok
    assert sched.rejected[-1].reason == "budget_too_long"
    assert sched.submit(Request(rid=902, prompt=prompt,
                                max_new_tokens=max_len - 5 - k))


def test_spec_prefix_telemetry_events(spec_engine, tmp_path):
    """The acceptance and prefix rollups land: serve/spec_proposed /
    serve/spec_accepted / serve/prefix_* counters plus the spec_report
    and prefix_report events tools/telemetry_report.py renders. The
    module engine is reused — its instruments resolve the ACTIVE
    registry per call, so scoping is the registry context, not the
    engine (and an extra AOT build would be pure tier-1 cost)."""
    import json

    with use_registry(MetricsRegistry(jsonl_dir=str(tmp_path))) as reg:
        spec_engine.serve(_trace(n=4))
        assert reg.counter_value("serve/spec_proposed") > 0
        # the module engine's store is warm by now: hits, not misses
        assert reg.counter_value("serve/prefix_hits") > 0
        reg.flush()
    events = []
    for p in tmp_path.glob("telemetry-rank*.jsonl"):
        events += [json.loads(ln) for ln in p.read_text().splitlines()]
    names = [(e.get("kind"), e.get("name")) for e in events]
    assert ("serve", "spec_report") in names
    assert ("serve", "prefix_report") in names
    assert ("serve", "prefix_lookup") in names
    spec_ev = [e for e in events
               if e.get("name") == "spec_report"][-1]
    assert spec_ev["proposed"] >= spec_ev["accepted"] >= 0


def test_spec_engine_validation(models):
    target, tparams, draft, dparams = models
    with pytest.raises(ValueError, match="draft_params"):
        ServeEngine(target, tparams, _serve_cfg(draft_model=draft))
    with pytest.raises(ValueError, match="greedy-only"):
        ServeEngine(target, tparams, _serve_cfg(
            draft_model=draft, draft_params=dparams, temperature=0.7))
    with pytest.raises(ValueError, match="num_draft_tokens"):
        ServeEngine(target, tparams, _serve_cfg(
            draft_model=draft, draft_params=dparams,
            num_draft_tokens=0))
    with pytest.raises(ValueError, match="vocab"):
        small = GPTModel(dataclasses.replace(_cfg(layers=1, hidden=32),
                                             vocab_size=48),
                         decode=True)
        ServeEngine(target, tparams, _serve_cfg(
            draft_model=small, draft_params=dparams))
    with pytest.raises(ValueError, match="decode=True"):
        ServeEngine(target, tparams, _serve_cfg(
            draft_model=GPTModel(_cfg(layers=1, hidden=32)),
            draft_params=dparams))


def test_census_labels_cover_draft(spec_engine, plain_engine):
    """The bugfix satellite: OOM census labels must name the draft
    ladder's buffers, and every AOT registration (draft/verify
    included) must sit under the engine's name prefix so fleet respawn
    recompile accounting stays exact."""
    labels = spec_engine.census_labels()
    assert set(labels) == {"params", "kv_cache", "draft_params",
                           "kv_cache_draft"}
    assert set(plain_engine.census_labels()) == {"params", "kv_cache"}
    assert spec_engine.draft_kv_cache_bytes() > 0
    assert plain_engine.draft_kv_cache_bytes() == 0
    # named engine: every ladder entry registers under the prefix
    target, tparams, draft, dparams = (
        spec_engine.model, spec_engine._params,
        spec_engine.config.draft_model, spec_engine._draft_params)
    from apex_tpu.telemetry import CompileWatcher

    watcher = CompileWatcher(enabled=True)
    ServeEngine(target, tparams, _serve_cfg(
        batch_buckets=(2,), prefill_buckets=(8,),
        draft_model=draft, draft_params=dparams, num_draft_tokens=2),
        watcher=watcher, name="replica9.g1")
    names = [n for n in watcher.functions
             if "spec_decode" in n or "prefill" in n]
    assert names, "no AOT registrations observed"
    assert all(n.startswith("replica9.g1/serve/") for n in names)


def test_update_rows_span_no_drift(models):
    """int8 span update: positions outside [start, start+span) keep
    their exact int8 payload + scales; span=1 matches update_rows_at
    bit-for-bit."""
    target, _, _, _ = models
    from apex_tpu.serving import KVCacheSpec

    spec = KVCacheSpec(target, 2, mode="int8")
    rng = np.random.RandomState(3)

    def rand_rows(b):
        def leaf(sd):
            return jnp.asarray(
                rng.randn(*((b,) + tuple(sd.shape))).astype(
                    np.float32)).astype(sd.dtype)
        return jax.tree_util.tree_map(leaf, spec.template)

    base = rand_rows(2)
    store_rows = spec.quantize_rows(base)
    fresh = rand_rows(2)
    start = jnp.asarray([4, 9], jnp.int32)
    span = 3
    merged = spec.update_rows_span(store_rows, fresh, start, span)

    def kv_leaves(tree):
        return [(p, l) for p, l in
                jax.tree_util.tree_flatten_with_path(
                    tree, is_leaf=lambda x: isinstance(x, dict)
                    and "q" in x)[0] if isinstance(l, dict)]

    for (_, old), (_, new) in zip(kv_leaves(store_rows),
                                  kv_leaves(merged)):
        t = old["q"].shape[-3]
        for b in range(2):
            lo = int(start[b])
            for pos in range(t):
                inside = lo <= pos < lo + span
                same_q = np.array_equal(np.asarray(old["q"][b, pos]),
                                        np.asarray(new["q"][b, pos]))
                same_s = np.array_equal(
                    np.asarray(old["scale"][b, pos]),
                    np.asarray(new["scale"][b, pos]))
                if not inside:
                    assert same_q and same_s, \
                        f"untouched position {pos} drifted"
    # span=1 == update_rows_at
    pos1 = jnp.asarray([4, 9], jnp.int32)
    a = spec.update_rows_at(store_rows, fresh, pos1)
    b = spec.update_rows_span(store_rows, fresh, pos1, 1)
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_prefix_store_semantics():
    """Host-side store unit contract: hash-keyed lookup returns the
    longest usable cut, covers() blocks redundant insertions, strict
    prefixes are superseded, LRU bounds entries."""
    store = PrefixStore(max_entries=2, min_len=3)
    rows = {"x": np.ones((4,), np.float32)}
    p1 = np.asarray([1, 2, 3, 4, 5], np.int32)
    assert store.insert(p1, rows) is not None
    # full-coverage re-insert refused
    assert store.covers(p1)
    assert store.insert(p1, rows) is None
    # longer prompt sharing the prefix: lookup cut caps at len-1
    p2 = np.asarray([1, 2, 3, 4, 5, 6, 7], np.int32)
    cut, entry = store.lookup(p2)
    assert cut == 5 and entry is not None
    # a longer entry supersedes its strict prefix (still 1 keyed slot)
    assert store.insert(p2, rows) is not None
    assert len(store) == 1
    cut, _ = store.lookup(np.asarray([1, 2, 3, 9], np.int32))
    assert cut == 3
    # too-short prompts neither hit nor insert
    assert store.lookup(np.asarray([1, 2, 3], np.int32)) == (0, None)
    assert store.insert(np.asarray([1, 2, 3], np.int32), rows) is None
    # LRU eviction at capacity
    assert store.insert(np.asarray([9, 9, 9, 9], np.int32), rows)
    assert store.insert(np.asarray([8, 8, 8, 8], np.int32), rows)
    assert len(store) == 2
    s = store.stats()
    assert s["evictions"] == 1 and s["entries"] == 2
    assert s["lookups"] >= 3 and s["hits"] >= 2


def test_shared_prefix_trace_determinism():
    """shared_prefix_len=0 leaves the legacy byte stream untouched;
    > 0 makes ~shared_frac of prompts open with ONE shared block,
    deterministically per seed."""
    legacy_a = synthetic_trace(8, seed=11)
    legacy_b = synthetic_trace(8, seed=11, shared_prefix_len=0)
    for ra, rb in zip(legacy_a, legacy_b):
        np.testing.assert_array_equal(ra.prompt, rb.prompt)
        assert ra.arrival == rb.arrival
        assert ra.max_new_tokens == rb.max_new_tokens
    shared_a = synthetic_trace(16, seed=11, shared_prefix_len=6)
    shared_b = synthetic_trace(16, seed=11, shared_prefix_len=6)
    for ra, rb in zip(shared_a, shared_b):
        np.testing.assert_array_equal(ra.prompt, rb.prompt)
    counts = {}
    for r in shared_a:
        counts[tuple(r.prompt[:6].tolist())] = \
            counts.get(tuple(r.prompt[:6].tolist()), 0) + 1
    # one dominant shared block covering most requests
    assert max(counts.values()) >= 16 * 0.5
