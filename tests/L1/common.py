"""L1 harness: run a short training job at an opt level, dump traces.

Parity: reference tests/L1/common/main_amp.py (dumps per-iteration loss +
grad-norm per opt level) + compare.py (asserts closeness against the O0
baseline). Models are compact stand-ins (small CNN, small GPT) so traces
run in seconds on the CPU mesh; tolerances account for bf16 vs the
reference's fp16.
"""

import dataclasses
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam, FusedSGD


@dataclasses.dataclass
class Trace:
    losses: List[float]
    grad_norms: List[float]


def _global_norm(grads):
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def run_cnn_trace(opt_level, optimizer_name="sgd", iters=20, seed=0,
                  loss_scale=None):
    """Train a small CNN classifier; return per-iteration loss/grad-norm
    (reference main_amp.py trace dump)."""
    import flax.linen as nn

    class SmallCNN(nn.Module):
        dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            x = x.astype(self.dtype)
            x = nn.Conv(16, (3, 3), dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.avg_pool(x, (2, 2), (2, 2))
            x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
            x = nn.relu(x)
            x = x.reshape(x.shape[0], -1)
            return nn.Dense(10, dtype=self.dtype)(x).astype(jnp.float32)

    rng = np.random.RandomState(seed)
    images = jnp.asarray(rng.randn(16, 16, 16, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, size=(16,)))

    compute_dtype = (jnp.float32 if opt_level in ("O0",)
                     else jnp.bfloat16)
    model = SmallCNN(dtype=compute_dtype)
    params = model.init(jax.random.PRNGKey(seed), images[:2])["params"]

    if optimizer_name == "sgd":
        base_opt = FusedSGD(lr=0.05, momentum=0.9)
    else:
        base_opt = FusedAdam(lr=1e-3)
    params, opt = amp.initialize(params, base_opt, opt_level=opt_level,
                                 loss_scale=loss_scale, verbosity=0)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, images)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))

        scale = opt_state["scaler"].loss_scale
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p) * scale)(params)
        gnorm = _global_norm(grads) / scale
        new_params, new_opt_state = opt.step(grads, opt_state, params)
        return new_params, new_opt_state, loss / scale, gnorm

    losses, gnorms = [], []
    for _ in range(iters):
        params, opt_state, loss, gnorm = train_step(
            params, opt_state, images, labels)
        losses.append(float(loss))
        gnorms.append(float(gnorm))
    return Trace(losses, gnorms)


def run_gpt_trace(opt_level, iters=15, seed=0):
    """Train a toy GPT; return the trace."""
    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.models.gpt import gpt_loss_fn
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    compute_dtype = jnp.float32 if opt_level == "O0" else jnp.bfloat16
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=256, max_position_embeddings=64,
        compute_dtype=compute_dtype, use_flash_attention=False)
    model = GPTModel(cfg)
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(0, 256, size=(4, 32)))
    params = model.init(jax.random.PRNGKey(seed), tokens)
    params, opt = amp.initialize(params, FusedAdam(lr=1e-3),
                                 opt_level=opt_level, verbosity=0)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            logits = model.apply(p, tokens)
            return gpt_loss_fn(logits[:, :-1], tokens[:, 1:]).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        gnorm = _global_norm(grads)
        new_params, new_opt_state = opt.step(grads, opt_state, params)
        return new_params, new_opt_state, loss, gnorm

    losses, gnorms = [], []
    for _ in range(iters):
        params, opt_state, loss, gnorm = train_step(params, opt_state, tokens)
        losses.append(float(loss))
        gnorms.append(float(gnorm))
    return Trace(losses, gnorms)


def compare_traces(baseline: Trace, candidate: Trace, *, loss_rtol,
                   gnorm_rtol, label=""):
    """Assert trace closeness (reference tests/L1/common/compare.py
    semantics: per-iteration relative comparison vs the O0 baseline)."""
    bl = np.asarray(baseline.losses)
    cl = np.asarray(candidate.losses)
    # Denominator floored at 1% of the initial loss: once a trace is
    # near-converged (loss within bf16 epsilon of zero) the plain
    # relative error is ill-conditioned — a 4e-5 absolute difference on
    # an 8e-4 loss is precision noise, not divergence. What the test
    # pins is that the *training trajectory* matches at the scale the
    # model actually trains through.
    floor = np.maximum(1e-6, 0.01 * np.abs(bl[0]))
    rel = np.abs(bl - cl) / np.maximum(np.abs(bl), floor)
    assert rel.max() < loss_rtol, (
        f"{label}: loss trace diverged (max rel {rel.max():.4f} at iter "
        f"{int(rel.argmax())}: baseline {bl[rel.argmax()]:.5f} vs "
        f"{cl[rel.argmax()]:.5f})")
    bg = np.asarray(baseline.grad_norms)
    cg = np.asarray(candidate.grad_norms)
    relg = np.abs(bg - cg) / np.maximum(np.abs(bg), 1e-6)
    assert relg.max() < gnorm_rtol, (
        f"{label}: grad-norm trace diverged (max rel {relg.max():.4f})")
    # both must actually train
    assert cl[-1] < cl[0], f"{label}: candidate loss did not decrease"
