"""L1 cross-product: opt-level x optimizer loss-trace comparison vs O0.

Parity: reference tests/L1/cross_product/run.sh runs {O0..O3} x
{SGD, FusedSGD/Adam} through run_test.sh and compares each trace to the
O0 baseline (common/compare.py). bf16 tolerances are looser than the
reference's fp16 ones (bf16 has 8 mantissa bits); what must hold is that
every opt level *trains the same model the same way* within precision.
"""

import pytest

from tests.L1.common import compare_traces, run_cnn_trace, run_gpt_trace

# bf16 per-iteration tolerances (empirically ~1e-2 observed; headroom 3x)
LOSS_RTOL = {"O1": 0.05, "O2": 0.08, "O3": 0.10}
GNORM_RTOL = {"O1": 0.15, "O2": 0.20, "O3": 0.25}


@pytest.fixture(scope="module")
def cnn_baseline_sgd():
    return run_cnn_trace("O0", "sgd")


@pytest.fixture(scope="module")
def cnn_baseline_adam():
    return run_cnn_trace("O0", "adam")


@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
def test_cnn_sgd_opt_levels_match_O0(cnn_baseline_sgd, opt_level):
    trace = run_cnn_trace(opt_level, "sgd")
    compare_traces(cnn_baseline_sgd, trace,
                   loss_rtol=LOSS_RTOL[opt_level],
                   gnorm_rtol=GNORM_RTOL[opt_level],
                   label=f"cnn/sgd/{opt_level}")


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_cnn_adam_opt_levels_match_O0(cnn_baseline_adam, opt_level):
    trace = run_cnn_trace(opt_level, "adam")
    compare_traces(cnn_baseline_adam, trace,
                   loss_rtol=LOSS_RTOL[opt_level],
                   gnorm_rtol=GNORM_RTOL[opt_level],
                   label=f"cnn/adam/{opt_level}")


def test_cnn_static_loss_scale_matches_dynamic(cnn_baseline_sgd):
    trace = run_cnn_trace("O2", "sgd", loss_scale=128.0)
    compare_traces(cnn_baseline_sgd, trace, loss_rtol=LOSS_RTOL["O2"],
                   gnorm_rtol=GNORM_RTOL["O2"], label="cnn/sgd/O2/static128")


@pytest.fixture(scope="module")
def gpt_baseline():
    return run_gpt_trace("O0")


@pytest.mark.parametrize("opt_level", [
    "O1",
    # tier-1 budget (round 23): O1 covers the opt-level parity mechanism
    pytest.param("O2", marks=pytest.mark.slow),
])
def test_gpt_opt_levels_match_O0(gpt_baseline, opt_level):
    baseline = gpt_baseline
    trace = run_gpt_trace(opt_level)
    compare_traces(baseline, trace, loss_rtol=LOSS_RTOL[opt_level],
                   gnorm_rtol=GNORM_RTOL[opt_level],
                   label=f"gpt/{opt_level}")
