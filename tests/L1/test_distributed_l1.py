"""L1 distributed: DDP training trace must match single-device training.

Parity: reference tests/L1/cross_product_distributed/ (same cross-product
under torch.distributed.launch with 2 processes) and
tests/distributed/amp_master_params (master params bitwise identical
across ranks after DDP steps).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel

from tests.L1.common import Trace, compare_traces


def _small_mlp():
    import flax.linen as nn

    class SmallMLP(nn.Module):
        dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            x = x.astype(self.dtype)
            x = nn.Dense(32, dtype=self.dtype)(x)
            x = nn.relu(x)
            return nn.Dense(10, dtype=self.dtype)(x).astype(jnp.float32)

    return SmallMLP


def _loss(model, p, x, y):
    logits = model.apply({"params": p}, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], -1))


@pytest.mark.parametrize("opt_level", ["O0", "O2"])
def test_ddp_trace_matches_single_device(opt_level):
    iters, global_batch = 15, 16
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(global_batch, 8).astype(np.float32))
    ys = jnp.asarray(rng.randint(0, 10, size=(global_batch,)))

    dtype = jnp.float32 if opt_level == "O0" else jnp.bfloat16
    model = _small_mlp()(dtype=dtype)
    params0 = model.init(jax.random.PRNGKey(0), xs[:2])["params"]

    def make_opt():
        p, opt = amp.initialize(params0, FusedSGD(lr=0.05, momentum=0.9),
                                opt_level=opt_level, verbosity=0)
        return p, opt

    # ---- single device -----------------------------------------------
    params, opt = make_opt()
    opt_state = opt.init(params)

    @jax.jit
    def single_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(
            lambda p: _loss(model, p, x, y))(params)
        new_p, new_s = opt.step(grads, opt_state, params)
        return new_p, new_s, loss

    ref_losses = []
    for _ in range(iters):
        params, opt_state, loss = single_step(params, opt_state, xs, ys)
        ref_losses.append(float(loss))

    # ---- 4-way DDP ---------------------------------------------------
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("dp",))
    ddp = DistributedDataParallel(axis_name="dp")
    params, opt = make_opt()
    opt_state = opt.init(params)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(), P(), P("dp"), P("dp")),
                       out_specs=(P(), P(), P()), check_vma=False)
    def ddp_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(
            lambda p: _loss(model, p, x, y))(params)
        grads = ddp.sync(grads)  # bucketed psum-mean over dp
        new_p, new_s = opt.step(grads, opt_state, params)
        return new_p, new_s, jax.lax.pmean(loss, "dp")

    ddp_step = jax.jit(ddp_step)
    ddp_losses = []
    for _ in range(iters):
        params, opt_state, loss = ddp_step(params, opt_state, xs, ys)
        ddp_losses.append(float(loss))

    tol = 1e-5 if opt_level == "O0" else 0.05
    compare_traces(Trace(ref_losses, [1.0] * iters),
                   Trace(ddp_losses, [1.0] * iters),
                   loss_rtol=max(tol, 1e-5), gnorm_rtol=1.0,
                   label=f"ddp/{opt_level}")


def test_amp_master_params_identical_across_replicas():
    """After DDP steps, O2 master weights must be identical on every
    replica (reference tests/distributed/amp_master_params)."""
    rng = np.random.RandomState(1)
    xs = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    ys = jnp.asarray(rng.randint(0, 10, size=(16,)))
    model = _small_mlp()(dtype=jnp.bfloat16)
    params0 = model.init(jax.random.PRNGKey(0), xs[:2])["params"]
    params, opt = amp.initialize(params0, FusedSGD(lr=0.05),
                                 opt_level="O2", verbosity=0)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("dp",))
    ddp = DistributedDataParallel(axis_name="dp")
    opt_state = opt.init(params)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(), P(), P("dp"), P("dp")),
                       out_specs=(P(None), P(None), P(None)),
                       check_vma=False)
    def ddp_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(
            lambda p: _loss(model, p, x, y))(params)
        grads = ddp.sync(grads)
        new_p, new_s = opt.step(grads, opt_state, params)
        # return per-replica copies stacked so we can compare them
        stack = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: jax.lax.all_gather(a, "dp"), t)
        return stack(new_p), stack(new_s), loss[None]

    new_params, new_state, _ = jax.jit(ddp_step)(params, opt_state, xs, ys)
    for leaf in jax.tree_util.tree_leaves(new_params):
        per_replica = np.asarray(leaf)
        for r in range(1, per_replica.shape[0]):
            np.testing.assert_array_equal(per_replica[0], per_replica[r])
    masters = new_state["inner"]["amp_master"]  # O2 must create masters
    assert jax.tree_util.tree_leaves(masters), "no master params in state"
    for leaf in jax.tree_util.tree_leaves(masters):
        per_replica = np.asarray(leaf)
        assert per_replica.dtype == np.float32
        for r in range(1, per_replica.shape[0]):
            np.testing.assert_array_equal(per_replica[0], per_replica[r])
