"""Expert-parallel MoE GPT training walkthrough.

No reference counterpart (the reference has no MoE; this is an apex_tpu
capability beyond it — COVERAGE.md §2.3). Shows the full recipe: ep mesh
axis, SwitchMLP layers via TransformerConfig, aux-loss collection, the
split dense/expert grad-sync rule, and checkpointing.

Run (8 virtual devices on CPU, or a real slice):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/moe/train_moe_gpt.py --steps 20 --ep 2 --tp 2
"""

import argparse
import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

import jax
import jax.numpy as jnp
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--ep", type=int, default=2,
                   help="expert-parallel ways (experts sharded over 'ep')")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel ways inside each expert")
    p.add_argument("--experts", type=int, default=4)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--batch-per-replica", type=int, default=2)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--capacity-factor", type=float, default=1.5)
    p.add_argument("--save-dir", default=None,
                   help="optional checkpoint directory")
    p.add_argument("--cpu", action="store_true",
                   help="force the virtual CPU platform")
    args = p.parse_args()

    if args.cpu or len(jax.devices()) < args.ep * args.tp:
        jax.config.update("jax_platforms", "cpu")

    from apex_tpu.models.transformer_lm import TransformerConfig
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing.gpt_moe import build_gpt_moe_harness

    world = len(jax.devices())
    dp = world // (args.ep * args.tp)
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        expert_model_parallel_size_=args.ep,
        devices=jax.devices()[:dp * args.ep * args.tp])
    print(f"mesh: {dict(mesh.shape)}  "
          f"dense-grad axes: {parallel_state.get_data_parallel_axes()}")

    cfg = TransformerConfig(
        hidden_size=args.hidden, num_layers=args.layers,
        num_attention_heads=4, vocab_size=256,
        max_position_embeddings=args.seq, compute_dtype=jnp.bfloat16,
        use_flash_attention=False, num_moe_experts=args.experts,
        moe_top_k=args.top_k, moe_capacity_factor=args.capacity_factor)

    B = args.batch_per_replica * dp * args.ep
    rng = np.random.RandomState(0)
    data = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, args.seq + 1)))
    tokens, labels = data[:, :-1], data[:, 1:]

    opt = FusedAdam(lr=args.lr)
    init_state, step = build_gpt_moe_harness(cfg, mesh, opt)
    params, opt_state = init_state(jax.random.PRNGKey(0), tokens)

    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")

    if args.save_dir:
        from apex_tpu import checkpoint

        path = checkpoint.save_training_state(
            args.save_dir, args.steps, params, opt_state)
        print("saved:", path)


if __name__ == "__main__":
    main()
