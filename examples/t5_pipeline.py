"""Encoder-decoder (T5-style) pipeline-parallel training walkthrough.

Parity target: the reference runs ModelType.encoder_and_decoder models
through its pipeline schedules with dual p2p tensor shapes
(apex/transformer/pipeline_parallel/schedules/fwd_bwd_pipelining_without_interleaving.py:29-86)
and places the encoder/decoder boundary at
pipeline_model_parallel_split_rank (apex/transformer/parallel_state.py:243-331).
apex_tpu's equivalent is `forward_backward_pipelining_with_split`: one
jitted SPMD tick machine whose cross-stage payload is an
{encoder, decoder} pytree pair, with the encoder stream forwarded to
decoder ranks as cross-attention memory.

Shown here: the split mesh, per-stage params, the schedule call, and a
FusedAdam update applied rank-locally to each stage's params.

Run (4 virtual devices on CPU, or a real slice):

    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python examples/t5_pipeline.py --steps 20 --pp 4 --split 2
"""

import argparse
import functools
import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the tunneled-TPU plugin ignores the env var; the config route must
    # win before any backend init (same guard as the other examples)
    jax.config.update("jax_platforms", "cpu")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--pp", type=int, default=4,
                   help="pipeline stages (encoder + decoder ranks)")
    p.add_argument("--split", type=int, default=2,
                   help="first decoder rank; ranks < split run the encoder")
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--batch", type=int, default=2,
                   help="microbatch size")
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--model", choices=["standalone", "real"],
                   default="standalone",
                   help="'real' runs the full T5Model family (relative-"
                        "position buckets, RMS norms, tied head) as the "
                        "pipeline stages; needs --pp 2 --split 1")
    args = p.parse_args()

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.testing import shard_map
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_split,
        make_encoder_decoder_step,
    )
    from apex_tpu.transformer.testing.standalone_t5 import (
        decoder_block,
        encoder_block,
        init_stage_params,
        t5_loss,
        t5_test_config,
    )
    from jax.sharding import Mesh, PartitionSpec as P

    if len(jax.devices()) < args.pp:
        raise SystemExit(
            f"need {args.pp} devices; run with XLA_FLAGS="
            f"--xla_force_host_platform_device_count={args.pp}")
    if not (0 < args.split < args.pp):
        raise SystemExit("--split must satisfy 0 < split < pp")

    cfg = t5_test_config(hidden=args.hidden, ffn=2 * args.hidden)
    M, B = args.microbatches, args.batch
    rng = np.random.RandomState(0)
    mbs = {
        "enc_tokens": jnp.asarray(
            rng.randint(0, cfg["vocab"], (M, B, cfg["enc_seq"]))),
        "dec_tokens": jnp.asarray(
            rng.randint(0, cfg["vocab"], (M, B, cfg["dec_seq"]))),
        "dec_targets": jnp.asarray(
            rng.randint(0, cfg["vocab"], (M, B, cfg["dec_seq"]))),
    }

    mesh = Mesh(np.asarray(jax.devices()[:args.pp]), ("pp",))
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=args.pp,
        pipeline_model_parallel_split_rank_=args.split,
        devices=jax.devices()[:args.pp])

    if args.model == "real":
        # the full T5 family (models/t5.py) as the pipeline stages: the
        # encoder rank runs T5Model.encode, the decoder rank runs
        # decode_hidden with the forwarded memory, the loss applies the
        # tied head. One whole side per rank -> pp=2/split=1.
        if (args.pp, args.split) != (2, 1):
            raise SystemExit("--model real needs --pp 2 --split 1 "
                             "(one full encoder rank + one decoder rank)")
        from apex_tpu.models.t5 import T5Config, T5Model, t5_loss_fn

        tcfg = T5Config(
            vocab_size=cfg["vocab"], d_model=args.hidden, d_kv=16,
            d_ff=2 * args.hidden, num_layers=2, num_decoder_layers=2,
            num_heads=cfg["heads"], compute_dtype=jnp.float32)
        model = T5Model(tcfg)

        def enc_fn(p, h, mb, is_first):
            del h, is_first
            return model.apply({"params": p}, mb["enc_tokens"],
                               method=T5Model.encode)

        def dec_fn(p, h, memory, mb, is_split):
            del h, is_split
            return model.apply({"params": p}, mb["dec_tokens"], memory,
                               method=T5Model.decode_hidden)

        step = make_encoder_decoder_step(enc_fn, dec_fn)

        def loss_func(params, payload, mb):
            logits = model.apply({"params": params}, payload["decoder"],
                                 method=T5Model.head)
            return t5_loss_fn(logits, mb["dec_targets"])

        init_rank = lambda r: model.init(
            jax.random.PRNGKey(r), mbs["enc_tokens"][0],
            mbs["dec_tokens"][0])["params"]
    else:
        step = make_encoder_decoder_step(
            functools.partial(encoder_block, cfg=cfg),
            functools.partial(decoder_block, cfg=cfg))

        def loss_func(params, payload, mb):
            return t5_loss(params, payload["decoder"], mb)

        init_rank = lambda r: init_stage_params(rng, cfg)

    opt = FusedAdam(lr=args.lr)
    # one stage's params per pp rank, stacked for shard_map entry
    stage_params = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[init_rank(r) for r in range(args.pp)])
    opt_state = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[opt.init(jax.tree_util.tree_map(lambda a: a[r], stage_params))
          for r in range(args.pp)])

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("pp"), P("pp"), P()),
        out_specs=(P("pp"), P("pp"), P("pp")))
    def train_step(p_stage, o_stage, mbs_):
        params = jax.tree_util.tree_map(lambda a: a[0], p_stage)
        o = jax.tree_util.tree_map(lambda a: a[0], o_stage)
        losses, grads = forward_backward_pipelining_with_split(
            step, loss_func, params, mbs_, num_microbatches=M,
            encoder_tensor_shape=(cfg["enc_seq"], B, cfg["hidden"]),
            decoder_tensor_shape=(cfg["dec_seq"], B, cfg["hidden"]),
            dtype=jnp.float32, pp_size=args.pp)
        params, o = opt.step(grads, o, params)
        lift = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
        return lift(params), lift(o), losses[None]

    for i in range(args.steps):
        stage_params, opt_state, losses = train_step(
            stage_params, opt_state, mbs)
        # per-microbatch losses live on the last stage's lane
        loss = float(np.asarray(losses)[args.pp - 1].mean())
        print(f"step {i:3d}  loss {loss:.4f}")


if __name__ == "__main__":
    main()
