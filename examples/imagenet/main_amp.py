"""ImageNet training with amp + fused optimizers on TPU.

Parity: reference examples/imagenet/main_amp.py (543 LoC) — the full CLI:
``--opt-level O0..O3``, ``--loss-scale``, ``--sync_bn``, ``--batch-size``,
``--lr``, ``--epochs``, ``--deterministic``, ``--resume``, DDP, prefetching
loader with device-side normalization.

TPU design: one jitted train step; data parallelism over all local devices
via a 'dp' mesh (the reference's one-process-per-GPU + DDP); input pipeline
feeds NHWC uint8 batches and normalization runs on device (the reference's
data_prefetcher does the same on GPU, main_amp.py:256-290). Without an
ImageNet directory, synthetic data is used so the example runs anywhere.
"""

import argparse
import functools
import os
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

from apex_tpu import amp
from apex_tpu.models import ResNet50
from apex_tpu.optimizers import FusedAdam, FusedSGD

MEAN = np.array([0.485, 0.456, 0.406], np.float32) * 255
STD = np.array([0.229, 0.224, 0.225], np.float32) * 255


def parse_args():
    p = argparse.ArgumentParser(description="TPU ImageNet amp training")
    p.add_argument("data", nargs="?", default=None,
                   help="path to dataset (synthetic if omitted)")
    p.add_argument("--arch", "-a", default="resnet50")
    p.add_argument("-b", "--batch-size", type=int, default=256,
                   help="global batch size")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--resume", default="", type=str)
    p.add_argument("--opt-level", type=str, default="O1")
    p.add_argument("--loss-scale", type=str, default=None)
    p.add_argument("--keep-batchnorm-fp32", type=str, default=None)
    p.add_argument("--sync_bn", action="store_true",
                   help="cross-replica batchnorm over the dp axis")
    p.add_argument("--fused-adam", action="store_true")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--prof", action="store_true",
                   help="emit a jax profiler trace for 10 steps")
    return p.parse_args()


def synthetic_batches(global_batch, steps, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        imgs = rng.randint(0, 256, size=(global_batch, 224, 224, 3),
                           dtype=np.uint8)
        labels = rng.randint(0, 1000, size=(global_batch,), dtype=np.int32)
        yield imgs, labels


def imagefolder_batches(root, global_batch, seed=0):
    """Minimal ImageFolder loader (reference uses
    torchvision.datasets.ImageFolder, main_amp.py:205-214)."""
    try:
        from torchvision import datasets, transforms
        import torch
    except ImportError as e:
        raise SystemExit(
            f"--data requires torchvision for ImageFolder loading ({e}); "
            "omit the data argument to run on synthetic batches") from e
    tfm = transforms.Compose([
        transforms.RandomResizedCrop(224),
        transforms.RandomHorizontalFlip(),
        transforms.PILToTensor(),
    ])
    ds = datasets.ImageFolder(os.path.join(root, "train"), tfm)
    g = torch.Generator().manual_seed(seed)
    loader = torch.utils.data.DataLoader(
        ds, batch_size=global_batch, shuffle=True, drop_last=True,
        num_workers=4, generator=g)
    for imgs, labels in loader:
        # NCHW uint8 -> NHWC uint8
        yield (imgs.permute(0, 2, 3, 1).contiguous().numpy(),
               labels.numpy().astype(np.int32))


def main():
    args = parse_args()
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("dp",))
    ndev = len(devices)
    assert args.batch_size % ndev == 0

    loss_scale = args.loss_scale
    if loss_scale is not None and loss_scale != "dynamic":
        loss_scale = float(loss_scale)
    keep_bn = args.keep_batchnorm_fp32
    if keep_bn is not None:
        keep_bn = keep_bn == "True"

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     sync_bn=args.sync_bn, bn_axis_name="dp")
    seed = 0 if args.deterministic else int(time.time())
    init_imgs = jnp.zeros((2, 224, 224, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(seed), init_imgs, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    if args.fused_adam:
        optimizer = FusedAdam(lr=args.lr, weight_decay=args.weight_decay)
    else:
        optimizer = FusedSGD(lr=args.lr, momentum=args.momentum,
                             weight_decay=args.weight_decay)
    params, opt = amp.initialize(params, optimizer,
                                 opt_level=args.opt_level,
                                 keep_batchnorm_fp32=keep_bn,
                                 loss_scale=loss_scale, verbosity=1)
    opt_state = opt.init(params)

    start_epoch = 0
    if args.resume and os.path.isfile(args.resume):
        with open(args.resume, "rb") as f:
            ckpt = pickle.load(f)
        params, batch_stats, opt_state = (
            ckpt["params"], ckpt["batch_stats"], ckpt["opt_state"])
        amp.load_state_dict(ckpt["amp"])
        start_epoch = ckpt["epoch"]
        print(f"=> resumed from {args.resume} (epoch {start_epoch})")

    mean = jnp.asarray(MEAN)
    std = jnp.asarray(STD)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P(), P()),
        check_vma=False)
    def train_step(params, batch_stats, opt_state, images, labels):
        # device-side normalization (reference data_prefetcher)
        x = (images.astype(jnp.float32) - mean) / std

        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                                 axis=-1))
            return loss, updates["batch_stats"]

        scale = opt_state["scaler"].loss_scale
        (scaled_loss, new_bs), grads = jax.value_and_grad(
            lambda p: (lambda l, b: (l * scale, b))(*loss_fn(p)),
            has_aux=True)(params)
        # DDP: average grads over the dp axis (scaled grads; the scaler
        # unscale happens inside opt.step).
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "dp"), grads)
        new_bs = jax.tree_util.tree_map(
            lambda s: jax.lax.pmean(s, "dp"), new_bs)
        new_params, new_opt_state = opt.step(grads, opt_state, params)
        loss = jax.lax.pmean(scaled_loss / scale, "dp")
        return new_params, new_bs, new_opt_state, loss

    print(f"training {args.arch} on {ndev} device(s), opt_level "
          f"{args.opt_level}, global batch {args.batch_size}")
    for epoch in range(start_epoch, args.epochs):
        t0 = time.time()
        seen = 0
        if args.data:
            batches = imagefolder_batches(args.data, args.batch_size,
                                          seed=epoch)
        else:
            batches = synthetic_batches(args.batch_size,
                                        args.steps_per_epoch, seed=epoch)
        for step, (imgs, labels) in enumerate(batches):
            if args.prof and epoch == start_epoch and step == 1:
                jax.profiler.start_trace("/tmp/apex_tpu_trace")
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, jnp.asarray(imgs),
                jnp.asarray(labels))
            if args.prof and epoch == start_epoch and step == 10:
                jax.block_until_ready(loss)
                jax.profiler.stop_trace()
                print("profiler trace written to /tmp/apex_tpu_trace")
            seen += args.batch_size
            if step % args.print_freq == 0:
                jax.block_until_ready(loss)
                rate = seen / (time.time() - t0)
                print(f"epoch {epoch} step {step} loss {float(loss):.4f} "
                      f"({rate:.1f} imgs/sec)")
        jax.block_until_ready(loss)
        rate = seen / (time.time() - t0)
        print(f"epoch {epoch} done: {rate:.1f} imgs/sec")

        ckpt = {"params": params, "batch_stats": batch_stats,
                "opt_state": opt_state, "amp": amp.state_dict(),
                "epoch": epoch + 1}
        with open("checkpoint.pkl", "wb") as f:
            pickle.dump(jax.device_get(ckpt), f)


if __name__ == "__main__":
    main()
