"""Minimal DDP + amp walkthrough.

Parity: reference examples/simple/distributed/distributed_data_parallel.py
(~70 LoC): a toy model trained with DistributedDataParallel + amp across
processes. TPU version: the same walkthrough over the local device mesh.
Run: python distributed_data_parallel.py
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel

N_FEATURES = 64
N_OUT = 16


def main():
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("dp",))
    ndev = len(devices)
    rng = np.random.RandomState(0)

    params = {"w": jnp.asarray(rng.randn(N_FEATURES, N_OUT).astype(np.float32) * 0.1),
              "b": jnp.zeros((N_OUT,), jnp.float32)}
    params, opt = amp.initialize(params, FusedSGD(lr=1e-2), opt_level="O2",
                                 verbosity=0)
    opt_state = opt.init(params)
    ddp = DistributedDataParallel(axis_name="dp")

    def model(params, x):
        return x.astype(params["w"].dtype) @ params["w"] + params["b"]

    x = jnp.asarray(rng.randn(ndev * 8, N_FEATURES).astype(np.float32))
    y = jnp.asarray(rng.randn(ndev * 8, N_OUT).astype(np.float32))

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(), P(), P("dp"), P("dp")),
                       out_specs=(P(), P(), P()),
                       check_vma=False)
    def step(params, opt_state, x, y):
        def loss_fn(p):
            out = model(p, x)
            return jnp.mean((out.astype(jnp.float32) - y) ** 2)

        wrapped = ddp(loss_fn)  # grads auto-averaged over dp
        scale = opt_state["scaler"].loss_scale
        loss, grads = jax.value_and_grad(lambda p: wrapped(p) * scale)(params)
        new_params, new_opt_state = opt.step(grads, opt_state, params)
        return new_params, new_opt_state, jax.lax.pmean(loss / scale, "dp")

    for i in range(20):
        params, opt_state, loss = step(params, opt_state, x, y)
        if i % 5 == 0:
            print(f"step {i} loss {float(loss):.5f}")
    print("final loss", float(loss))


if __name__ == "__main__":
    main()
