"""Minimal amp O1 walkthrough with the universal op shim.

The reference's O1 patches the torch namespaces at ``amp.initialize`` so
*user* code gets automatic mixed-precision casts (apex/amp/amp.py:74-183).
The TPU-native equivalent is an import swap: write your model against

    from apex_tpu.amp import jnp, nn      # instead of jax.numpy / jax.nn

and after ``amp.initialize(..., opt_level="O1")`` every white-listed op
(matmul/einsum/convs) runs in bf16 on the MXU while black-listed ops
(softmax, reductions, transcendentals) run in fp32 — no decorators, no
model changes. Import the shim BEFORE jitting (casts are trace-time).

Run:  PYTHONPATH=. python examples/simple/amp_o1_shim.py
"""

import jax
import numpy as np

from apex_tpu import amp
from apex_tpu.amp import jnp, nn
from apex_tpu.optimizers import FusedSGD


def model(params, x):
    h = nn.gelu(jnp.matmul(x, params["w1"]))      # bf16 under O1
    return jnp.matmul(h, params["w2"])            # bf16 under O1


def main():
    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(16, 64) * 0.3, jnp.float32),
              "w2": jnp.asarray(rng.randn(64, 8) * 0.3, jnp.float32)}
    x = jnp.asarray(rng.randn(128, 16), jnp.float32)
    y = jnp.asarray(rng.randint(0, 8, 128))

    # O1: params stay fp32, compute ops cast via the shim, dynamic loss
    # scale (kept for API parity; near-no-op on bf16).
    params, opt = amp.initialize(params, FusedSGD(lr=0.3), opt_level="O1")
    opt_state = opt.init(params)

    def loss_fn(p, s):
        logits = model(p, x)
        assert logits.dtype == jax.numpy.bfloat16  # white list applied
        logp = nn.log_softmax(logits)              # fp32 (black list)
        loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], -1))
        return opt.scale_loss(loss, s), loss       # scale-loss flow

    @jax.jit
    def step(p, s):
        (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, s)
        new_p, new_s = opt.step(grads, s, p)       # unscale + skip-on-inf
        return new_p, new_s, loss

    for i in range(40):
        params, opt_state, loss = step(params, opt_state)
        if i % 10 == 0 or i == 39:
            print(f"step {i:3d}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
