"""End-to-end migration demo: HF checkpoint -> apex_tpu -> generate.

    python examples/generation/run_hf_model.py            # tiny random GPT-2
    python examples/generation/run_hf_model.py --model-path /path/to/gpt2
    python examples/generation/run_hf_model.py --family llama --beams 4
    python examples/generation/run_hf_model.py --family t5        # enc-dec
    python examples/generation/run_hf_model.py --family whisper   # audio
    python examples/generation/run_hf_model.py --family deepseek  # MLA

Loads (or randomly initializes, offline) a HuggingFace causal LM,
converts the weights with tools/convert_hf_*, and decodes with the
KV-cache generate()/beam_search() API.
"""

import argparse
import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

import jax
import jax.numpy as jnp
import numpy as np


_LLAMA_KW = dict(vocab_size=256, hidden_size=64, intermediate_size=176,
                 num_hidden_layers=4, num_attention_heads=4,
                 max_position_embeddings=128)

# family -> (tools converter module, transformers class, tiny-config
# factory for the offline demo)
FAMILIES = {
    "gpt2": ("convert_hf_gpt2", "GPT2LMHeadModel",
             lambda t: t.GPT2Config(vocab_size=256, n_positions=128,
                                    n_embd=64, n_layer=4, n_head=4)),
    "helium": ("convert_hf_helium", "HeliumForCausalLM",
               lambda t: t.HeliumConfig(
                   num_key_value_heads=2, head_dim=16,
                   attention_bias=False, mlp_bias=False, pad_token_id=0,
                   bos_token_id=1, eos_token_id=2, **_LLAMA_KW)),
    "llama": ("convert_hf_llama", "LlamaForCausalLM",
              lambda t: t.LlamaConfig(num_key_value_heads=2, **_LLAMA_KW)),
    "mistral": ("convert_hf_mistral", "MistralForCausalLM",
                lambda t: t.MistralConfig(num_key_value_heads=2,
                                          sliding_window=32, **_LLAMA_KW)),
    "qwen2": ("convert_hf_qwen2", "Qwen2ForCausalLM",
              lambda t: t.Qwen2Config(num_key_value_heads=2,
                                      sliding_window=None, **_LLAMA_KW)),
    "gemma": ("convert_hf_gemma", "GemmaForCausalLM",
              lambda t: t.GemmaConfig(num_key_value_heads=1, head_dim=16,
                                      **_LLAMA_KW)),
    "nemotron": ("convert_hf_nemotron", "NemotronForCausalLM",
                 lambda t: t.NemotronConfig(
                     num_key_value_heads=2, hidden_act="relu2",
                     partial_rotary_factor=0.5, **_LLAMA_KW)),
    "neox": ("convert_hf_neox", "GPTNeoXForCausalLM",
             lambda t: t.GPTNeoXConfig(rotary_pct=0.25, **_LLAMA_KW)),
    "gptj": ("convert_hf_gptj", "GPTJForCausalLM",
             lambda t: t.GPTJConfig(vocab_size=256, n_embd=64, n_layer=4,
                                    n_head=4, n_positions=128,
                                    rotary_dim=8)),
    "phi": ("convert_hf_phi", "PhiForCausalLM",
            lambda t: t.PhiConfig(num_key_value_heads=4, **_LLAMA_KW)),
    "exaone4": ("convert_hf_exaone4", "Exaone4ForCausalLM",
                lambda t: t.Exaone4Config(
                    num_key_value_heads=2, head_dim=16, sliding_window=32,
                    sliding_window_pattern=2, pad_token_id=0,
                    bos_token_id=1, eos_token_id=2, **_LLAMA_KW)),
    "falcon": ("convert_hf_falcon", "FalconForCausalLM",
               lambda t: t.FalconConfig(vocab_size=256, hidden_size=64,
                                        num_hidden_layers=4,
                                        num_attention_heads=4, alibi=False,
                                        multi_query=True, bias=False)),
    "opt": ("convert_hf_opt", "OPTForCausalLM",
            lambda t: t.OPTConfig(vocab_size=256, hidden_size=64,
                                  ffn_dim=176, num_hidden_layers=4,
                                  num_attention_heads=4,
                                  max_position_embeddings=128,
                                  word_embed_proj_dim=64)),
    "bloom": ("convert_hf_bloom", "BloomForCausalLM",
              lambda t: t.BloomConfig(vocab_size=256, hidden_size=64,
                                      n_layer=4, n_head=4)),
    "mpt": ("convert_hf_mpt", "MptForCausalLM",
            lambda t: t.MptConfig(vocab_size=96, d_model=48, n_heads=4,
                                  n_layers=2, max_seq_len=64)),
    "cohere": ("convert_hf_cohere", "CohereForCausalLM",
               lambda t: t.CohereConfig(
                   num_key_value_heads=2, logit_scale=0.0625,
                   use_qk_norm=False, pad_token_id=0, bos_token_id=1,
                   eos_token_id=2, **_LLAMA_KW)),
    "dbrx": ("convert_hf_dbrx", "DbrxForCausalLM",
             lambda t: t.DbrxConfig(
                 d_model=64, n_heads=4, n_layers=2, max_seq_len=128,
                 vocab_size=256,
                 attn_config=dict(kv_n_heads=2, clip_qkv=8.0),
                 ffn_config=dict(ffn_hidden_size=96, moe_num_experts=4,
                                 moe_top_k=2,
                                 moe_normalize_expert_weights=1.0),
                 pad_token_id=0, eos_token_id=2)),
    "deepseek": ("convert_hf_deepseek", "DeepseekV2ForCausalLM",
                 lambda t: t.DeepseekV2Config(
                     vocab_size=96, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, q_lora_rank=16,
                     kv_lora_rank=8, qk_rope_head_dim=4,
                     qk_nope_head_dim=8, v_head_dim=8,
                     n_routed_experts=None, first_k_dense_replace=2,
                     max_position_embeddings=64, attention_dropout=0.0)),
    "gptbigcode": ("convert_hf_gptbigcode", "GPTBigCodeForCausalLM",
                   lambda t: t.GPTBigCodeConfig(
                       vocab_size=96, n_embd=48, n_layer=2, n_head=4,
                       n_positions=64, multi_query=True, resid_pdrop=0.0,
                       embd_pdrop=0.0, attn_pdrop=0.0)),
    "smollm3": ("convert_hf_smollm3", "SmolLM3ForCausalLM",
                lambda t: t.SmolLM3Config(
                    num_key_value_heads=2, no_rope_layer_interval=2,
                    use_sliding_window=False, pad_token_id=0,
                    bos_token_id=1, eos_token_id=2, **_LLAMA_KW)),
    "starcoder2": ("convert_hf_starcoder2", "Starcoder2ForCausalLM",
                   lambda t: t.Starcoder2Config(
                       num_key_value_heads=2, use_bias=True,
                       sliding_window=None,
                       pad_token_id=0, bos_token_id=1, eos_token_id=2,
                       **_LLAMA_KW)),
    "stablelm": ("convert_hf_stablelm", "StableLmForCausalLM",
                 lambda t: t.StableLmConfig(
                     vocab_size=96, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, num_key_value_heads=2,
                     intermediate_size=128, partial_rotary_factor=0.25,
                     max_position_embeddings=64)),
    # audio encoder-decoder: random mel features in, KV-cache greedy out
    "whisper": ("convert_hf_whisper", "WhisperForConditionalGeneration",
                lambda t: t.WhisperConfig(
                    vocab_size=96, d_model=48, encoder_layers=2,
                    decoder_layers=2, encoder_attention_heads=4,
                    decoder_attention_heads=4, encoder_ffn_dim=96,
                    decoder_ffn_dim=96, num_mel_bins=8,
                    max_source_positions=16, max_target_positions=48,
                    pad_token_id=0, bos_token_id=1, eos_token_id=2,
                    decoder_start_token_id=1, suppress_tokens=None,
                    begin_suppress_tokens=None)),
    # encoder-decoder: decodes via t5_cached_generate (cross K/V cached
    # at prefill); single-program greedy in this example
    "t5": ("convert_hf_t5", "T5ForConditionalGeneration",
           lambda t: t.T5Config(vocab_size=96, d_model=48, d_kv=16,
                                d_ff=96, num_layers=2, num_heads=4,
                                dropout_rate=0.0,
                                decoder_start_token_id=0)),
    "mixtral": ("convert_hf_mixtral", "MixtralForCausalLM",
                lambda t: t.MixtralConfig(num_key_value_heads=2,
                                          num_local_experts=4,
                                          num_experts_per_tok=2,
                                          sliding_window=None, **_LLAMA_KW)),
    "qwen2moe": ("convert_hf_qwen2moe", "Qwen2MoeForCausalLM",
                 lambda t: t.Qwen2MoeConfig(
                     vocab_size=96, hidden_size=48, intermediate_size=64,
                     moe_intermediate_size=24,
                     shared_expert_intermediate_size=40,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, num_experts=8,
                     num_experts_per_tok=2, norm_topk_prob=False,
                     max_position_embeddings=32, attention_dropout=0.0,
                     use_sliding_window=False)),
    "gemma3": ("convert_hf_gemma3", "Gemma3ForCausalLM",
               lambda t: t.Gemma3TextConfig(
                   num_key_value_heads=2, head_dim=16, sliding_window=32,
                   sliding_window_pattern=2,
                   attn_implementation="eager", **_LLAMA_KW)),
    "glm4": ("convert_hf_glm4", "Glm4ForCausalLM",
             lambda t: t.Glm4Config(
                 num_key_value_heads=2, head_dim=16,
                 partial_rotary_factor=0.5, attention_bias=True,
                 pad_token_id=0, eos_token_id=2, **_LLAMA_KW)),
    "granite": ("convert_hf_granite", "GraniteForCausalLM",
                lambda t: t.GraniteConfig(
                    num_key_value_heads=2, embedding_multiplier=12.0,
                    attention_multiplier=0.2, residual_multiplier=0.22,
                    logits_scaling=8.0, **_LLAMA_KW)),
    "gemma2": ("convert_hf_gemma2", "Gemma2ForCausalLM",
               lambda t: t.Gemma2Config(
                   num_key_value_heads=2, head_dim=16, sliding_window=32,
                   attn_implementation="eager", **_LLAMA_KW)),
    "olmo2": ("convert_hf_olmo2", "Olmo2ForCausalLM",
              lambda t: t.Olmo2Config(num_key_value_heads=2,
                                      **_LLAMA_KW)),
    "olmo3": ("convert_hf_olmo3", "Olmo3ForCausalLM",
              lambda t: t.Olmo3Config(num_key_value_heads=2,
                                      sliding_window=32, **_LLAMA_KW)),
    "olmoe": ("convert_hf_olmoe", "OlmoeForCausalLM",
              lambda t: t.OlmoeConfig(
                  num_key_value_heads=2, num_experts=8,
                  num_experts_per_tok=2, clip_qkv=None, **_LLAMA_KW)),
    "qwen3": ("convert_hf_qwen3", "Qwen3ForCausalLM",
              lambda t: t.Qwen3Config(num_key_value_heads=2, head_dim=16,
                                      use_sliding_window=False,
                                      **_LLAMA_KW)),
    "phi3": ("convert_hf_phi3", "Phi3ForCausalLM",
             lambda t: t.Phi3Config(num_key_value_heads=2,
                                    rope_scaling=None, pad_token_id=0,
                                    bos_token_id=1, eos_token_id=2,
                                    **_LLAMA_KW)),
    "qwen3moe": ("convert_hf_qwen3moe", "Qwen3MoeForCausalLM",
                 lambda t: t.Qwen3MoeConfig(
                     num_key_value_heads=2, head_dim=16,
                     moe_intermediate_size=24, num_experts=8,
                     num_experts_per_tok=2, norm_topk_prob=True,
                     use_sliding_window=False, **_LLAMA_KW)),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=sorted(FAMILIES), default="gpt2")
    ap.add_argument("--model-path", default=None,
                    help="HF checkpoint dir; omit for a tiny random model")
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--beams", type=int, default=0,
                    help="0 = sample, N>1 = beam search")
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel ways: split the converted "
                         "checkpoint and serve it over the 'tp' mesh axis")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import transformers

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import beam_search, generate

    conv_mod, cls_name, tiny_cfg = FAMILIES[args.family]
    import importlib

    convert = getattr(importlib.import_module(f"tools.{conv_mod}"),
                      conv_mod.replace("convert_hf", "convert"))
    cls = getattr(transformers, cls_name)
    if args.model_path:
        hf = cls.from_pretrained(args.model_path)
    else:
        hf = cls(tiny_cfg(transformers))

    cfg, params = convert(hf.eval().state_dict(), hf.config)

    if args.family == "deepseek":
        from apex_tpu.models import DeepseekModel, mla_cached_generate

        if args.tp > 1 or args.beams > 1:
            raise SystemExit("the deepseek path in this example is "
                             "greedy single-program (tp oracle lives in "
                             "tests)")
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)))
        out = mla_cached_generate(DeepseekModel(cfg), params, prompt,
                                  max_new_tokens=args.max_new_tokens)
        print("token ids:\n", np.asarray(out))
        return

    if args.family == "whisper":
        from apex_tpu.models import (WhisperModel, whisper_beam_generate,
                                     whisper_cached_generate)

        if args.tp > 1:
            raise SystemExit("the whisper path in this example is "
                             "single-program")
        feats = jnp.asarray(np.random.RandomState(0).randn(
            2, cfg.num_mel_bins, 2 * cfg.max_source_positions),
            jnp.float32)
        new = min(args.max_new_tokens, cfg.max_target_positions)
        wmodel = WhisperModel(cfg)
        # token ids come from the HF config — a real checkpoint's eos /
        # decoder_start differ from the tiny offline config's
        start_id = hf.config.decoder_start_token_id
        if args.beams > 1:
            out, scores = whisper_beam_generate(
                wmodel, params, feats, new, decoder_start_token_id=start_id,
                num_beams=args.beams, eos_token_id=hf.config.eos_token_id,
                pad_token_id=hf.config.pad_token_id or 0)
            print("beam scores:", np.asarray(scores))
        else:
            out = whisper_cached_generate(wmodel, params, feats, new,
                                          decoder_start_token_id=start_id)
        print("token ids:\n", np.asarray(out))
        return

    if args.family == "t5":
        from apex_tpu.models import (T5Model, t5_beam_generate,
                                     t5_cached_generate)

        if args.tp > 1:
            raise SystemExit("the t5 path in this example is "
                             "single-program; see tests for the tp2 "
                             "logits oracle")
        enc = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)))
        tmodel = T5Model(cfg)
        if args.beams > 1:
            out, scores = t5_beam_generate(
                tmodel, params, enc, args.max_new_tokens,
                num_beams=args.beams, eos_token_id=hf.config.eos_token_id,
                pad_token_id=hf.config.pad_token_id or 0)
            print("beam scores:", np.asarray(scores))
        else:
            out = t5_cached_generate(tmodel, params, enc,
                                     max_new_tokens=args.max_new_tokens)
        print("token ids:\n", np.asarray(out))
        return

    model = GPTModel(cfg, decode=True)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)))

    if args.tp > 1:
        from apex_tpu.models import (split_params_for_tp,
                                     tensor_parallel_beam_search,
                                     tensor_parallel_generate)
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=args.tp,
            devices=jax.devices()[:args.tp])
        shards = split_params_for_tp(cfg, params, args.tp)
        if args.beams > 1:
            out, scores = tensor_parallel_beam_search(
                model, shards, prompt, max_new_tokens=args.max_new_tokens,
                num_beams=args.beams, mesh=mesh)
            print("beam scores:", np.asarray(scores))
        else:
            out = tensor_parallel_generate(
                model, shards, prompt, max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, rng=jax.random.PRNGKey(0),
                mesh=mesh)
    elif args.beams > 1:
        out, scores = beam_search(model, params, prompt,
                                  max_new_tokens=args.max_new_tokens,
                                  num_beams=args.beams)
        print("beam scores:", np.asarray(scores))
    else:
        out = generate(model, params, prompt,
                       max_new_tokens=args.max_new_tokens,
                       temperature=args.temperature,
                       rng=jax.random.PRNGKey(0))
    print("token ids:\n", np.asarray(out))


if __name__ == "__main__":
    main()
