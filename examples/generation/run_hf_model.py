"""End-to-end migration demo: HF checkpoint -> apex_tpu -> generate.

    python examples/generation/run_hf_model.py            # tiny random GPT-2
    python examples/generation/run_hf_model.py --model-path /path/to/gpt2
    python examples/generation/run_hf_model.py --family llama --beams 4

Loads (or randomly initializes, offline) a HuggingFace causal LM,
converts the weights with tools/convert_hf_*, and decodes with the
KV-cache generate()/beam_search() API.
"""

import argparse
import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "llama", "gemma"],
                    default="gpt2")
    ap.add_argument("--model-path", default=None,
                    help="HF checkpoint dir; omit for a tiny random model")
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--beams", type=int, default=0,
                    help="0 = sample, N>1 = beam search")
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel ways: split the converted "
                         "checkpoint and serve it over the 'tp' mesh axis")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import transformers

    from apex_tpu.models import GPTModel
    from apex_tpu.models.generation import beam_search, generate

    if args.family == "gpt2":
        from tools.convert_hf_gpt2 import convert_gpt2 as convert

        if args.model_path:
            hf = transformers.GPT2LMHeadModel.from_pretrained(args.model_path)
        else:
            hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
                vocab_size=256, n_positions=128, n_embd=64, n_layer=4,
                n_head=4))
    elif args.family == "gemma":
        from tools.convert_hf_gemma import convert_gemma as convert

        if args.model_path:
            hf = transformers.GemmaForCausalLM.from_pretrained(args.model_path)
        else:
            hf = transformers.GemmaForCausalLM(transformers.GemmaConfig(
                vocab_size=256, hidden_size=64, intermediate_size=176,
                num_hidden_layers=4, num_attention_heads=4,
                num_key_value_heads=1, head_dim=16,
                max_position_embeddings=128))
    else:
        from tools.convert_hf_llama import convert_llama as convert

        if args.model_path:
            hf = transformers.LlamaForCausalLM.from_pretrained(args.model_path)
        else:
            hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
                vocab_size=256, hidden_size=64, intermediate_size=176,
                num_hidden_layers=4, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=128))

    cfg, params = convert(hf.eval().state_dict(), hf.config)
    model = GPTModel(cfg, decode=True)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)))

    if args.tp > 1:
        from apex_tpu.models import (split_params_for_tp,
                                     tensor_parallel_beam_search,
                                     tensor_parallel_generate)
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=args.tp,
            devices=jax.devices()[:args.tp])
        shards = split_params_for_tp(cfg, params, args.tp)
        if args.beams > 1:
            out, scores = tensor_parallel_beam_search(
                model, shards, prompt, max_new_tokens=args.max_new_tokens,
                num_beams=args.beams, mesh=mesh)
            print("beam scores:", np.asarray(scores))
        else:
            out = tensor_parallel_generate(
                model, shards, prompt, max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, rng=jax.random.PRNGKey(0),
                mesh=mesh)
    elif args.beams > 1:
        out, scores = beam_search(model, params, prompt,
                                  max_new_tokens=args.max_new_tokens,
                                  num_beams=args.beams)
        print("beam scores:", np.asarray(scores))
    else:
        out = generate(model, params, prompt,
                       max_new_tokens=args.max_new_tokens,
                       temperature=args.temperature,
                       rng=jax.random.PRNGKey(0))
    print("token ids:\n", np.asarray(out))


if __name__ == "__main__":
    main()
