"""Tensor-parallel serving walkthrough: one checkpoint, many chips.

Flow: build (or HF-convert, tools/convert_hf_*.py) a tp=1 GPT, split its
params into per-rank shards, and decode with the KV-cache loop running
inside shard_map over the 'tp' mesh axis — sampling and beam search both
see the full vocabulary via the per-step tp all-gather, and every rank
emits identical tokens.

Run (8-way virtual CPU mesh for demonstration):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/generation/tp_serving.py
"""

import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

import jax
import numpy as np

if "--xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", ""):
    # the demo run line: go straight to the virtual CPU mesh without
    # touching an accelerator plugin (a wedged tunnel's init can block)
    jax.config.update("jax_platforms", "cpu")
else:
    try:  # prefer real accelerators; fall back to CPU
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        jax.extend.backend.clear_backends()

import jax.numpy as jnp

from apex_tpu.models import (
    GPTModel,
    TransformerConfig,
    split_params_for_tp,
    tensor_parallel_beam_search,
    tensor_parallel_generate,
)
from apex_tpu.transformer import parallel_state


def main():
    # largest tp <= 4 that divides the K/V groups (split_params_for_tp
    # validates divisibility) and fits the visible devices
    n_dev = len(jax.devices())
    tp = max(t for t in (1, 2, 4) if t <= n_dev and 4 % t == 0)
    cfg = TransformerConfig(
        hidden_size=256, num_layers=4, num_attention_heads=8,
        vocab_size=1024, max_position_embeddings=256,
        compute_dtype=jnp.bfloat16, use_flash_attention=False,
        position_embedding_type="rope", activation="swiglu",
        normalization="rmsnorm", num_query_groups=4)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 1024, (2, 16)))

    # a tp=1 checkpoint (stand-in for an HF-converted one)
    parallel_state.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPTModel(cfg, decode=True)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    parallel_state.destroy_model_parallel()

    # split once, serve sharded
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tp, devices=jax.devices()[:tp])
    shards = split_params_for_tp(cfg, params, tp)

    out = tensor_parallel_generate(
        GPTModel(cfg, decode=True), shards, prompt, max_new_tokens=32,
        mesh=mesh, rng=jax.random.PRNGKey(1), temperature=0.8, top_p=0.95)
    print(f"tp={tp} sampled: {np.asarray(out[0, 16:26])}...")

    seqs, scores = tensor_parallel_beam_search(
        GPTModel(cfg, decode=True), shards, prompt, max_new_tokens=16,
        num_beams=4, mesh=mesh, length_penalty=0.9)
    print(f"tp={tp} beam-4:  {np.asarray(seqs[0, 16:26])}...  "
          f"scores {np.asarray(scores)}")


if __name__ == "__main__":
    main()
