"""DCGAN with amp multi-loss training.

Parity: reference examples/dcgan/main_amp.py — two models (D, G), three
losses (``num_losses=3``: D-real, D-fake, G), separate FusedAdam
optimizers, amp O2 loss scaling per loss id.
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))
while _d != os.path.dirname(_d) and not os.path.isdir(os.path.join(_d, "apex_tpu")):
    _d = os.path.dirname(_d)
sys.path.insert(0, _d)  # repo root (walk up: examples may be nested)

from apex_tpu import amp
from apex_tpu.models import Discriminator, Generator
from apex_tpu.optimizers import FusedAdam


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--opt-level", default="O2")
    return p.parse_args()


def bce_with_logits(logits, targets):
    x = logits.astype(jnp.float32)
    return jnp.mean(jnp.maximum(x, 0) - x * targets +
                    jnp.log1p(jnp.exp(-jnp.abs(x))))


def main():
    args = parse_args()
    rng = np.random.RandomState(0)
    netG = Generator()
    netD = Discriminator()

    z0 = jnp.asarray(rng.randn(args.batch_size, 1, 1, args.nz).astype(np.float32))
    img0 = jnp.asarray(rng.randn(args.batch_size, 64, 64, 3).astype(np.float32))
    vG = netG.init(jax.random.PRNGKey(0), z0, train=True)
    vD = netD.init(jax.random.PRNGKey(1), img0, train=True)
    pG, bsG = vG["params"], vG.get("batch_stats", {})
    pD, bsD = vD["params"], vD.get("batch_stats", {})

    # Two models, two optimizers, three loss scalers (reference
    # main_amp.py: amp.initialize([netD, netG], [optD, optG], num_losses=3).
    (pD, pG), (optD, optG) = amp.initialize(
        [pD, pG],
        [FusedAdam(lr=args.lr, betas=(args.beta1, 0.999)),
         FusedAdam(lr=args.lr, betas=(args.beta1, 0.999))],
        opt_level=args.opt_level, num_losses=3, verbosity=0)
    sD = optD.init(pD)
    sG = optG.init(pG)

    @jax.jit
    def train_step(pD, bsD, sD, pG, bsG, sG, real, z):
        # ---- D step: real + fake losses (loss ids 0, 1)
        def d_loss(pd):
            out_real, new_bsD = netD.apply(
                {"params": pd, "batch_stats": bsD}, real, train=True,
                mutable=["batch_stats"])
            fake, new_bsG = netG.apply(
                {"params": pG, "batch_stats": bsG}, z, train=True,
                mutable=["batch_stats"])
            out_fake, new_bsD2 = netD.apply(
                {"params": pd, "batch_stats": new_bsD["batch_stats"]},
                jax.lax.stop_gradient(fake), train=True,
                mutable=["batch_stats"])
            errD_real = bce_with_logits(out_real, 1.0)
            errD_fake = bce_with_logits(out_fake, 0.0)
            return errD_real + errD_fake, (new_bsD2["batch_stats"],
                                           new_bsG["batch_stats"], fake)

        scaleD = sD["scaler"].loss_scale
        (lossD, (bsD2, bsG2, fake)), gD = jax.value_and_grad(
            lambda p: (lambda l, a: (l * scaleD, a))(*d_loss(p)),
            has_aux=True)(pD)
        pD2, sD2 = optD.step(gD, sD, pD)

        # ---- G step (loss id 2)
        def g_loss(pg):
            fake, new_bsG = netG.apply(
                {"params": pg, "batch_stats": bsG2}, z, train=True,
                mutable=["batch_stats"])
            out, _ = netD.apply({"params": pD2, "batch_stats": bsD2}, fake,
                                train=True, mutable=["batch_stats"])
            return bce_with_logits(out, 1.0), new_bsG["batch_stats"]

        scaleG = sG["scaler"].loss_scale
        (lossG, bsG3), gG = jax.value_and_grad(
            lambda p: (lambda l, a: (l * scaleG, a))(*g_loss(p)),
            has_aux=True)(pG)
        pG2, sG2 = optG.step(gG, sG, pG)
        return (pD2, bsD2, sD2, pG2, bsG3, sG2,
                lossD / scaleD, lossG / scaleG)

    t0 = time.time()
    for step in range(args.steps):
        real = jnp.asarray(
            rng.randn(args.batch_size, 64, 64, 3).astype(np.float32))
        z = jnp.asarray(
            rng.randn(args.batch_size, 1, 1, args.nz).astype(np.float32))
        pD, bsD, sD, pG, bsG, sG, lD, lG = train_step(
            pD, bsD, sD, pG, bsG, sG, real, z)
        if step % 10 == 0:
            print(f"step {step} loss_D {float(lD):.4f} loss_G {float(lG):.4f}")
    jax.block_until_ready(lG)
    print(f"{args.steps} steps in {time.time()-t0:.1f}s")


if __name__ == "__main__":
    main()
