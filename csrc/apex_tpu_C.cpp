/* apex_tpu_C — native runtime helpers for the TPU framework.
 *
 * TPU-native counterpart of the reference's host-side C++ layer
 * (csrc/flatten_unflatten.cpp: apex_C.flatten/unflatten used by the DDP
 * bucketing engine). The compute path is JAX/XLA/Pallas; this module owns
 * the host-side runtime work that should not pay Python-loop overhead:
 *
 *   flatten(buffers)            - coalesce N same-dtype host arrays into one
 *                                 contiguous 1-D buffer (parallel memcpy,
 *                                 GIL released)
 *   unflatten_into(flat, outs)  - scatter a flat buffer back into N arrays
 *   assign_buckets(sizes, cap)  - greedy in-order DDP gradient bucketing
 *                                 (reference apex/parallel/distributed.py
 *                                 bucket construction, message_size cap)
 *   pack_batch(samples, out)    - multi-threaded gather of B sample arrays
 *                                 into a preallocated [B, ...] batch buffer
 *                                 (host side of the prefetching data loader;
 *                                 reference examples/imagenet data_prefetcher)
 *
 * Implemented against the raw CPython C API + buffer protocol (no pybind11,
 * no numpy C API dependency) so it builds with nothing but a C++ compiler.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

/* Acquire C-contiguous buffers for every element of a sequence. Returns
   false (with a Python error set) on failure; releases everything it
   acquired. */
bool acquire_all(PyObject *seq, int flags, std::vector<Py_buffer> *out) {
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  out->resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(item, &(*out)[i], flags) != 0) {
      for (Py_ssize_t j = 0; j < i; ++j) PyBuffer_Release(&(*out)[j]);
      out->clear();
      return false;
    }
  }
  return true;
}

void release_all(std::vector<Py_buffer> *views) {
  for (auto &v : *views) PyBuffer_Release(&v);
  views->clear();
}

/* Below this many total bytes, thread create+join overhead exceeds the
   memcpy cost; copy serially. */
constexpr Py_ssize_t kParallelThresholdBytes = 1 << 20;

/* Run fn(i) for i in [0, n) on up to `threads` std::threads; serial when
   total_bytes is under the threshold. */
void parallel_for(size_t n, unsigned threads, Py_ssize_t total_bytes,
                  const std::function<void(size_t)> &fn) {
  if (n == 0) return;
  unsigned hw = std::thread::hardware_concurrency();
  unsigned t = std::min<unsigned>(threads ? threads : 1,
                                  std::min<size_t>(hw ? hw : 1, n));
  if (t <= 1 || total_bytes < kParallelThresholdBytes) {
    for (size_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(t);
  for (unsigned w = 0; w < t; ++w) {
    pool.emplace_back([&, w]() {
      for (size_t i = w; i < n; i += t) fn(i);
    });
  }
  for (auto &th : pool) th.join();
}

/* flatten(list_of_arrays, out) -> total_bytes
   Copies each source buffer, in order, into the contiguous writable
   buffer `out`. All GIL-free. */
PyObject *flatten(PyObject *, PyObject *args) {
  PyObject *list_obj, *out_obj;
  if (!PyArg_ParseTuple(args, "OO", &list_obj, &out_obj)) return nullptr;

  PyObject *seq = PySequence_Fast(list_obj, "flatten: expected a sequence");
  if (!seq) return nullptr;
  std::vector<Py_buffer> srcs;
  if (!acquire_all(seq, PyBUF_C_CONTIGUOUS, &srcs)) {
    Py_DECREF(seq);
    return nullptr;
  }
  Py_buffer out;
  if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) != 0) {
    release_all(&srcs);
    Py_DECREF(seq);
    return nullptr;
  }

  Py_ssize_t total = 0;
  for (auto &s : srcs) total += s.len;
  if (total > out.len) {
    PyBuffer_Release(&out);
    release_all(&srcs);
    Py_DECREF(seq);
    PyErr_Format(PyExc_ValueError,
                 "flatten: output buffer too small (%zd < %zd bytes)",
                 out.len, total);
    return nullptr;
  }

  std::vector<Py_ssize_t> offsets(srcs.size());
  Py_ssize_t off = 0;
  for (size_t i = 0; i < srcs.size(); ++i) {
    offsets[i] = off;
    off += srcs[i].len;
  }

  char *dst = static_cast<char *>(out.buf);
  Py_BEGIN_ALLOW_THREADS
  parallel_for(srcs.size(), 8, total, [&](size_t i) {
    std::memcpy(dst + offsets[i], srcs[i].buf,
                static_cast<size_t>(srcs[i].len));
  });
  Py_END_ALLOW_THREADS

  PyBuffer_Release(&out);
  release_all(&srcs);
  Py_DECREF(seq);
  return PyLong_FromSsize_t(total);
}

/* unflatten_into(flat, list_of_out_arrays) -> total_bytes */
PyObject *unflatten_into(PyObject *, PyObject *args) {
  PyObject *flat_obj, *list_obj;
  if (!PyArg_ParseTuple(args, "OO", &flat_obj, &list_obj)) return nullptr;

  PyObject *seq = PySequence_Fast(list_obj, "unflatten_into: expected a sequence");
  if (!seq) return nullptr;
  std::vector<Py_buffer> dsts;
  if (!acquire_all(seq, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS, &dsts)) {
    Py_DECREF(seq);
    return nullptr;
  }
  Py_buffer flat;
  if (PyObject_GetBuffer(flat_obj, &flat, PyBUF_C_CONTIGUOUS) != 0) {
    release_all(&dsts);
    Py_DECREF(seq);
    return nullptr;
  }

  Py_ssize_t total = 0;
  for (auto &d : dsts) total += d.len;
  if (total > flat.len) {
    PyBuffer_Release(&flat);
    release_all(&dsts);
    Py_DECREF(seq);
    PyErr_Format(PyExc_ValueError,
                 "unflatten_into: flat buffer too small (%zd < %zd bytes)",
                 flat.len, total);
    return nullptr;
  }

  std::vector<Py_ssize_t> offsets(dsts.size());
  Py_ssize_t off = 0;
  for (size_t i = 0; i < dsts.size(); ++i) {
    offsets[i] = off;
    off += dsts[i].len;
  }

  const char *src = static_cast<const char *>(flat.buf);
  Py_BEGIN_ALLOW_THREADS
  parallel_for(dsts.size(), 8, total, [&](size_t i) {
    std::memcpy(dsts[i].buf, src + offsets[i],
                static_cast<size_t>(dsts[i].len));
  });
  Py_END_ALLOW_THREADS

  PyBuffer_Release(&flat);
  release_all(&dsts);
  Py_DECREF(seq);
  return PyLong_FromSsize_t(total);
}

/* assign_buckets(sizes, cap) -> list[int]
   Greedy in-order bucketing: consecutive tensors share a bucket until the
   byte cap is exceeded (a new tensor larger than cap gets its own bucket).
   Mirrors the reference DDP's message_size bucketing semantics. */
PyObject *assign_buckets(PyObject *, PyObject *args) {
  PyObject *sizes_obj;
  long long cap;
  if (!PyArg_ParseTuple(args, "OL", &sizes_obj, &cap)) return nullptr;
  if (cap <= 0) {
    PyErr_SetString(PyExc_ValueError, "assign_buckets: cap must be positive");
    return nullptr;
  }
  PyObject *seq = PySequence_Fast(sizes_obj, "assign_buckets: expected a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

  PyObject *result = PyList_New(n);
  if (!result) {
    Py_DECREF(seq);
    return nullptr;
  }
  long long acc = 0;
  long long bucket = 0;
  bool empty = true;
  for (Py_ssize_t i = 0; i < n; ++i) {
    long long sz = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(seq, i));
    if (sz == -1 && PyErr_Occurred()) {
      Py_DECREF(result);
      Py_DECREF(seq);
      return nullptr;
    }
    if (!empty && acc + sz > cap) {
      bucket += 1;
      acc = 0;
      empty = true;
    }
    acc += sz;
    empty = false;
    PyList_SET_ITEM(result, i, PyLong_FromLongLong(bucket));
  }
  Py_DECREF(seq);
  return result;
}

/* pack_batch(samples, out) -> batch_size
   samples: sequence of equally-sized C-contiguous arrays; out: writable
   buffer of exactly batch*sample_bytes. Parallel gather into the batch
   dimension. */
PyObject *pack_batch(PyObject *, PyObject *args) {
  PyObject *list_obj, *out_obj;
  if (!PyArg_ParseTuple(args, "OO", &list_obj, &out_obj)) return nullptr;

  PyObject *seq = PySequence_Fast(list_obj, "pack_batch: expected a sequence");
  if (!seq) return nullptr;
  std::vector<Py_buffer> srcs;
  if (!acquire_all(seq, PyBUF_C_CONTIGUOUS, &srcs)) {
    Py_DECREF(seq);
    return nullptr;
  }
  if (srcs.empty()) {
    release_all(&srcs);
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "pack_batch: empty sample list");
    return nullptr;
  }
  Py_ssize_t item = srcs[0].len;
  for (auto &s : srcs) {
    if (s.len != item) {
      release_all(&srcs);
      Py_DECREF(seq);
      PyErr_SetString(PyExc_ValueError,
                      "pack_batch: samples must be equally sized");
      return nullptr;
    }
  }
  Py_buffer out;
  if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) != 0) {
    release_all(&srcs);
    Py_DECREF(seq);
    return nullptr;
  }
  if (out.len != item * static_cast<Py_ssize_t>(srcs.size())) {
    PyBuffer_Release(&out);
    Py_ssize_t nsrc = static_cast<Py_ssize_t>(srcs.size());
    release_all(&srcs);
    Py_DECREF(seq);
    PyErr_Format(PyExc_ValueError,
                 "pack_batch: out must be batch*sample bytes (%zd != %zd*%zd)",
                 out.len, nsrc, item);
    return nullptr;
  }

  char *dst = static_cast<char *>(out.buf);
  Py_BEGIN_ALLOW_THREADS
  parallel_for(srcs.size(), 8, out.len, [&](size_t i) {
    std::memcpy(dst + static_cast<Py_ssize_t>(i) * item, srcs[i].buf,
                static_cast<size_t>(item));
  });
  Py_END_ALLOW_THREADS

  PyBuffer_Release(&out);
  Py_ssize_t nsrc = static_cast<Py_ssize_t>(srcs.size());
  release_all(&srcs);
  Py_DECREF(seq);
  return PyLong_FromSsize_t(nsrc);
}

PyMethodDef methods[] = {
    {"flatten", flatten, METH_VARARGS,
     "flatten(arrays, out) -> bytes copied: coalesce arrays into out."},
    {"unflatten_into", unflatten_into, METH_VARARGS,
     "unflatten_into(flat, arrays) -> bytes copied: scatter flat into arrays."},
    {"assign_buckets", assign_buckets, METH_VARARGS,
     "assign_buckets(sizes, cap) -> bucket index per tensor (greedy, in order)."},
    {"pack_batch", pack_batch, METH_VARARGS,
     "pack_batch(samples, out) -> batch size: parallel gather into out."},
    {nullptr, nullptr, 0, nullptr}};

struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "apex_tpu_C",
    "Native host-side runtime helpers for apex_tpu.", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit_apex_tpu_C(void) { return PyModule_Create(&moduledef); }
