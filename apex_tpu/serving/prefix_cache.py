"""Cross-request prefix cache: shared system prompts prefill once.

The realistic millions-of-users trace is prefix-heavy — most prompts
open with one of a handful of system prompts — so the engine keeps a
**host-side** store of previously prefilled prompts keyed by prompt
prefix hash. On admission the engine looks up the longest cached
prefix of the new prompt; on a hit the slot's KV rows are *seeded*
from the cached entry (the rows ride into the AOT prefill executable
as an argument) and the prefill runs only the *suffix* bucket at
offset positions, so TTFT drops roughly with the shared fraction.

Two properties make this safe without any new executables:

- **rollback generality**: a cached entry holds one slot's FULL row
  buffers with every prefilled position resident; reusing a *shorter*
  prefix of the same entry is just a smaller ``cache_index`` at seed
  time (positions past the cut stay resident but masked — the same
  trick speculative rejection uses), so one entry serves every prompt
  sharing any prefix of its tokens;
- **raw-value exactness**: entries are host numpy copies of the RAW
  (model-layout, full-precision) rows the prefill computed — never
  the quantized store form. A hit's suffix forward therefore attends
  over exactly the prefix K/V a cold full prefill would have
  computed, and re-quantizing the raw prefix inside the seeded
  prefill reproduces the cold store's int8 blocks bit-for-bit (same
  values through the same deterministic grid). Seeding dequantized
  int8 instead perturbs every suffix K/V through the lossy prefix —
  enough to flip a near-tie argmax many tokens later — which is why
  the entries deliberately pay full-precision host bytes.

Everything here is plain numpy + dict bookkeeping: nothing traces,
nothing compiles, so the engine's flat-compile invariant is untouched.
The store is **fleet-scoped**: `ServeFleet` builds one shared store
and every replica adopts it (``ServeEngine.adopt_prefix_store``), so
a system prompt prefilled once by replica 0 hits on replica 3, a dead
replica's prefix work survives it, and a migrated continuation hits
its own carried prefix on the survivor. Because entries are CANONICAL
(cross-rank, full-precision) rows, engines of different tensor-
parallel sizes share the same store — each re-slices at seed time
through its prefill in_specs. Per-caller attribution goes through the
``scope=`` keyword on :meth:`lookup` / :meth:`insert`: the store
keeps per-scope lookup/hit/hit-token/insertion counters next to the
globals (``stats()["by_scope"]``), which is what keeps each replica's
hit-rate column truthful when the store itself is shared. A
standalone engine passes its own name and behaves exactly as the old
per-engine store did. Memory is bounded by ``max_entries`` x
bytes-per-entry (one slot row, plus the draft row when speculative
decode is on) with LRU eviction; docs/serving.md has the accounting
worked example.
"""

import hashlib

import jax
import numpy as np


def _tok_bytes(tokens):
    return np.ascontiguousarray(np.asarray(tokens, np.int32)).tobytes()


def _common_prefix_len(a, b):
    n = min(a.shape[0], b.shape[0])
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if neq.size else n


def _tree_bytes(tree):
    return int(sum(np.asarray(leaf).nbytes
                   for leaf in jax.tree_util.tree_leaves(tree)))


class PrefixEntry:
    """One cached prompt: its tokens plus host copies of the RAW
    (model-layout, full-precision) slot row buffers the prefill
    produced — and the draft-model row when the engine decodes
    speculatively."""

    __slots__ = ("tokens", "rows", "draft_rows", "hits", "bytes")

    def __init__(self, tokens, rows, draft_rows=None):
        self.tokens = np.asarray(tokens, np.int32)
        self.rows = rows
        self.draft_rows = draft_rows
        self.hits = 0
        self.bytes = _tree_bytes(rows) + (
            _tree_bytes(draft_rows) if draft_rows is not None else 0)


class PrefixStore:
    """Bounded LRU store of prefilled prompts keyed by prefix hash.

    ``min_len`` is both the keying width (entries index under the hash
    of their first ``min_len`` tokens, so lookup only scans candidates
    that share at least that much) and the floor below which hits are
    not worth seeding. Lookup returns the longest common prefix with
    any candidate, capped at ``len(prompt) - 1`` — the suffix prefill
    needs at least one real token to sample the first output from.
    """

    def __init__(self, *, max_entries=8, min_len=4):
        if max_entries < 1:
            raise ValueError(f"max_entries ({max_entries}) must be >= 1")
        if min_len < 1:
            raise ValueError(f"min_len ({min_len}) must be >= 1")
        self.max_entries = int(max_entries)
        self.min_len = int(min_len)
        self._order = []             # LRU order: index 0 = oldest
        self._index = {}             # prefix-hash key -> [entries]
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.insertions = 0
        self.evictions = 0
        self._scopes = {}            # scope name -> per-scope counters

    def _scope(self, name):
        return self._scopes.setdefault(str(name), {
            "lookups": 0, "hits": 0, "hit_tokens": 0, "insertions": 0})

    def _key(self, tokens):
        return hashlib.sha1(
            _tok_bytes(tokens[:self.min_len])).hexdigest()

    def __len__(self):
        return len(self._order)

    def total_bytes(self):
        return sum(e.bytes for e in self._order)

    def _touch(self, entry):
        self._order.remove(entry)
        self._order.append(entry)

    def _drop(self, entry):
        self._order.remove(entry)
        bucket = self._index[self._key(entry.tokens)]
        bucket.remove(entry)
        if not bucket:
            del self._index[self._key(entry.tokens)]

    def lookup(self, prompt, *, scope=None):
        """Longest usable cached prefix of ``prompt``: returns
        ``(cut, entry)`` with ``cut`` the number of prefix tokens the
        entry covers (``0, None`` on a miss). ``cut`` never exceeds
        ``len(prompt) - 1`` and never undershoots ``min_len``.
        ``scope`` attributes the lookup (and any hit) to that caller's
        per-scope counters on top of the store-wide ones."""
        prompt = np.asarray(prompt, np.int32)
        self.lookups += 1
        sc = self._scope(scope) if scope is not None else None
        if sc is not None:
            sc["lookups"] += 1
        if prompt.shape[0] <= self.min_len:
            return 0, None
        best_cut, best = 0, None
        for entry in self._index.get(self._key(prompt), ()):
            cut = min(_common_prefix_len(entry.tokens, prompt),
                      prompt.shape[0] - 1)
            if cut >= self.min_len and cut > best_cut:
                best_cut, best = cut, entry
        if best is None:
            return 0, None
        self._touch(best)
        best.hits += 1
        self.hits += 1
        self.hit_tokens += best_cut
        if sc is not None:
            sc["hits"] += 1
            sc["hit_tokens"] += best_cut
        return best_cut, best

    def covers(self, prompt):
        """True when some entry already shares ``prompt`` entirely —
        inserting it again would add bytes but no new reusable
        prefix."""
        prompt = np.asarray(prompt, np.int32)
        return any(
            _common_prefix_len(e.tokens, prompt) >= prompt.shape[0]
            for e in self._index.get(self._key(prompt), ()))

    def insert(self, prompt, rows, draft_rows=None, *, scope=None):
        """Cache one prefilled prompt (host numpy copies of the raw
        model-layout rows). Refuses prompts shorter than ``min_len`` + 1 (nothing
        to key on plus a suffix) and exact re-covers; an entry whose
        prompt is a strict prefix of the new one is replaced (the
        longer entry serves every shorter cut); evicts LRU past
        ``max_entries``. Returns the entry or None."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.shape[0] <= self.min_len or self.covers(prompt):
            return None
        key = self._key(prompt)
        for old in list(self._index.get(key, ())):
            if (_common_prefix_len(old.tokens, prompt)
                    >= old.tokens.shape[0]):
                self._drop(old)
        entry = PrefixEntry(prompt, rows, draft_rows)
        self._order.append(entry)
        self._index.setdefault(key, []).append(entry)
        self.insertions += 1
        if scope is not None:
            self._scope(scope)["insertions"] += 1
        while len(self._order) > self.max_entries:
            self._drop(self._order[0])
            self.evictions += 1
        return entry

    def stats(self):
        return {
            "entries": len(self._order),
            "bytes": self.total_bytes(),
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": (self.hits / self.lookups) if self.lookups
            else 0.0,
            "hit_tokens": self.hit_tokens,
            "insertions": self.insertions,
            "evictions": self.evictions,
            "by_scope": {
                name: dict(c) for name, c in sorted(self._scopes.items())
            },
        }

    def scope_stats(self, scope):
        """One scope's counters (zeros if the scope never called in) —
        what a fleet replica reads back to report its OWN hit rate
        against the shared store."""
        c = self._scopes.get(str(scope))
        return dict(c) if c else {
            "lookups": 0, "hits": 0, "hit_tokens": 0, "insertions": 0}
