"""apex_tpu.serving — AOT-compiled continuous-batching decode.

The forward-only production path (ROADMAP open item 3): a
:class:`~apex_tpu.serving.engine.ServeEngine` ahead-of-time compiles
one prefill and one decode executable per (batch-bucket, seq-bucket)
pair over a preallocated, slotted, optionally int8-quantized KV cache
(:mod:`~apex_tpu.serving.kv_cache`), and a host-side
:class:`~apex_tpu.serving.scheduler.Scheduler` continuously batches
concurrent requests through it — admission into free slots, eviction
on finish, per-request TTFT / per-token latency into the telemetry
registry (``serve/*``). Compile count equals the bucket-ladder size
and stays flat under any traffic shape (``assert_no_recompiles`` is a
hard invariant of the steady state).

Fault tolerance (:mod:`~apex_tpu.serving.robust`): a bounded pending
queue with reject-newest / shed-oldest load shedding, per-request TTFT
and total-latency deadlines, per-slot NaN quarantine (a poisoned
sequence is evicted with its KV rows reset in-graph while healthy
slots keep decoding), capped-backoff decode retries that fail only the
implicated requests, and PreemptionGuard-driven graceful drain — all
host-side policy, so every failure path holds
``assert_no_recompiles``.

Latency multipliers: ``ServeConfig(draft_model=..., draft_params=...)``
turns every decode dispatch into one speculative draft-k -> verify ->
rollback round inside the SAME bucket ladder (per-slot mixed
acceptance, greedy token-identical to the plain engine), and
``ServeConfig(prefix_cache=True)`` keeps a host-side
:class:`~apex_tpu.serving.prefix_cache.PrefixStore` — FLEET-scoped:
one instance is shared across every replica (``adopt_prefix_store``)
with per-scope hit accounting, so a system prompt prefilled by one
replica hits on all of them — and prompts sharing a cached prefix
seed their KV rows from the stored copy and prefill only the suffix
bucket. Both leave the AOT compile count exactly at the ladder size.

Fleet (:mod:`~apex_tpu.serving.fleet`): a host-side router over N
engines on distinct mesh slices — load-aware dispatch, per-tier SLOs
(``Request.tier`` -> tier-default deadlines), a replica health state
machine (healthy -> degraded -> quarantined -> respawning) with
drain + request migration, and elastic scale-up/down driven by
sustained pending depth. Engines span a ``(data, model)`` slice when
``FleetConfig(model_parallel=m)`` is set — TP-sharded KV cache and
in-executable psums on the ``"tp"`` axis, same ladder invariants.
Migration carries KV *state*, not just tokens:
:meth:`~apex_tpu.serving.engine.ServeEngine.extract_kv_state` hands
the survivor a crc32-checksummed host payload
(:func:`~apex_tpu.serving.engine.kv_payload_crc`) that seeds the
shared prefix store, so a migrated request re-prefills a ONE-token
suffix — constant cost in context length. A failed checksum or
layout mismatch falls back loudly (``fleet/kv_fallback_reprefills``
+ ``kv_fallback`` event) to token re-prefill; greedy continuations
stay token-identical either way.

Quickstart (docs/serving.md has the full tour)::

    from apex_tpu.serving import (RobustConfig, ServeConfig,
                                  ServeEngine, synthetic_trace)
    engine = ServeEngine(model, params, ServeConfig(
        batch_buckets=(2, 4, 8), prefill_buckets=(16, 32),
        num_slots=8, cache_mode="int8"))
    completed, stats = engine.serve(
        synthetic_trace(32, seed=0),
        robust=RobustConfig(max_pending=64, ttft_deadline_s=30.0))
"""

from apex_tpu.serving.engine import (  # noqa: F401
    ServeConfig,
    ServeEngine,
    kv_payload_crc,
)
from apex_tpu.serving.fleet import (  # noqa: F401
    DEFAULT_TIERS,
    FleetConfig,
    Replica,
    ServeFleet,
    TierConfig,
    TIERS,
    diurnal_trace,
)
from apex_tpu.serving.kv_cache import (  # noqa: F401
    KVCacheSpec,
    row_template,
    store_lengths,
    zero_row,
)
from apex_tpu.serving.prefix_cache import (  # noqa: F401
    PrefixEntry,
    PrefixStore,
)
from apex_tpu.serving.robust import (  # noqa: F401
    DecodeFailedError,
    DrainReport,
    RejectedRequest,
    RobustConfig,
    ServeHealth,
)
from apex_tpu.serving.scheduler import (  # noqa: F401
    CompletedRequest,
    Request,
    Scheduler,
    synthetic_trace,
)
