"""Serving-path fault tolerance: admission policy, deadlines, retries.

PR 6 made the serving loop *fast* (one AOT executable per bucket, flat
compile count under any traffic); this module makes it *survivable*.
The production failure modes all land here as host-side policy —
nothing in this file ever traces or compiles, so every knob composes
with ``assert_no_recompiles`` by construction:

- **Admission control & load shedding** (:class:`RobustConfig`
  ``max_pending`` / ``admission_policy``): the pending queue is
  bounded; past the bound either the newcomer is rejected
  (``reject_newest``) or the oldest queued request is shed to make
  room (``shed_oldest`` — the newest request is the one the user is
  still waiting at). Every shed lands a ``serve/rejected`` counter
  tick and a ``serve`` JSONL event with the reason.
- **Per-request deadlines** (``ttft_deadline_s`` /
  ``total_deadline_s``, overridable per :class:`~apex_tpu.serving.
  scheduler.Request`): checked each scheduler tick; an expired request
  is evicted with the ``deadline_exceeded`` terminal status instead of
  occupying a slot (or a queue position) forever.
- **Per-slot NaN quarantine**: the engine's decode step derives an
  in-graph per-slot finite flag from the decode logits (vmapped with
  the step itself — no executable beyond the ladder) and resets a
  poisoned slot's KV rows to zero in the same dispatch; the scheduler
  evicts the poisoned sequence with status ``poisoned`` while healthy
  slots keep decoding. The *whole-batch* guard — every slot non-finite
  at once, which smells like poisoned weights, not one poisoned
  request — escalates to :class:`~apex_tpu.resilience.NonFiniteError`.
- **Decode retry with capped exponential backoff**
  (:func:`retry_backoff_s`, :func:`is_retryable_decode_error`): a
  transient dispatch failure (``UNAVAILABLE`` / ``RESOURCE_EXHAUSTED``
  / an armed :func:`~apex_tpu.resilience.faults.inject_decode_failure`)
  is retried up to ``decode_retries`` times before
  :class:`DecodeFailedError` fails ONLY the implicated requests.
- **Graceful drain**: a :class:`~apex_tpu.resilience.preemption.
  PreemptionGuard` (or an explicit ``Scheduler.drain()``) stops
  admissions, lets in-flight work finish up to ``drain_deadline_s``,
  and emits a drain report — see :class:`DrainReport`.

Terminal statuses (``CompletedRequest.finish_reason``): ``length`` and
``eos`` are the *goodput* statuses (:data:`OK_STATUSES`); everything
else — ``deadline_exceeded``, ``poisoned``, ``failed``, ``drained``,
``max_steps`` — is a non-silent failure with its own counter and JSONL
event. docs/serving.md has the symptom -> status -> telemetry ->
operator-action triage table.
"""

import dataclasses
from typing import Optional

ADMISSION_POLICIES = ("reject_newest", "shed_oldest")

# finish_reason values that count toward goodput; every other terminal
# status is a failure mode with its own serve/* counter
OK_STATUSES = ("length", "eos")
FAILURE_STATUSES = ("deadline_exceeded", "poisoned", "failed",
                    "drained", "max_steps")

# rejection reasons recorded on serve/rejected events (requests that
# never reached a slot; distinct from the terminal statuses above)
REJECT_REASONS = ("queue_full", "shed", "prompt_too_long",
                  "budget_too_long", "duplicate_rid", "draining")


class DecodeFailedError(RuntimeError):
    """A decode dispatch kept failing past the retry budget. Carries
    ``attempts`` (total tries) and ``last_error``; the scheduler
    catches it and fails only the implicated requests."""

    def __init__(self, msg, *, attempts=0, last_error=None):
        super().__init__(msg)
        self.attempts = int(attempts)
        self.last_error = last_error


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """Serving fault-tolerance knobs — all host-side policy.

    ``None`` disables a deadline; ``max_pending=None`` leaves the
    queue unbounded (the PR-6 behavior). Defaults are deliberately
    permissive: an unconfigured scheduler behaves exactly like before,
    except that failures now carry terminal statuses instead of
    raising out of ``run``.
    """

    max_pending: Optional[int] = None
    admission_policy: str = "reject_newest"
    ttft_deadline_s: Optional[float] = None
    total_deadline_s: Optional[float] = None
    decode_retries: int = 2
    retry_backoff_s: float = 0.05
    retry_backoff_cap_s: float = 1.0
    drain_deadline_s: float = 30.0
    quarantine: bool = True
    health_every: int = 0          # ticks between health events; 0 = end only

    def __post_init__(self):
        if self.admission_policy not in ADMISSION_POLICIES:
            raise ValueError(
                f"admission_policy {self.admission_policy!r} not in "
                f"{ADMISSION_POLICIES}")
        if self.max_pending is not None and self.max_pending < 0:
            raise ValueError(
                f"max_pending ({self.max_pending}) must be >= 0 or None")
        if self.decode_retries < 0:
            raise ValueError(
                f"decode_retries ({self.decode_retries}) must be >= 0")
        for name in ("ttft_deadline_s", "total_deadline_s"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"{name} ({v}) must be > 0 or None")
        if self.retry_backoff_s < 0 or self.retry_backoff_cap_s < 0:
            raise ValueError("retry backoff must be >= 0")
        if self.drain_deadline_s < 0:
            raise ValueError(
                f"drain_deadline_s ({self.drain_deadline_s}) must be >= 0")


def retry_backoff_s(attempt, base_s, cap_s):
    """Capped exponential backoff before retry ``attempt`` (0-based):
    ``min(base * 2**attempt, cap)``. The cap keeps a retry burst from
    blowing a request's total-latency deadline on its own."""
    return min(float(base_s) * (2.0 ** int(attempt)), float(cap_s))


# markers in a runtime error message that make a decode dispatch worth
# retrying: the XLA runtime's transient statuses, plus the literal
# RESOURCE_EXHAUSTED an HBM blip raises (a fragmented allocator often
# succeeds on the re-dispatch once transient buffers are freed)
_RETRYABLE_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "ABORTED",
                      "DEADLINE_EXCEEDED", "INTERNAL")


def is_retryable_decode_error(exc) -> bool:
    """Whether a decode dispatch failure is worth re-dispatching.

    True for the armed :class:`~apex_tpu.resilience.faults.
    InjectedDecodeFailure` (both transient and permanent flavors — a
    permanent one simply keeps failing until the budget runs out,
    which is exactly the drill), for
    :class:`~apex_tpu.telemetry.memory.HBMExhaustedError` (already
    post-mortemed by ``guarded_call``; the retry is free), and for
    runtime errors carrying a transient XLA status marker. Anything
    else — a shape error, a Python bug — fails fast."""
    from apex_tpu.resilience.faults import InjectedDecodeFailure
    from apex_tpu.telemetry.memory import HBMExhaustedError

    if isinstance(exc, (InjectedDecodeFailure, HBMExhaustedError)):
        return True
    msg = str(exc)
    return any(marker in msg for marker in _RETRYABLE_MARKERS)


@dataclasses.dataclass
class RejectedRequest:
    """A request that never reached a slot: shed at admission, bounced
    for an impossible shape, or refused during drain. Lands in
    ``Scheduler.rejected`` next to a ``serve/rejected`` counter tick
    and a ``serve`` JSONL event naming the reason."""

    rid: int
    reason: str                    # one of REJECT_REASONS
    tick: float
    prompt_len: int = 0
    detail: str = ""


@dataclasses.dataclass
class DrainReport:
    """What a graceful drain accomplished inside its deadline: emitted
    as the ``serve``/``drain_report`` JSONL event and kept on the
    scheduler as ``drain_report`` for the caller (the bench prints
    it; an operator reads it to decide whether the grace window is
    long enough)."""

    reason: str                    # "preempted" | "requested"
    started_tick: float
    drain_s: float
    completed_in_drain: int        # in-flight requests that finished
    cancelled_active: int          # evicted at the deadline, status "drained"
    cancelled_pending: int         # never admitted, status "drained"
    deadline_hit: bool

    def as_event_fields(self):
        return dataclasses.asdict(self)


class ServeHealth:
    """Rolling backpressure / failure accounting for one scheduler.

    One instance per :class:`~apex_tpu.serving.scheduler.Scheduler`;
    the scheduler increments the fields as requests move through
    terminal states and calls :meth:`emit` for the periodic
    health-snapshot event (``serve``/``health``) plus the
    ``serve/pending_depth`` gauge. Counters here are *host truth* —
    they exist even when the telemetry registry is disabled, so
    ``Scheduler.stats()`` can report shed rate and goodput without a
    sink configured."""

    __slots__ = ("submitted", "rejected", "expired", "quarantined",
                 "failed", "drained", "max_steps", "decode_retries",
                 "decode_failures", "all_slots_nonfinite")

    def __init__(self):
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.quarantined = 0
        self.failed = 0
        self.drained = 0
        self.max_steps = 0
        self.decode_retries = 0
        self.decode_failures = 0
        self.all_slots_nonfinite = 0

    def shed_rate(self):
        """Fraction of submitted requests rejected at admission."""
        return (self.rejected / self.submitted) if self.submitted else 0.0

    def snapshot(self):
        return {name: getattr(self, name) for name in self.__slots__}

    def emit(self, registry, *, tick, pending, active, free,
             completed_ok, draining):
        """Land the health snapshot: gauge + one structured event."""
        if not registry.enabled:
            return
        registry.gauge("serve/pending_depth").set(pending)
        registry.event(
            "serve", "health", tick=tick, pending=pending, active=active,
            free=free, completed_ok=completed_ok, draining=draining,
            shed_rate=round(self.shed_rate(), 4), **self.snapshot())
